"""Kernel injection: swap user transformer layers for the fused layer.

Reference: deepspeed/module_inject/replace_module.py:6-193
(replace_transformer_layer, generic replace_module policy walker :161-193)
and inject.py:6-121. The reference mutates a torch module tree, moving each
HF/Megatron layer's weights into a DeepSpeedTransformerLayer and back
(revert). Here models are params PYTREES, so injection is a pure tree
transformation: a policy recognizes a layer's param subtree by shape/keys
and converts it to the fused layer's 12-tensor dict (transformer.py param
names), or back. The model then runs those params through
transformer_layer_forward — same capability (run HF weights on the fused
kernel path), no monkey-patching.

**Coverage contract (loud, never silent).**  One policy family is
implemented: `HFBertLayerPolicy` (HF/flax BERT encoder layers).  A
policy walk that recognizes NOTHING is almost always a caller error —
wrong tree layout, a model family without a policy — and returning the
tree unchanged would let the caller run UNINJECTED weights believing
injection happened (the reference's silent-stub trap).  So
`replace_transformer_layer` raises `NotImplementedError` when zero
layers matched; pass `strict=False` to get the old pass-through with a
logged warning instead (e.g. probing a mixed checkpoint).  For decoder
/ GPT-family models there is no injection policy: convert the weights
with `models/hf.py` (`load_hf_gpt2` — the supported path, after
which every engine feature and `deepspeed_tpu.serving` apply
unchanged).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..ops.transformer.transformer import DeepSpeedTransformerConfig
from ..utils.logging import logger

FUSED_KEYS = ("attn_qkvw", "attn_qkvb", "attn_ow", "attn_ob", "attn_nw",
              "attn_nb", "inter_w", "inter_b", "output_w", "output_b",
              "norm_w", "norm_b")


class InjectionPolicy:
    """Recognize + convert one layer family. Subclasses implement:

    matches(subtree) -> bool             does this dict hold one layer?
    convert(subtree) -> fused dict       -> transformer.py param names
    revert(fused) -> subtree             inverse mapping
    layer_config_overrides() -> dict     e.g. pre_layer_norm for the family
    """

    def matches(self, subtree: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def convert(self, subtree: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def revert(self, fused: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def layer_config_overrides(self) -> Dict[str, Any]:
        return {}


def _dense(kernel, bias, transpose):
    k = jnp.asarray(kernel)
    return (k.T if transpose else k), jnp.asarray(bias)


class HFBertLayerPolicy(InjectionPolicy):
    """HuggingFace BERT encoder layer (reference replace_module.py:12-63
    HFBertLayerPolicy).

    Recognizes the flax layout
      {attention: {self: {query,key,value}, output: {dense, LayerNorm}},
       intermediate: {dense}, output: {dense, LayerNorm}}
    with [in, out] kernels (set torch_layout=True for [out, in] weights
    from a torch state dict). HF BERT is post-LN.
    """

    def __init__(self, torch_layout: bool = False):
        self.torch_layout = torch_layout

    @staticmethod
    def _get(d, *names):
        for n in names:
            if n in d:
                return d[n]
        raise KeyError(names)

    def matches(self, t) -> bool:
        try:
            return ("attention" in t and "intermediate" in t
                    and "output" in t and "self" in t["attention"])
        except TypeError:
            return False

    def _wb(self, d):
        w = self._get(d, "kernel", "weight")
        b = self._get(d, "bias")
        return _dense(w, b, self.torch_layout or "weight" in d)

    def _ln(self, d):
        return (jnp.asarray(self._get(d, "scale", "weight", "gamma")),
                jnp.asarray(self._get(d, "bias", "beta")))

    def convert(self, t):
        sa = t["attention"]["self"]
        qw, qb = self._wb(sa["query"])
        kw, kb = self._wb(sa["key"])
        vw, vb = self._wb(sa["value"])
        ow, ob = self._wb(t["attention"]["output"]["dense"])
        anw, anb = self._ln(t["attention"]["output"]["LayerNorm"])
        iw, ib = self._wb(t["intermediate"]["dense"])
        pw, pb = self._wb(t["output"]["dense"])
        nw, nb = self._ln(t["output"]["LayerNorm"])
        return {
            "attn_qkvw": jnp.concatenate([qw, kw, vw], axis=-1),
            "attn_qkvb": jnp.concatenate([qb, kb, vb], axis=-1),
            "attn_ow": ow, "attn_ob": ob,
            "attn_nw": anw, "attn_nb": anb,
            "inter_w": iw, "inter_b": ib,
            "output_w": pw, "output_b": pb,
            "norm_w": nw, "norm_b": nb,
        }

    def revert(self, fused):
        qw, kw, vw = jnp.split(jnp.asarray(fused["attn_qkvw"]), 3, axis=-1)
        qb, kb, vb = jnp.split(jnp.asarray(fused["attn_qkvb"]), 3, axis=-1)
        mk = (lambda w: w.T) if self.torch_layout else (lambda w: w)
        kkey = "weight" if self.torch_layout else "kernel"
        skey = "weight" if self.torch_layout else "scale"
        dense = lambda w, b: {kkey: mk(w), "bias": b}
        ln = lambda w, b: {skey: w, "bias": b}
        return {
            "attention": {
                "self": {"query": dense(qw, qb), "key": dense(kw, kb),
                         "value": dense(vw, vb)},
                "output": {"dense": dense(fused["attn_ow"], fused["attn_ob"]),
                           "LayerNorm": ln(fused["attn_nw"],
                                           fused["attn_nb"])},
            },
            "intermediate": {"dense": dense(fused["inter_w"],
                                            fused["inter_b"])},
            "output": {"dense": dense(fused["output_w"], fused["output_b"]),
                       "LayerNorm": ln(fused["norm_w"], fused["norm_b"])},
        }

    def layer_config_overrides(self):
        return {"pre_layer_norm": False}  # HF BERT is post-LN


def replace_module(params: Any, policy: InjectionPolicy,
                   _path: Tuple = ()) -> Tuple[Any, List[Tuple]]:
    """Generic walker (reference replace_module.py:161-193): descend the
    params tree; whenever `policy.matches` a subtree, replace it with the
    converted fused dict. Returns (new_tree, list of replaced paths)."""
    replaced = []
    if isinstance(params, dict):
        if policy.matches(params):
            return policy.convert(params), [_path]
        out = {}
        for key, sub in params.items():
            out[key], r = replace_module(sub, policy, _path + (key,))
            replaced.extend(r)
        return out, replaced
    if isinstance(params, (list, tuple)):
        out = []
        for i, sub in enumerate(params):
            new, r = replace_module(sub, policy, _path + (i,))
            out.append(new)
            replaced.extend(r)
        return type(params)(out), replaced
    return params, replaced


def replace_transformer_layer(policy: InjectionPolicy, params: Any,
                              config: Optional[DeepSpeedTransformerConfig]
                              = None, strict: bool = True):
    """reference replace_module.py:66-145. Returns (new_params, layer_config,
    replaced_paths): params with every recognized layer subtree converted to
    fused-layer params, plus the DeepSpeedTransformerConfig to run them with
    (family overrides applied, e.g. post-LN for HF BERT).

    Zero recognized layers is a loud failure (`strict=True`, default):
    running un-injected weights while believing injection happened is
    the silent-stub trap this contract exists to close.  `strict=False`
    downgrades it to a logged pass-through (the tree returns
    unchanged).  See the module docstring: decoder/GPT checkpoints have
    no injection policy — import them via models/hf.py instead."""
    new_params, replaced = replace_module(params, policy)
    if not replaced:
        msg = (f"kernel injection: {type(policy).__name__} recognized NO "
               f"layer subtree in the given params — either the tree "
               f"layout does not match the policy, or this model family "
               f"has no injection policy (only HF BERT encoder layers "
               f"are covered; for GPT-family checkpoints convert the "
               f"weights via deepspeed_tpu.models.hf instead — the "
               f"supported path for the engine and for "
               f"deepspeed_tpu.serving)")
        if strict:
            raise NotImplementedError(msg)
        logger.warning(msg + "; strict=False: returning the params "
                       "UNCHANGED (no layer runs the fused kernel)")
    if config is not None:
        for k, v in policy.layer_config_overrides().items():
            setattr(config, k, v)
    return new_params, config, replaced


def revert_transformer_layer(policy: InjectionPolicy, params: Any):
    """Inverse of replace_transformer_layer (reference
    replace_module.py:148-158): fused dicts -> original family layout."""

    def walk(t):
        if isinstance(t, dict):
            if all(k in t for k in FUSED_KEYS):
                return policy.revert(t), 1
            out, n = {}, 0
            for key, sub in t.items():
                out[key], m = walk(sub)
                n += m
            return out, n
        if isinstance(t, (list, tuple)):
            outs, n = [], 0
            for sub in t:
                new, m = walk(sub)
                outs.append(new)
                n += m
            return type(t)(outs), n
        return t, 0

    reverted, _n = walk(params)
    return reverted
