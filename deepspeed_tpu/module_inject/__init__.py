from .replace_module import (HFBertLayerPolicy, InjectionPolicy,
                             replace_module, replace_transformer_layer,
                             revert_transformer_layer)

__all__ = ["InjectionPolicy", "HFBertLayerPolicy", "replace_module",
           "replace_transformer_layer", "revert_transformer_layer"]
