"""`deepspeed` CLI runner — multi-host TPU job launcher.

Reference: deepspeed/launcher/runner.py:33-378 (hostfile `slots=N` parsing,
--include/--exclude resource filters, base64 world-info, PDSH/MPI multinode
backends). The UX is preserved; the execution model is TPU-native:

* a "slot" is a host-local device (TPU chip); JAX is single-controller
  PER HOST — one Python process per host, not one per device (contrast
  reference launch.py:122-157 spawning one proc per GPU).
* rendezvous is jax.distributed's coordinator (first host:port), exported
  as DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID and
  consumed by comm.dist.init_distributed.
* multinode backends: pdsh (parallel ssh fan-out) or mpirun, selected by
  availability exactly like the reference's PDSH/OpenMPI runners.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shutil
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..constants import TORCH_DISTRIBUTED_DEFAULT_PORT
from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"  # reference runner.py:26
EXPORT_ENVS = ["PYTHON", "PATH", "LD_LIBRARY", "XLA_", "JAX_", "TPU_",
               "DSTPU_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: `hostname slots=N` per line")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='e.g. "worker-0@worker-1:0,2"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help='e.g. "worker-1:0"')
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus")
    parser.add_argument("--master_port", type=int,
                        default=TORCH_DISTRIBUTED_DEFAULT_PORT)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "mvapich", "local"])
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """reference runner.py:84-116: `hostname slots=N` lines -> ordered
    {host: slots}. None when the file doesn't exist (single-node mode)."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"hostfile has bad format: {line!r}")
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} repeated in hostfile")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active: "OrderedDict[str, List[int]]" = OrderedDict()
    for host, slots in resource_pool.items():
        active[host] = list(range(slots))
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """reference runner.py:119-186: `host1@host2:0,2` selection strings.
    Only one of include/exclude may be set."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    filtered: "OrderedDict[str, List[int]]" = OrderedDict()
    if not include_str and not exclude_str:
        return host_info

    spec = include_str or exclude_str
    parsed: Dict[str, Optional[List[int]]] = OrderedDict()
    for term in spec.split("@"):
        term = term.strip()
        if ":" in term:
            host, slots = term.split(":")
            parsed[host] = [int(s) for s in slots.split(",")]
        else:
            parsed[term] = None  # whole host

    for host, slot_filter in parsed.items():
        if host not in host_info:
            raise ValueError(f"host {host!r} not in resource pool")
        if slot_filter is not None:
            for s in slot_filter:
                if s not in host_info[host]:
                    raise ValueError(f"slot {s} not on host {host!r}")

    if include_str:
        for host, slot_filter in parsed.items():
            filtered[host] = (list(slot_filter) if slot_filter is not None
                              else list(host_info[host]))
    else:
        for host, slots in host_info.items():
            if host not in parsed:
                filtered[host] = list(slots)
            else:
                slot_filter = parsed[host]
                if slot_filter is None:
                    continue  # whole host excluded
                keep = [s for s in slots if s not in slot_filter]
                if keep:
                    filtered[host] = keep
    return filtered


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    """reference runner.py:198-203: json -> base64 (shell-safe)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def _export_env_lines() -> List[str]:
    """Env vars to propagate to remote hosts (reference EXPORT_ENVS +
    ~/.deepspeed_env, runner.py:27-29,289-309)."""
    exports = []
    for key, val in os.environ.items():
        if any(key.startswith(p) for p in EXPORT_ENVS):
            exports.append(f"export {key}={val}")
    env_file = os.path.join(os.path.expanduser("~"),
                            DEEPSPEED_ENVIRONMENT_NAME)
    if os.path.isfile(env_file):
        with open(env_file) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    exports.append(f"export {line}")
    return exports


def _probe_local_slots() -> int:
    """Local device count WITHOUT initializing jax in this process (TPU
    runtime allows one owner process; the trainer child must be it)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.local_device_count())"],
            capture_output=True, text=True, timeout=120)
        return max(1, int(out.stdout.strip().splitlines()[-1]))
    except Exception:
        return 1


def _is_local_host(host: str) -> bool:
    import socket

    if host in ("localhost", "127.0.0.1"):
        return True
    try:
        return host in (socket.gethostname(), socket.getfqdn())
    except Exception:
        return False


def build_local_cmd(args, world_info_b64: str,
                    node_rank: int = 0) -> List[str]:
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           f"--world_info={world_info_b64}",
           f"--master_addr={args.master_addr or '127.0.0.1'}",
           f"--master_port={args.master_port}",
           f"--node_rank={node_rank}",
           args.user_script] + args.user_args
    return cmd


def _local_node_rank(active_resources) -> int:
    """This host's position in the active host list (for --launcher local
    run per-host against a multinode hostfile); 0 if not found."""
    for i, host in enumerate(active_resources):
        if _is_local_host(host):
            return i
    return 0


def build_pdsh_cmd(args, active_resources, world_info_b64: str):
    """reference multinode_runner.py:35-77 PDSHRunner."""
    os.environ["PDSH_RCMD_TYPE"] = "ssh"
    hosts = ",".join(active_resources.keys())
    exports = "; ".join(_export_env_lines())
    launch = (f"cd {os.path.abspath('.')}; "
              + (exports + "; " if exports else "")
              + f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
              f"--world_info={world_info_b64} "
              f"--master_addr={args.master_addr} "
              f"--master_port={args.master_port} "
              f"--node_rank=%n "
              + args.user_script + " " + " ".join(args.user_args))
    return ["pdsh", "-S", "-f", "1024", "-w", hosts, launch]


def _write_hostfile(active_resources, line_fmt: str) -> str:
    """Filtered temp hostfile with ONE entry per active host
    (single-controller: one proc per host); the user's hostfile may
    contain excluded hosts and slots=N entries that would let the MPI
    stack ranks on one box.  Removed at interpreter exit (the launcher
    process outlives the mpirun it spawns)."""
    import atexit
    import tempfile

    fh = tempfile.NamedTemporaryFile(
        "w", prefix="dstpu_hostfile_", suffix=".txt", delete=False)
    for host in active_resources:
        fh.write(line_fmt.format(host=host))
    fh.close()
    atexit.register(lambda p=fh.name: os.path.exists(p) and os.remove(p))
    return fh.name


def build_mpi_cmd(args, active_resources, world_info_b64: str):
    """reference multinode_runner.py:80-121 OpenMPIRunner: one proc per
    HOST (TPU single-controller), not per slot."""
    nprocs = len(active_resources)
    hostfile = _write_hostfile(active_resources, "{host} slots=1\n")
    cmd = ["mpirun", "-n", str(nprocs), "-hostfile", hostfile,
           "--mca", "btl", "^openib"]
    for line in _export_env_lines():
        cmd += ["-x", line.split("=", 1)[0].replace("export ", "")]
    cmd += [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={world_info_b64}",
            f"--master_addr={args.master_addr}",
            f"--master_port={args.master_port}",
            "--node_rank=-1",  # from OMPI env
            args.user_script] + args.user_args
    return cmd


def build_mvapich_cmd(args, active_resources, world_info_b64: str):
    """reference multinode_runner.py MVAPICHRunner: mpirun_rsh with
    ENV=VAL forwarding and a bare host-per-line hostfile; one proc per
    HOST (TPU single-controller), rank from MV2_COMM_WORLD_RANK."""
    import shlex

    nprocs = len(active_resources)
    hostfile = _write_hostfile(active_resources, "{host}\n")
    cmd = ["mpirun_rsh", "-np", str(nprocs), "-hostfile", hostfile]
    # mpirun_rsh takes ENV=VAL pairs before the executable.  A bare KEY
    # line (export-by-name, valid for the OpenMPI -x path) would be
    # parsed as the remote executable — skip it.  Values with whitespace
    # (multi-flag XLA_FLAGS) would shatter when mpirun_rsh re-joins the
    # command line — those ride a shell-quoted env(1) prefix instead
    # (remote start goes through ssh, so the remote shell re-parses the
    # joined line and the quoting survives).
    spaced = []
    for ln in _export_env_lines():
        pair = ln.replace("export ", "", 1)
        if "=" not in pair:
            logger.warning(
                f"mvapich launcher: skipping bare env line (no '='): "
                f"{pair!r} — export it as KEY=VALUE in ~/.deepspeed_env")
            continue
        if any(c in pair for c in " \t"):
            spaced.append(pair)
        else:
            cmd.append(pair)
    if spaced:
        cmd += ["/usr/bin/env"] + [shlex.quote(p) for p in spaced]
    cmd += [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={world_info_b64}",
            f"--master_addr={args.master_addr}",
            f"--master_port={args.master_port}",
            "--node_rank=-1",  # from MV2 env
            args.user_script] + args.user_args
    return cmd


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # single node (reference runner.py:312-340). Slot probe runs in a
        # THROWAWAY subprocess: importing jax here would take the
        # per-process TPU lock and starve the spawned trainer.
        slots = args.num_gpus if args.num_gpus > 0 else _probe_local_slots()
        world_info = {"localhost": list(range(slots))}
        cmd = build_local_cmd(args, encode_world_info(world_info))
        logger.info(f"cmd = {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        return result.returncode

    active = _parse_inclusion_exclusion(resource_pool, args.include,
                                        args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active = OrderedDict(
            (h, s[:args.num_gpus]) for h, s in active.items())
    if not args.master_addr:
        args.master_addr = list(active.keys())[0]

    world_info_b64 = encode_world_info(active)
    # hostfile-backed pools are multinode unless the single active host IS
    # this machine (a lone remote host must still be reached via ssh)
    multi = (args.force_multi or len(active) > 1
             or not _is_local_host(next(iter(active))))
    if not multi or args.launcher == "local":
        # --launcher local against a multinode hostfile is run once per
        # host; each host derives its own node rank from its hostfile slot
        cmd = build_local_cmd(args, world_info_b64,
                              node_rank=_local_node_rank(active))
    elif args.launcher == "pdsh" and shutil.which("pdsh"):
        cmd = build_pdsh_cmd(args, active, world_info_b64)
    elif args.launcher == "openmpi" and shutil.which("mpirun"):
        cmd = build_mpi_cmd(args, active, world_info_b64)
    elif args.launcher == "mvapich" and shutil.which("mpirun_rsh"):
        cmd = build_mvapich_cmd(args, active, world_info_b64)
    elif args.launcher == "pdsh" and shutil.which("mpirun"):
        # pdsh requested but absent; mpirun present — usable fallback
        logger.warning("pdsh not found; falling back to mpirun")
        cmd = build_mpi_cmd(args, active, world_info_b64)
    else:
        missing = {"pdsh": "pdsh (or mpirun)", "openmpi": "mpirun",
                   "mvapich": "mpirun_rsh"}.get(args.launcher,
                                                "pdsh/mpirun")
        raise RuntimeError(
            f"launcher {args.launcher!r} unavailable ({missing} not "
            f"found) — install it or use --launcher local on each host")
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=os.environ.copy())
    result.wait()
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
