from . import runner  # noqa: F401
