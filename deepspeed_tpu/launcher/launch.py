"""Per-host launcher.

Reference: deepspeed/launcher/launch.py:69-176 — decode world info, set
rank env vars, spawn one subprocess per local GPU, kill the local group on
any child failure, forward SIGINT/SIGTERM.

TPU difference: JAX is single-controller per host, so ONE user process per
host drives all local chips (the reference's proc-per-device model would
fight the TPU runtime for chip ownership). The spawned process gets:
  DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID  (jax.distributed)
  RANK / LOCAL_RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT  (torch-style parity)
`--procs_per_node N` (testing / CPU meshes) restores proc-per-slot
spawning with per-process DSTPU_PROCESS_ID — the reference behavior.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
from typing import List

from ..constants import TORCH_DISTRIBUTED_DEFAULT_PORT
from ..utils.logging import logger
from .runner import decode_world_info


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int,
                        default=TORCH_DISTRIBUTED_DEFAULT_PORT)
    parser.add_argument("--procs_per_node", type=int, default=1)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    node_rank = args.node_rank
    if node_rank < 0:  # from MPI env (reference launch.py via OMPI/MV2)
        node_rank = int(os.environ.get(
            "OMPI_COMM_WORLD_RANK",
            os.environ.get("MV2_COMM_WORLD_RANK", 0)))
    num_nodes = len(hosts)
    ppn = max(1, args.procs_per_node)
    world_size = num_nodes * ppn

    processes: List[subprocess.Popen] = []
    for local_rank in range(ppn):
        rank = node_rank * ppn + local_rank
        env = os.environ.copy()
        env.update({
            "DSTPU_COORDINATOR": f"{args.master_addr}:{args.master_port}",
            "DSTPU_NUM_PROCESSES": str(world_size),
            "DSTPU_PROCESS_ID": str(rank),
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": args.master_addr,
            "MASTER_PORT": str(args.master_port),
        })
        cmd = [sys.executable, "-u", args.user_script,
               f"--local_rank={local_rank}"] + args.user_args
        logger.info(f"launching process {rank}/{world_size}: {' '.join(cmd)}")
        processes.append(subprocess.Popen(cmd, env=env))

    # signal fan-out + fail-fast group kill (reference launch.py:139-175)
    def sig_handler(signum, frame):
        for p in processes:
            p.terminate()
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    alive = list(processes)
    rc = 0
    while alive:
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                logger.error(f"process {p.pid} exited with code {ret}; "
                             f"terminating local group")
                for q in alive:
                    q.terminate()
                for q in alive:
                    q.wait()
                return ret
        if alive:
            try:
                alive[0].wait(timeout=1)
            except subprocess.TimeoutExpired:
                pass
    return rc


if __name__ == "__main__":
    sys.exit(main())
