"""SPMD pipeline parallelism — GPipe compiled into one XLA program.

The reference's pipeline runtime is an eager instruction interpreter
(deepspeed/runtime/pipe/engine.py:1280-1306) moving activations with NCCL
p2p (pipe/p2p.py:31-75) under the TrainSchedule ISA (pipe/schedule.py). A
TPU-native pipeline instead compiles the whole schedule into a single
jitted program:

* the repeated layer block's params are STACKED on a leading axis and
  sharded over the `pipe` mesh axis (stage s holds slices
  [s*L/P, (s+1)*L/P));
* `shard_map` manual over ONLY the pipe axis (data/model/seq stay auto, so
  in-block tensor-parallel sharding constraints still apply);
* a `lax.scan` over M + P - 1 clock ticks: each tick every stage applies
  its local layer stack to the activation it holds, then `ppermute` hands
  activations to the next stage (ICI neighbor exchange — the p2p
  equivalent);
* reverse-mode autodiff through the scan + ppermute yields the backward
  pipeline automatically (ppermute's transpose is the reverse ppermute),
  i.e. the 1F1B-style backward schedule falls out of XLA instead of being
  hand-interpreted.

The compute cost of the bubble is explicit: every stage computes every
tick, so overhead = (M + P - 1) / M like any GPipe schedule.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from ..comm.mesh import PIPE_AXIS, MeshInfo


def stack_stage_params(per_layer_params):
    """Stack a list of identically-structured per-layer param pytrees along
    a new leading axis (to be sharded over `pipe`)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_layer_params)


def unstack_stage_params(stacked, n):
    return [jax.tree_util.tree_map(lambda l: l[i], stacked)
            for i in range(n)]


def spmd_pipeline(block_fn: Callable, stacked_params, x,
                  mesh_info: MeshInfo, num_micro: int = 0,
                  remat: bool = True):
    """Run `x` through L stacked layers pipelined over the pipe axis.

    block_fn(params_one_layer, x) -> x       (same shape)
    stacked_params: leaves [L, ...] (L divisible by pipe size)
    x: [B, ...] activations (B divisible by num_micro)
    Returns activations [B, ...] after all L layers.
    """
    P = mesh_info.axis_size(PIPE_AXIS)
    if P == 1:
        def body(h, p):
            return (block_fn(p, h), None)
        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    # XLA:CPU workaround: the AllReducePromotion pass aborts ("Invalid
    # binary instruction opcode copy") on a bf16 collective this shard_map
    # pipeline's autodiff produces. On the CPU backend (virtual-mesh tests
    # and the driver dryrun) run the pipeline region in fp32; TPU keeps
    # bf16 end to end.
    orig_dtype = x.dtype
    if jax.default_backend() == "cpu" and orig_dtype == jnp.bfloat16:
        up = lambda t: jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == jnp.bfloat16 else a, t)
        out = spmd_pipeline(block_fn, up(stacked_params),
                            x.astype(jnp.float32), mesh_info,
                            num_micro=num_micro, remat=remat)
        return out.astype(orig_dtype)

    M = num_micro or P
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by micro count {M}"
    mb = B // M
    x_chunks = x.reshape(M, mb, *x.shape[1:])

    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    assert L % P == 0, f"layer count {L} not divisible by pipe size {P}"

    apply_block = block_fn
    if remat:
        apply_block = jax.checkpoint(block_fn)

    def stage_apply(local_params, h):
        def body(h, p):
            return (apply_block(p, h), None)
        out, _ = jax.lax.scan(body, h, local_params)
        return out

    perm = [(i, i + 1) for i in range(P - 1)]

    def per_stage(local_params, chunks):
        stage = jax.lax.axis_index(PIPE_AXIS)

        def tick(carry, t):
            held, out_buf = carry
            recv = jax.lax.ppermute(held, PIPE_AXIS, perm)
            inject = jax.lax.dynamic_index_in_dim(
                chunks, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            h = jnp.where(stage == 0, inject, recv)
            y = stage_apply(local_params, h)
            m = t - (P - 1)
            upd = jax.lax.dynamic_update_slice_in_dim(
                out_buf, y[None].astype(out_buf.dtype),
                jnp.clip(m, 0, M - 1), axis=0)
            valid = jnp.logical_and(stage == P - 1, m >= 0)
            out_buf = jnp.where(valid, upd, out_buf)
            return (y, out_buf), None

        # initial carries derive from the pipe-replicated input: mark them
        # device-varying so the scan carry type is stable across ticks
        held0 = jax.lax.pcast(jnp.zeros_like(chunks[0]), (PIPE_AXIS,), to='varying')
        out0 = jax.lax.pcast(jnp.zeros_like(chunks), (PIPE_AXIS,), to='varying')
        (_, out_buf), _ = jax.lax.scan(
            tick, (held0, out0), jnp.arange(M + P - 1))
        # broadcast last stage's outputs to all stages (sum of one nonzero).
        # fp32 for the wire: XLA:CPU's AllReducePromotion pass crashes
        # ("Invalid binary instruction opcode copy") cloning a bf16
        # all-reduce here; promoting explicitly sidesteps it and costs
        # nothing on TPU (the collective would promote anyway)
        summed = jax.lax.psum(
            jnp.where(stage == P - 1, out_buf,
                      jnp.zeros_like(out_buf)).astype(jnp.float32),
            PIPE_AXIS)
        return summed.astype(out_buf.dtype)

    from jax.sharding import PartitionSpec as PSpec

    shard_spec = jax.tree_util.tree_map(
        lambda _: PSpec(PIPE_AXIS), stacked_params)
    fn = jax.shard_map(
        per_stage,
        mesh=mesh_info.mesh,
        in_specs=(shard_spec, PSpec()),
        out_specs=PSpec(),
        axis_names={PIPE_AXIS},
    )
    out_chunks = fn(stacked_params, x_chunks)
    return out_chunks.reshape(B, *x.shape[1:])
