"""Parallelism runtimes beyond plain sharding annotations: SPMD pipeline
execution over the `pipe` mesh axis and ring attention over the `seq` axis."""

from .pipeline import spmd_pipeline, stack_stage_params, unstack_stage_params
from .ring_attention import ring_attention

__all__ = ["spmd_pipeline", "stack_stage_params", "unstack_stage_params",
           "ring_attention"]
