"""Parallelism runtimes beyond plain sharding annotations: SPMD pipeline
execution over the `pipe` mesh axis and ring attention over the `seq` axis."""

from .pipeline import spmd_pipeline, stack_stage_params

__all__ = ["spmd_pipeline", "stack_stage_params"]
