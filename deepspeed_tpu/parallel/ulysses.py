"""Ulysses-style all-to-all sequence parallelism.

Complement to ring attention (parallel/ring_attention.py) for long
sequences: instead of rotating K/V blocks around the ring, the activation
sharding is MOVED from the sequence dim to the head dim for the attention
op and back afterwards. Under GSPMD this is two sharding constraints —
XLA inserts the all_to_all pair over the `seq` mesh axis (the DeepSpeed-
Ulysses wire pattern, arXiv:2309.14509, built on XLA collectives instead
of explicit NCCL all_to_all).

Within the attention op every device holds the FULL sequence for H/P of
the heads, so the existing dense/flash kernels run unchanged — causal
masking, unlike the ring formulation, needs no cross-block bookkeeping.
Requires num_heads divisible by the seq-axis size; the projections before
and after stay sequence-sharded, so MLP/LayerNorm memory remains O(S/P).

Note on kernels: GSPMD partitions XLA ops across the head dim freely; a
Pallas custom call is partitioned only when its operands' shardings map
whole blocks per device (heads here), which holds for the flash kernel's
[B*H, S, D] layout. If a mesh/layout combination ever fails to
partition, set attn_impl="xla" for the SP blocks — the einsum path
partitions unconditionally.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import DATA_AXIS, SEQ_AXIS


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x  # no mesh in scope (single-device tests)


def ulysses_attention(q, k, v, attention_fn, causal: bool = True,
                      seq_axis: str = SEQ_AXIS, **attn_kwargs):
    """All-to-all sequence-parallel attention over [B, S, H, D] inputs.

    attention_fn(q, k, v, causal=..., **kwargs) -> [B, S, H, D] — any
    dense attention (ops.transformer.attention.multihead_attention).
    Inputs arrive sequence-sharded; outputs return sequence-sharded.

    Dropout note: with in-kernel hash dropout, the mask indexes by the
    kernel-local (batch·head) coordinate; if XLA partitions the kernel
    over the head dim, head-shards on different devices draw the same
    mask pattern for their local head slots. Per-head statistics are
    unaffected (correct rate and scaling per head) — only cross-device
    mask IDENTITY correlates, which dense-path training never observes.
    Manual-partition callers (shard_map over batch or heads) decorrelate
    shards by passing `bh_offset=jax.lax.axis_index(axis) * local_BH`
    through to flash_attention — the hash then uses the GLOBAL
    batch·head coordinate and matches the unsharded run bit-for-bit
    (tests/test_flash_attention.py pins it); this SPMD-constraint path
    has no manual axis in scope, so the note above stands here.
    """
    head_spec = P(DATA_AXIS, None, seq_axis, None)
    seq_spec = P(DATA_AXIS, seq_axis, None, None)
    # seq-shard -> head-shard: XLA lowers the resharding to an all_to_all
    q = _constrain(q, head_spec)
    k = _constrain(k, head_spec)
    v = _constrain(v, head_spec)
    out = attention_fn(q, k, v, causal=causal, **attn_kwargs)
    # head-shard -> seq-shard for the rest of the block
    return _constrain(out, seq_spec)
