"""Ring attention — sequence-parallel exact attention over the `seq` axis.

The reference has NO sequence parallelism (SURVEY.md §2.2: absent in
v0.3.15; its long-sequence story is block-sparse attention + activation
partitioning). This is the TPU-native long-context path: the sequence
dimension is sharded over the `seq` mesh axis; each device holds local
Q/K/V chunks and K/V blocks rotate around the ring via `ppermute` (ICI
neighbor traffic), combined with an online-softmax accumulator — flash
attention at the inter-chip level. Compute and memory per chip are
O(S/n · S) and O(S/n), enabling sequences n× longer than one chip's HBM
would allow.

Backward is reverse-mode autodiff through the scan+ppermute program (the
ppermute transpose reverses the ring), so no hand-written backward pass.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import SEQ_AXIS, MeshInfo

NEG_INF = -1e30


def _softmax_block(qf, kc, vc, acc, m, l, mask=None):
    """One online-softmax accumulator update against a K/V block.
    qf: [B, Sq, H, D] fp32 pre-scaled; kc/vc: [B, Sk, H, D];
    acc/m/l: [B, H, Sq, D] / [B, H, Sq] / [B, H, Sq].
    Shared by the contiguous and zigzag ring bodies — ONE copy of the
    numerically delicate masking + rescaling logic."""
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)  # fully-masked chunks contribute zero
    alpha = jnp.exp(m - m_new)
    l = alpha * l + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return acc, m_new, l


def _softmax_block_tiled(qf, kc, vc, acc, m, l, mask=None, block_q=0):
    """_softmax_block with optional sequential Q-tiling: peak score
    memory drops from [B, H, Sq, Sk] to [B, H, block_q, Sk] — the knob
    that keeps VERY long local chunks (ring attention's whole point)
    from materializing a quadratic block. block_q=0 or non-divisible
    sizes fall back to one tile."""
    Sq = qf.shape[1]
    if not block_q or Sq <= block_q or Sq % block_q:
        return _softmax_block(qf, kc, vc, acc, m, l, mask)
    nq = Sq // block_q
    B, _, H, D = qf.shape
    qt = qf.reshape(B, nq, block_q, H, D).transpose(1, 0, 2, 3, 4)
    at = acc.reshape(B, H, nq, block_q, D).transpose(2, 0, 1, 3, 4)
    mt = m.reshape(B, H, nq, block_q).transpose(2, 0, 1, 3)
    lt = l.reshape(B, H, nq, block_q).transpose(2, 0, 1, 3)

    if mask is not None:
        Sk = kc.shape[1]
        mk = jnp.broadcast_to(mask, (1, 1, Sq, Sk)).reshape(
            1, 1, nq, block_q, Sk).transpose(2, 0, 1, 3, 4)

        def body(_, xs):
            q_, a_, m_, l_, k_ = xs
            return _, _softmax_block(q_, kc, vc, a_, m_, l_, k_)

        _, (a2, m2, l2) = jax.lax.scan(body, None, (qt, at, mt, lt, mk))
    else:
        def body(_, xs):
            q_, a_, m_, l_ = xs
            return _, _softmax_block(q_, kc, vc, a_, m_, l_, None)

        _, (a2, m2, l2) = jax.lax.scan(body, None, (qt, at, mt, lt))
    acc = a2.transpose(1, 2, 0, 3, 4).reshape(B, H, Sq, D)
    m = m2.transpose(1, 2, 0, 3).reshape(B, H, Sq)
    l = l2.transpose(1, 2, 0, 3).reshape(B, H, Sq)
    return acc, m, l


def _ring_body(q, k, v, n, causal, scale, block_q=0):
    """Per-device ring loop. q/k/v: local [B, Sc, H, D] chunks."""
    idx = jax.lax.axis_index(SEQ_AXIS)
    B, Sc, H, D = q.shape
    qf = q.astype(jnp.float32) * scale
    perm = [(i, (i + 1) % n) for i in range(n)]

    iota_q = jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 0)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 1)

    def step(carry, t):
        acc, m, l, kc, vc = carry
        src = (idx - t) % n  # global chunk id currently held in kc/vc
        if causal:
            qpos = idx * Sc + iota_q
            kpos = src * Sc + iota_k
            mask = (qpos >= kpos)[None, None]
        else:
            mask = None
        acc, m, l = _softmax_block_tiled(qf, kc, vc, acc, m, l,
                                         mask=mask, block_q=block_q)
        kc = jax.lax.ppermute(kc, SEQ_AXIS, perm)
        vc = jax.lax.ppermute(vc, SEQ_AXIS, perm)
        return (acc, m, l, kc, vc), None

    # mark fresh accumulators device-varying so the scan carry type is
    # stable (they become varying after the first masked update)
    vary = lambda x: jax.lax.pcast(x, (SEQ_AXIS,), to="varying")
    acc0 = vary(jnp.zeros((B, H, Sc, D), jnp.float32))
    m0 = vary(jnp.full((B, H, Sc), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, H, Sc), jnp.float32))
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # -> [B, Sc, H, D]


def zigzag_order(S: int, n: int):
    """Token permutation for the load-balanced causal layout: the
    sequence splits into 2n chunks and device i holds chunks
    (i, 2n-1-i). Returns (perm, inv): x_zigzag = x[:, perm] lays tokens
    out so that `seq`-sharding assigns each device its chunk pair;
    x = x_zigzag[:, inv] undoes it."""
    import numpy as np

    if S % (2 * n):
        raise ValueError(f"zigzag needs seq len divisible by 2n={2 * n}")
    c = S // (2 * n)
    chunks = np.arange(S).reshape(2 * n, c)
    perm = np.concatenate([np.concatenate([chunks[i], chunks[2 * n - 1 - i]])
                           for i in range(n)])
    inv = np.argsort(perm)
    return perm, inv


def _zigzag_body(q, k, v, n, scale, block_q=0):
    """Load-balanced CAUSAL ring: local chunks are the zigzag pair
    (lo = chunk idx, hi = chunk 2n-1-idx), each [B, c, H, D]. After the
    self-pair step, every ring step is exactly TWO dense unmasked
    [c, c] blocks on every device — the causal triangle's work spread
    evenly, ~2x fewer FLOPs than masking dense blocks (the public
    zigzag/striped context-parallel formulation; beyond the reference,
    which has no SP at all)."""
    idx = jax.lax.axis_index(SEQ_AXIS)
    B, S2, H, D = q.shape
    c = S2 // 2
    qf = q.astype(jnp.float32) * scale
    qlo, qhi = qf[:, :c], qf[:, c:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    from functools import partial

    block = partial(_softmax_block_tiled, block_q=block_q)
    vary = lambda x: jax.lax.pcast(x, (SEQ_AXIS,), to="varying")
    zero = lambda: (vary(jnp.zeros((B, H, c, D), jnp.float32)),
                    vary(jnp.full((B, H, c), NEG_INF, jnp.float32)),
                    vary(jnp.zeros((B, H, c), jnp.float32)))
    acc_lo = zero()
    acc_hi = zero()

    # step 0 — the self pair: both diagonals (triangular) + hi->lo (full)
    tri = (jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
           >= jax.lax.broadcasted_iota(jnp.int32, (c, c), 1))[None, None]
    klo0, khi0 = k[:, :c], k[:, c:]
    vlo0, vhi0 = v[:, :c], v[:, c:]
    acc_lo = block(qlo, klo0, vlo0, *acc_lo, mask=tri)
    acc_hi = block(qhi, khi0, vhi0, *acc_hi, mask=tri)
    acc_hi = block(qhi, klo0, vlo0, *acc_hi)

    def step(carry, _t):
        acc_lo, acc_hi, kc, vc = carry
        kc = jax.lax.ppermute(kc, SEQ_AXIS, perm)
        vc = jax.lax.ppermute(vc, SEQ_AXIS, perm)
        t = _t  # ring distance of the received pair
        src = (idx - t) % n
        klo, khi = kc[:, :c], kc[:, c:]
        vlo, vhi = vc[:, :c], vc[:, c:]
        # my hi chunk (global id 2n-1-idx) is causally after every lo
        # chunk: always one dense block
        acc_hi = block(qhi, klo, vlo, *acc_hi)
        # the second dense block: lo->lo when idx > src (my lo is later),
        # else hi->hi (src's hi is earlier than mine)
        pred = idx > src
        qsel = jnp.where(pred, qlo, qhi)
        ksel = jnp.where(pred, klo, khi)
        vsel = jnp.where(pred, vlo, vhi)
        a, m_, l_ = block(qsel, ksel, vsel,
                          jnp.where(pred, acc_lo[0], acc_hi[0]),
                          jnp.where(pred, acc_lo[1], acc_hi[1]),
                          jnp.where(pred, acc_lo[2], acc_hi[2]))
        new_lo = tuple(jnp.where(pred, x, y)
                       for x, y in zip((a, m_, l_), acc_lo))
        new_hi = tuple(jnp.where(pred, y, x)
                       for x, y in zip((a, m_, l_), acc_hi))
        return (new_lo, new_hi, kc, vc), None

    (acc_lo, acc_hi, _, _), _ = jax.lax.scan(
        step, (acc_lo, acc_hi, k, v), jnp.arange(1, n))

    def finish(accml):
        acc, m, l = accml
        return acc / jnp.maximum(l, 1e-30)[..., None]

    out = jnp.concatenate([finish(acc_lo), finish(acc_hi)], axis=2)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, 2c, H, D]


def ring_attention(q, k, v, mesh_info: Optional[MeshInfo] = None,
                   causal: bool = True, scale: Optional[float] = None,
                   layout: str = "contiguous", block_q: int = 0):
    """Sequence-parallel attention. [B, S, H, D] with S sharded over `seq`.

    layout="zigzag" (causal only): tokens are pre-permuted by
    zigzag_order() so each device owns chunks (i, 2n-1-i); the causal
    triangle's work is then uniform across devices and all post-diagonal
    blocks are dense and unmasked (~2x fewer attention FLOPs than
    masking). Falls back to a single-device flash/XLA path when the seq
    axis is 1.
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag" and not causal:
        # validated BEFORE the n==1 fallback so the invalid combination
        # fails identically on single-device debug configs and real meshes
        raise ValueError("zigzag layout only makes sense for causal "
                         "attention (it balances the causal triangle)")
    if mesh_info is None:
        from ..comm.mesh import get_current_mesh

        mesh_info = get_current_mesh()
    n = mesh_info.axis_size(SEQ_AXIS)
    scale = (q.shape[-1] ** -0.5) if scale is None else scale
    if n == 1:
        from ..ops.transformer.attention import multihead_attention

        return multihead_attention(q, k, v, causal=causal, scale=scale)
    if block_q < 0:
        raise ValueError(f"block_q must be >= 0, got {block_q}")
    if layout == "zigzag":
        if q.shape[1] % (2 * n):
            # an odd per-device shard would silently broadcast mismatched
            # accumulators into garbage — refuse loudly instead
            raise ValueError(
                f"zigzag needs seq len divisible by 2n={2 * n}, got "
                f"{q.shape[1]} (use zigzag_order to lay out tokens)")
        chunk = q.shape[1] // (2 * n)
    else:
        chunk = q.shape[1] // n
    if block_q and chunk > block_q and chunk % block_q:
        # silently falling back would materialize the full quadratic
        # block — the OOM this knob exists to prevent (flash_attention
        # raises for the same reason)
        raise ValueError(
            f"block_q={block_q} must divide the per-device chunk "
            f"({chunk} for layout={layout!r} on a {n}-way seq axis)")
    if layout == "zigzag":
        body = lambda q, k, v: _zigzag_body(q, k, v, n, scale,
                                            block_q=block_q)
    else:
        body = lambda q, k, v: _ring_body(q, k, v, n, causal, scale,
                                          block_q=block_q)

    spec = P(None, SEQ_AXIS, None, None)
    fn = jax.shard_map(
        body,
        mesh=mesh_info.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={SEQ_AXIS},
    )
    return fn(q, k, v)
