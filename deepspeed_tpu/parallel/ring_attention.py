"""Ring attention — sequence-parallel exact attention over the `seq` axis.

The reference has NO sequence parallelism (SURVEY.md §2.2: absent in
v0.3.15; its long-sequence story is block-sparse attention + activation
partitioning). This is the TPU-native long-context path: the sequence
dimension is sharded over the `seq` mesh axis; each device holds local
Q/K/V chunks and K/V blocks rotate around the ring via `ppermute` (ICI
neighbor traffic), combined with an online-softmax accumulator — flash
attention at the inter-chip level. Compute and memory per chip are
O(S/n · S) and O(S/n), enabling sequences n× longer than one chip's HBM
would allow.

Backward is reverse-mode autodiff through the scan+ppermute program (the
ppermute transpose reverses the ring), so no hand-written backward pass.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import SEQ_AXIS, MeshInfo

NEG_INF = -1e30


def _ring_body(q, k, v, n, causal, scale):
    """Per-device ring loop. q/k/v: local [B, Sc, H, D] chunks."""
    idx = jax.lax.axis_index(SEQ_AXIS)
    B, Sc, H, D = q.shape
    qf = q.astype(jnp.float32) * scale
    perm = [(i, (i + 1) % n) for i in range(n)]

    iota_q = jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 0)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 1)

    def step(carry, t):
        acc, m, l, kc, vc = carry
        src = (idx - t) % n  # global chunk id currently held in kc/vc
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            qpos = idx * Sc + iota_q
            kpos = src * Sc + iota_k
            mask = (qpos >= kpos)[None, None]
            s = jnp.where(mask, s, NEG_INF)
        else:
            mask = jnp.ones((1, 1, Sc, Sc), bool)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(mask, p, 0.0)  # fully-masked chunks contribute zero
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        kc = jax.lax.ppermute(kc, SEQ_AXIS, perm)
        vc = jax.lax.ppermute(vc, SEQ_AXIS, perm)
        return (acc, m_new, l, kc, vc), None

    # mark fresh accumulators device-varying so the scan carry type is
    # stable (they become varying after the first masked update)
    vary = lambda x: jax.lax.pcast(x, (SEQ_AXIS,), to="varying")
    acc0 = vary(jnp.zeros((B, H, Sc, D), jnp.float32))
    m0 = vary(jnp.full((B, H, Sc), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, H, Sc), jnp.float32))
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # -> [B, Sc, H, D]


def ring_attention(q, k, v, mesh_info: Optional[MeshInfo] = None,
                   causal: bool = True, scale: Optional[float] = None):
    """Sequence-parallel attention. [B, S, H, D] with S sharded over `seq`.

    Falls back to a single-device flash/XLA path when the seq axis is 1.
    """
    if mesh_info is None:
        from ..comm.mesh import get_current_mesh

        mesh_info = get_current_mesh()
    n = mesh_info.axis_size(SEQ_AXIS)
    scale = (q.shape[-1] ** -0.5) if scale is None else scale
    if n == 1:
        from ..ops.transformer.attention import multihead_attention

        return multihead_attention(q, k, v, causal=causal, scale=scale)

    spec = P(None, SEQ_AXIS, None, None)
    fn = jax.shard_map(
        lambda q, k, v: _ring_body(q, k, v, n, causal, scale),
        mesh=mesh_info.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={SEQ_AXIS},
    )
    return fn(q, k, v)
