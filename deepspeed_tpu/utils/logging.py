"""Rank-aware logging.

TPU-native re-design of the reference logger
(/root/reference/deepspeed/utils/logging.py): same `logger` +
`log_dist(message, ranks=...)` surface, but rank comes from
`jax.process_index()` instead of torch.distributed.
"""

import logging
import os
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [deepspeed_tpu] %(message)s"


def _create_logger(name="deepspeed_tpu", level=logging.INFO):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT))
        lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO)
)


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log `message` only on the listed process ranks (None/[-1] => all).

    Reference parity: deepspeed/utils/logging.py `log_dist`.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
