"""TensorBoard scalar monitor.

Reference: the engine writes Train/Samples/* scalars from rank 0 when
tensorboard is configured (runtime/engine.py:1058-1068,1223-1237). Same
here; the writer is torch.utils.tensorboard (cpu torch is a baked-in dep),
gracefully disabled if unavailable.
"""

from __future__ import annotations

import os
from typing import Optional

from .logging import logger


class TensorBoardMonitor:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName"):
        self.enabled = False
        self.summary_writer = None
        base = output_path or os.path.join(os.path.expanduser("~"),
                                           "tensorboard")
        log_dir = os.path.join(base, job_name)
        try:
            from torch.utils.tensorboard import SummaryWriter

            os.makedirs(log_dir, exist_ok=True)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
            self.enabled = True
        except Exception as e:  # pragma: no cover - no tensorboard install
            logger.warning(f"tensorboard disabled: {e}")

    def add_scalar(self, tag: str, value, step: int):
        if self.enabled:
            self.summary_writer.add_scalar(tag, float(value), step)

    def flush(self):
        if self.enabled:
            self.summary_writer.flush()
