"""TensorBoard scalar monitor.

Reference: the engine writes Train/Samples/* scalars from rank 0 when
tensorboard is configured (runtime/engine.py:1058-1068,1223-1237). Same
here; the writer is torch.utils.tensorboard (cpu torch is a baked-in dep),
gracefully disabled if unavailable.  In the telemetry pipeline this is
one SINK beside the JSONL event stream (monitor/monitor.py), not the
primary record.
"""

from __future__ import annotations

import math
import os
from typing import Optional

from .logging import logger


class TensorBoardMonitor:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName",
                 flush_interval: int = 20, writer=None):
        """flush_interval: flush the event file every N distinct steps
        (the writer's own flush only runs at close/large buffers, so a
        killed run used to lose everything since the last explicit
        flush).  writer: injectable SummaryWriter-shaped object (tests,
        alternative sinks)."""
        self.enabled = False
        self.summary_writer = None
        self.flush_interval = max(1, int(flush_interval))
        self._last_flush_step = {}
        self._warned_nonfinite = set()
        if writer is not None:
            self.summary_writer = writer
            self.enabled = True
            return
        base = output_path or os.path.join(os.path.expanduser("~"),
                                           "tensorboard")
        log_dir = os.path.join(base, job_name)
        try:
            from torch.utils.tensorboard import SummaryWriter

            os.makedirs(log_dir, exist_ok=True)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
            self.enabled = True
        except Exception as e:  # pragma: no cover - no tensorboard install
            logger.warning(f"tensorboard disabled: {e}")

    def add_scalar(self, tag: str, value, step: int):
        if not self.enabled:
            return
        value = float(value)
        if not math.isfinite(value):
            # a NaN loss used to poison the event file silently; drop the
            # point and say so once per tag
            if tag not in self._warned_nonfinite:
                self._warned_nonfinite.add(tag)
                logger.warning(
                    f"tensorboard: dropping non-finite value for {tag!r} "
                    f"at step {step} (further drops for this tag are "
                    f"silent)")
            return
        self.summary_writer.add_scalar(tag, value, step)
        # per-tag step tracking: different writers use different x-scales
        # (engine: global_samples; run monitor: step) — a single shared
        # last-flush mark would thrash or never fire across them
        prev = self._last_flush_step.setdefault(tag, step)
        if step - prev >= self.flush_interval:
            self.flush()
            self._last_flush_step[tag] = step

    def flush(self):
        if self.enabled:
            self.summary_writer.flush()
