from .logging import log_dist, logger  # noqa: F401
