"""Wall-clock timers (reference: deepspeed/utils/timer.py:19-103
SynchronizedWallClockTimer).

The reference cuda-synchronizes before reading the clock; the JAX analog is
blocking on a marker value (jax.block_until_ready) or, with no marker,
jax.effects_barrier-less wall time — dispatch is async, so timing a region
that ends in device work REQUIRES passing that work's output to stop().
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax

from .logging import log_dist


class _Timer:
    """reference timer.py:25-69."""

    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"timer {self.name_} already started"
        self.start_time = time.time()
        self.started_ = True

    def stop(self, sync=None):
        assert self.started_, f"timer {self.name_} not started"
        if sync is not None:
            jax.block_until_ready(sync)
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True) -> float:
        started = self.started_
        if started:
            self.stop()
        out = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return out

    def mean(self, count: int) -> float:
        return self.elapsed(reset=False) / max(count, 1)


class SynchronizedWallClockTimer:
    """Named-timer registry (reference timer.py:19-103)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        """Device-memory line (reference reports cuda alloc/cache peaks),
        aggregated over ALL local devices: total (sum) and the hottest
        single device (max) — one device's stats alone under-reports
        every multi-chip host."""
        try:
            from ..monitor.monitor import device_memory_stats

            stats = device_memory_stats()
            if not stats:
                return "mem: unavailable"
            gb = 2 ** 30
            return (f"mem: in_use {stats['bytes_in_use_sum'] / gb:.2f} GB "
                    f"(max/dev {stats['bytes_in_use_max'] / gb:.2f}) | "
                    f"peak {stats['peak_bytes_in_use_sum'] / gb:.2f} GB "
                    f"(max/dev {stats['peak_bytes_in_use_max'] / gb:.2f})")
        except Exception:
            return "mem: unavailable"

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True, ranks: Optional[List[int]] = None,
            memory_breakdown: bool = False):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / \
                    normalizer
                parts.append(f"{name}: {ms:.2f}")
        if not parts and not memory_breakdown:
            return  # nothing matched: no bare "time (ms) |" line
        line = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            line += " | " + self.memory_usage()
        log_dist(line, ranks=ranks or [0])


# reference utils/timer.py:105 defines ThroughputTimer here; ours lives
# with the runtime helpers — re-exported for import-path parity
from ..runtime.utils import ThroughputTimer  # noqa: E402,F401
