"""zero_to_fp32 — offline checkpoint -> single fp32 state dict.

Reference: deepspeed/utils/zero_to_fp32.py:21-151 merges per-rank ZeRO
shard files into one fp32 state_dict; the engine drops a copy of the
script next to every checkpoint (reference engine.py:1800-1808).

This framework's checkpoints already store the consolidated fp32 master
pytree (runtime/checkpointing.py), so the job here is: load the tagged
checkpoint, strip training state (optimizer/scaler/scheduler), upcast to
fp32, and write one portable msgpack (or .npz) file.

Usage:
    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file>
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: str = None):
    """reference zero_to_fp32.py:70-121 (same name/signature)."""
    from ..runtime import checkpointing as ckpt_io

    _dir, model_state, _optim = ckpt_io.load_checkpoint_state(
        checkpoint_dir, tag)
    module = model_state["module"]

    def to_fp32(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return arr.astype(np.float32)
        return arr

    import jax

    return jax.tree_util.tree_map(to_fp32, module)


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: str = None):
    """reference zero_to_fp32.py:124-141."""
    state_dict = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    from flax import serialization

    with open(output_file, "wb") as fh:
        fh.write(serialization.msgpack_serialize(state_dict))
    print(f"saved fp32 state dict to {output_file}")
    return state_dict


def load_state_dict_from_zero_checkpoint(checkpoint_dir: str, tag: str = None):
    """Parity helper: returns the fp32 pytree ready for jnp.asarray."""
    return get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("checkpoint_dir",
                        help="checkpoint dir (holds 'latest' + tag dirs)")
    parser.add_argument("output_file",
                        help="output msgpack path for the fp32 state dict")
    parser.add_argument("-t", "--tag", default=None,
                        help="checkpoint tag (default: read 'latest')")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.checkpoint_dir):
        print(f"no such checkpoint dir: {args.checkpoint_dir}")
        return 1
    convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, tag=args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
