"""`deepspeed_tpu.pipe` — import-path parity with the reference's
top-level `deepspeed/pipe/__init__.py` (re-exports the pipeline module
surface so `from deepspeed_tpu.pipe import PipelineModule` works)."""

from ..runtime.pipe.module import (LayerSpec, PipelineModule,  # noqa: F401
                                   TiedLayerSpec)
from ..runtime.pipe.engine import PipelineEngine  # noqa: F401
from ..runtime.pipe.schedule import (DataParallelSchedule,  # noqa: F401
                                     InferenceSchedule,
                                     InterleavedTrainSchedule, TrainSchedule)
