"""Loss scaling — static + dynamic.

Reference: deepspeed/runtime/fp16/loss_scaler.py (Megatron lineage). The
semantics (scale_factor backoff, scale_window growth, hysteresis delayed
shift) are kept; the mechanism is redesigned for XLA: scaler state is a
pytree of scalars and `update_scale_jit` is a branchless pure function so
the whole skip-step decision lives inside the jitted train step
(`jnp.where` instead of Python control flow).
"""

from __future__ import annotations

import jax.numpy as jnp

# config keys (reference loss_scaler.py:19-22)
INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


def make_scaler_state(init_scale: float) -> dict:
    """Pytree state carried through the jitted step."""
    return {
        "cur_scale": jnp.asarray(init_scale, dtype=jnp.float32),
        "cur_iter": jnp.asarray(0, dtype=jnp.int32),
        "last_overflow_iter": jnp.asarray(-1, dtype=jnp.int32),
        "cur_hysteresis": jnp.asarray(1, dtype=jnp.int32),
    }


def update_scale_jit(state: dict, overflow, *, scale_factor: float = 2.0,
                     scale_window: int = 1000, min_scale: float = 1.0,
                     delayed_shift: int = 1,
                     consecutive_hysteresis: bool = False) -> dict:
    """Branchless DynamicLossScaler.update_scale (reference :150-170).

    overflow: bool scalar (traced). Static knobs are Python values baked at
    trace time.
    """
    cur_scale = state["cur_scale"]
    cur_iter = state["cur_iter"] + 1
    cur_hyst = state["cur_hysteresis"]

    shift_now = jnp.logical_or(delayed_shift == 1, cur_hyst <= 1)
    dec_scale = jnp.maximum(cur_scale / scale_factor, min_scale)

    window_hit = ((cur_iter - state["last_overflow_iter"]) % scale_window) == 0
    inc_scale = jnp.where(window_hit, cur_scale * scale_factor, cur_scale)

    new_scale = jnp.where(overflow,
                          jnp.where(shift_now, dec_scale, cur_scale),
                          inc_scale)
    new_hyst = jnp.where(
        overflow,
        jnp.where(shift_now, cur_hyst, cur_hyst - 1),
        jnp.where(jnp.logical_and(window_hit, not consecutive_hysteresis),
                  jnp.asarray(delayed_shift, jnp.int32),
                  (jnp.asarray(delayed_shift, jnp.int32)
                   if consecutive_hysteresis else cur_hyst)),
    )
    new_last_overflow = jnp.where(overflow, cur_iter,
                                  state["last_overflow_iter"])
    return {
        "cur_scale": new_scale,
        "cur_iter": cur_iter,
        "last_overflow_iter": new_last_overflow,
        "cur_hysteresis": new_hyst,
    }


class LossScalerBase:
    """Host-side API parity (reference LossScalerBase)."""

    def __init__(self, cur_scale):
        self.cur_scale = float(cur_scale)

    @property
    def loss_scale(self):
        return self.cur_scale

    def update_scale(self, overflow):
        pass

    def state_dict(self):
        return {"cur_scale": self.cur_scale}

    def load_state_dict(self, sd):
        self.cur_scale = sd["cur_scale"]


class LossScaler(LossScalerBase):
    """Static loss scale (reference LossScaler)."""

    def __init__(self, scale=1.0):
        super().__init__(scale)
        self.dynamic = False

    def jit_state(self):
        return make_scaler_state(self.cur_scale)

    def jit_update(self, state, overflow):
        state = dict(state)
        state["cur_iter"] = state["cur_iter"] + 1
        return state


class DynamicLossScaler(LossScalerBase):
    """Dynamic loss scale (reference DynamicLossScaler)."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False,
                 raise_error_at_min_scale=True):
        super().__init__(init_scale)
        self.dynamic = True
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.raise_error_at_min_scale = raise_error_at_min_scale
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.cur_hysteresis = delayed_shift

    def jit_state(self):
        st = make_scaler_state(self.cur_scale)
        st["cur_iter"] = jnp.asarray(self.cur_iter, jnp.int32)
        st["last_overflow_iter"] = jnp.asarray(self.last_overflow_iter, jnp.int32)
        st["cur_hysteresis"] = jnp.asarray(self.cur_hysteresis, jnp.int32)
        return st

    def jit_update(self, state, overflow):
        return update_scale_jit(state, overflow,
                                scale_factor=self.scale_factor,
                                scale_window=self.scale_window,
                                min_scale=self.min_scale,
                                delayed_shift=self.delayed_shift,
                                consecutive_hysteresis=self.consecutive_hysteresis)

    # host-side mirror (used outside jit, e.g. tests / eager mode)
    def update_scale(self, overflow):
        self.cur_iter += 1
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                if self.cur_scale == self.min_scale and self.raise_error_at_min_scale:
                    raise RuntimeError(
                        "Current loss scale already at minimum - cannot "
                        "decrease scale anymore. Exiting run.")
                self.cur_scale = max(self.cur_scale / self.scale_factor,
                                     self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor

    def state_dict(self):
        return {"cur_scale": self.cur_scale, "cur_iter": self.cur_iter,
                "last_overflow_iter": self.last_overflow_iter,
                "cur_hysteresis": self.cur_hysteresis}

    def load_state_dict(self, sd):
        self.cur_scale = sd["cur_scale"]
        self.cur_iter = sd.get("cur_iter", 0)
        self.last_overflow_iter = sd.get("last_overflow_iter", -1)
        self.cur_hysteresis = sd.get("cur_hysteresis", self.delayed_shift)


def create_loss_scaler(ds_config) -> LossScalerBase:
    """Build from DeepSpeedConfig (reference fp16 optimizer ctors)."""
    if ds_config.precision == "float16":
        if ds_config.loss_scale == 0:
            return DynamicLossScaler(
                init_scale=2 ** ds_config.initial_scale_power,
                scale_window=ds_config.loss_scale_window,
                min_scale=ds_config.min_loss_scale,
                delayed_shift=ds_config.hysteresis)
        return LossScaler(scale=ds_config.loss_scale)
    # bf16/fp32 need no loss scaling
    return LossScaler(scale=1.0)
