"""1-bit LAMB — error-compensated sign-compressed LAMB.

Reference: deepspeed/runtime/fp16/onebit/lamb.py (paper arXiv:2104.06069).
Semantics kept:

* warmup (`step <= freeze_step`): regular LAMB; per-tensor lamb
  coefficients (trust ratios) are EMA-tracked with `coeff_beta`.
* compression stage: the second moment and the lamb coefficient are
  FROZEN; only momentum is communicated (1-bit signs + error feedback,
  same pipeline as 1-bit Adam); the frozen coefficient is modulated by a
  scaling factor derived from the ratio of a "fresh" second-moment
  estimate (rebuilt from the decompressed momentum deltas — reference
  lamb.py's exp_avg_sq_fresh) to the frozen one, clamped to
  [factor_min, factor_max] and rate-limited by factor_threshold between
  steps.

TPU design matches OnebitAdam: the whole pipeline is a pure function in
the jitted step; signs ride pmean over the `data` axis inside shard_map
(`handles_dp_reduction`), errors/coefficients live in optimizer state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...comm.compressed import (compressed_allreduce,
                                int8_compressed_allreduce)


class OnebitLamb:
    name = "OnebitLamb"
    handles_dp_reduction = True

    def __init__(self, params=None, deepspeed=None, lr=1e-3,
                 freeze_step=100000, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, max_coeff=10.0,
                 min_coeff=0.01, amsgrad=False, cuda_aware=False,
                 comm_backend_name="xla", coeff_beta=0.9, factor_max=4.0,
                 factor_min=0.5, factor_threshold=0.1, wire="sign"):
        if amsgrad:
            raise RuntimeError("1-bit Lamb does not support AMSGrad")
        if wire not in ("sign", "int8"):
            raise ValueError(f"wire must be 'sign' or 'int8', got {wire!r}")
        # wire="int8": quantized all_to_all/allgather — the format whose
        # wire bytes XLA actually shrinks (see onebit/adam.py). Lamb's
        # reduction stays per-leaf (trust ratios are per-leaf anyway).
        self.wire = wire
        self.defaults = dict(lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay,
                             bias_correction=bias_correction,
                             max_coeff=max_coeff, min_coeff=min_coeff)
        self.param_groups = [dict(self.defaults)]
        self.freeze_step = int(freeze_step)
        self.eps_inside_sqrt = eps_inside_sqrt
        self.coeff_beta = coeff_beta
        self.factor_max = factor_max
        self.factor_min = factor_min
        self.factor_threshold = factor_threshold

    @property
    def lr(self):
        return self.param_groups[0]["lr"]

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        zt = lambda: jax.tree_util.tree_map(zeros, params)
        scal = lambda v: jax.tree_util.tree_map(
            lambda p: jnp.asarray(v, jnp.float32), params)
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": zt(),
            "exp_avg_sq": zt(),
            "exp_avg_sq_fresh": zt(),
            "worker_error": zt(),
            "server_error": zt(),
            "lamb_coeff_freeze": scal(0.0),
            "last_factor": scal(1.0),
        }

    def update(self, grads, state, params, lr=None, comm_axis=None):
        g = self.param_groups[0]
        lr = g["lr"] if lr is None else lr
        beta1, beta2 = g["betas"]
        eps = g["eps"]
        wd = g["weight_decay"]
        max_coeff, min_coeff = g["max_coeff"], g["min_coeff"]
        step = state["step"] + 1
        fstep = step.astype(jnp.float32)
        if g["bias_correction"]:
            bc1 = 1.0 - beta1 ** fstep
            bc2 = 1.0 - beta2 ** fstep
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        frozen = step > self.freeze_step
        # wire dispatch resolved once per update, not per leaf. NOTE:
        # lamb keeps a per-leaf reduction (one collective per leaf);
        # adam's flatten-reduce-split fusion is the performant wire shape
        # — proportionate quantization groups (_group_for) keep small
        # leaves from padding to W*2048 here
        reduce_fn = (int8_compressed_allreduce if self.wire == "int8"
                     else compressed_allreduce)

        def denom_of(v):
            if self.eps_inside_sqrt:
                return jnp.sqrt(v / bc2 + eps)
            return jnp.sqrt(v / bc2) + eps

        def lamb_step(p32, adam_step, coeff_lo, coeff_hi, fixed_coeff=None):
            if wd:
                adam_step = adam_step + wd * p32
            p_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(adam_step.reshape(-1))
            if fixed_coeff is None:
                trust = jnp.where(
                    u_norm > 0.0, p_norm / jnp.maximum(u_norm, 1e-12), 1.0)
                trust = jnp.where(p_norm > 0.0, trust, 1.0)
                trust = jnp.clip(trust, coeff_lo, coeff_hi)
            else:
                trust = fixed_coeff
            return p32 - lr * trust * adam_step, trust

        def upd(p, grad, m, v, v_fresh, we, se, coeff, last_factor):
            grad = grad.astype(jnp.float32)
            p32 = p.astype(jnp.float32)

            def warm(ops):
                grad_, m_, v_, v_fresh_, we_, se_, coeff_, lf_ = ops
                if comm_axis is not None:
                    grad_ = lax.pmean(grad_, comm_axis)
                m_n = beta1 * m_ + (1.0 - beta1) * grad_
                v_n = beta2 * v_ + (1.0 - beta2) * grad_ * grad_
                adam_step = (m_n / bc1) / denom_of(v_n)
                new_p, trust = lamb_step(p32, adam_step, min_coeff, max_coeff)
                # EMA of the observed trust ratio -> the frozen coefficient
                coeff_n = self.coeff_beta * coeff_ + \
                    (1.0 - self.coeff_beta) * trust
                return new_p, m_n, v_n, v_n, we_, se_, coeff_n, lf_

            def compressed(ops):
                grad_, m_, v_, v_fresh_, we_, se_, coeff_, lf_ = ops
                m_local = beta1 * m_ + (1.0 - beta1) * grad_
                m_n, we_n, se_n = reduce_fn(m_local, we_, se_, comm_axis)
                # rebuild a fresh second-moment estimate from the
                # decompressed momentum delta (reference exp_avg_sq_fresh)
                g_est = (m_n - beta1 * m_) / (1.0 - beta1)
                v_fresh_n = beta2 * v_fresh_ + (1.0 - beta2) * g_est * g_est
                # frozen coefficient modulated by sqrt(fresh/frozen),
                # clamped + rate-limited (reference factor_max/min/threshold)
                ratio = jnp.sqrt(
                    (jnp.mean(v_fresh_n) + eps) / (jnp.mean(v_) + eps))
                factor = jnp.clip(ratio, self.factor_min, self.factor_max)
                factor = jnp.clip(factor,
                                  lf_ * (1.0 - self.factor_threshold),
                                  lf_ * (1.0 + self.factor_threshold))
                # constant denominator after freeze (no bias corrections):
                # a growing 1/bc2 on the frozen v would be an unintended
                # lr ramp (reference 1-bit lamb uses exp_avg_sq.sqrt()+eps)
                adam_step = m_n / (jnp.sqrt(v_) + eps)
                new_p, _ = lamb_step(p32, adam_step, min_coeff, max_coeff,
                                     fixed_coeff=coeff_ * factor)
                return new_p, m_n, v_, v_fresh_n, we_n, se_n, coeff_, factor

            ops = (grad, m, v, v_fresh, we, se, coeff, last_factor)
            new_p, m_n, v_n, vf_n, we_n, se_n, coeff_n, lf_n = lax.cond(
                frozen, compressed, warm, ops)
            return (new_p.astype(p.dtype), m_n, v_n, vf_n, we_n, se_n,
                    coeff_n, lf_n)

        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        vf_leaves = treedef.flatten_up_to(state["exp_avg_sq_fresh"])
        we_leaves = treedef.flatten_up_to(state["worker_error"])
        se_leaves = treedef.flatten_up_to(state["server_error"])
        c_leaves = treedef.flatten_up_to(state["lamb_coeff_freeze"])
        f_leaves = treedef.flatten_up_to(state["last_factor"])
        outs = [upd(*args) for args in zip(p_leaves, g_leaves, m_leaves,
                                           v_leaves, vf_leaves, we_leaves,
                                           se_leaves, c_leaves, f_leaves)]
        unf = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in outs])
        new_state = {
            "step": step,
            "exp_avg": unf(1),
            "exp_avg_sq": unf(2),
            "exp_avg_sq_fresh": unf(3),
            "worker_error": unf(4),
            "server_error": unf(5),
            "lamb_coeff_freeze": unf(6),
            "last_factor": unf(7),
        }
        return unf(0), new_state

    def state_dict(self):
        return {"param_groups": [dict(g) for g in self.param_groups],
                "freeze_step": self.freeze_step}

    def load_state_dict(self, sd):
        if "param_groups" in sd:
            self.param_groups = [dict(g) for g in sd["param_groups"]]
        self.freeze_step = int(sd.get("freeze_step", self.freeze_step))
