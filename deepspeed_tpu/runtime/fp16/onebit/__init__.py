from .adam import OnebitAdam  # noqa: F401
from .lamb import OnebitLamb  # noqa: F401
