from .adam import OnebitAdam  # noqa: F401
