"""1-bit Adam — error-compensated sign-compressed momentum allreduce.

Reference: deepspeed/runtime/fp16/onebit/adam.py:14 + the NCCL/MPI compressed
backends (runtime/comm/nccl.py:47-186). Semantics kept: dense Adam during a
`freeze_step` warmup, then the second moment is frozen and only momentum is
communicated, 1-bit sign-compressed with worker- and server-side error
feedback.

TPU redesign: the reference's cupy packbits + all_to_all + allgather
machinery was a bandwidth workaround for commodity interconnects. Here the
compress -> reduce -> recompress pipeline is a pure function inside the
jitted step: signs ride a psum over the `data` mesh axis (ICI), and both
error-feedback stages live in optimizer state. The optimizer owns its DP
reduction (`handles_dp_reduction`), so the engine skips its gradient psum
after warmup.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


# the compress->reduce->recompress pipeline lives in runtime/comm
# (shared with OnebitLamb and the standalone CompressedBackend)
from ...comm.compressed import (compressed_allreduce,  # noqa: E402,F401
                                int8_compressed_allreduce)


class OnebitAdam:
    name = "OnebitAdam"
    handles_dp_reduction = True

    def __init__(self, params=None, deepspeed=None, lr=1e-3, freeze_step=100000,
                 bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 eps_inside_sqrt=False, weight_decay=0.0, max_grad_norm=0.0,
                 amsgrad=False, cuda_aware=False, wire="sign"):
        if amsgrad:
            raise RuntimeError("1-bit Adam does not support the AMSGrad variant.")
        if wire not in ("sign", "int8"):
            raise ValueError(f"wire must be 'sign' or 'int8', got {wire!r}")
        self.defaults = dict(lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay,
                             bias_correction=bias_correction)
        self.param_groups = [dict(self.defaults)]
        self.freeze_step = int(freeze_step)
        self.eps_inside_sqrt = eps_inside_sqrt
        # wire="int8": quantized all_to_all/allgather instead of sign
        # compression — the variant whose wire bytes XLA actually shrinks
        # (~4x vs fp32; sign rides pmean at full width — see BENCH.md)
        self.wire = wire

    @property
    def lr(self):
        return self.param_groups[0]["lr"]

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
        zt = lambda: jax.tree_util.tree_map(zeros, params)
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "exp_avg": zt(),
            "exp_avg_sq": zt(),
            "worker_error": zt(),
            "server_error": zt(),
        }

    def update(self, grads, state, params, lr=None, comm_axis=None):
        """grads must be LOCAL (per-shard, unreduced) gradients; this
        optimizer performs its own DP averaging (dense during warmup,
        compressed after)."""
        g = self.param_groups[0]
        lr = g["lr"] if lr is None else lr
        beta1, beta2 = g["betas"]
        eps = g["eps"]
        wd = g["weight_decay"]
        step = state["step"] + 1
        frozen = step > self.freeze_step  # traced scalar bool

        if g["bias_correction"]:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def moments(grad, m, we, se):
            """FLAT (single fused buffer) momentum update: the reference
            NCCL backend also compresses one flattened momentum buffer
            (grouped per-2048 scales), paying each collective's latency
            once per step instead of once per leaf. Only the COMMUNICATED
            buffers (m, grad, errors) flatten; v stays per-leaf outside
            the cond (it is untouched after freeze). Returns
            (m_new, g_reduced, we_new, se_new) — g_reduced is the dense
            mean during warmup (feeds the per-leaf v update) and zeros
            after freeze (v frozen)."""

            def warm_branch(operands):
                grad_, m_, we_, se_ = operands
                g_ = lax.pmean(grad_, comm_axis) if comm_axis is not None else grad_
                m_warm = beta1 * m_ + (1.0 - beta1) * g_
                return m_warm, g_, we_, se_

            def frozen_branch(operands):
                grad_, m_, we_, se_ = operands
                m_local = beta1 * m_ + (1.0 - beta1) * grad_
                reduce_fn = (int8_compressed_allreduce
                             if self.wire == "int8"
                             else compressed_allreduce)
                m_comp, we_new, se_new = reduce_fn(m_local, we_, se_,
                                                   comm_axis)
                return m_comp, jnp.zeros_like(grad_), we_new, se_new

            # lax.cond so only ONE communication path executes per step —
            # after freeze the dense allreduce must not run, or 1-bit's
            # bandwidth saving is negated.
            return lax.cond(
                frozen, frozen_branch, warm_branch, (grad, m, we, se))

        def upd(p, new_m, new_v):
            p32 = p.astype(jnp.float32)
            # bias corrections apply during warmup only: after freeze the
            # reference uses the CONSTANT denominator exp_avg_sq.sqrt()+eps
            # (1-bit adam.py step) — a still-growing 1/bc2 on a frozen v
            # would act as an unintended lr ramp through the compressed
            # stage
            bc1_eff = jnp.where(frozen, 1.0, bc1)
            bc2_eff = jnp.where(frozen, 1.0, bc2)
            if self.eps_inside_sqrt:
                denom = jnp.sqrt(new_v / bc2_eff + eps)
            else:
                denom = jnp.sqrt(new_v / bc2_eff) + eps
            step_val = (new_m / bc1_eff) / denom
            if wd:
                step_val = step_val + wd * p32
            return (p32 - lr * step_val).astype(p.dtype)

        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        gl = treedef.flatten_up_to(grads)
        ml = treedef.flatten_up_to(state["exp_avg"])
        vl = treedef.flatten_up_to(state["exp_avg_sq"])
        wel = treedef.flatten_up_to(state["worker_error"])
        sel = treedef.flatten_up_to(state["server_error"])

        flat = lambda ls: jnp.concatenate(
            [l.astype(jnp.float32).ravel() for l in ls])
        new_fm, fgred, new_fwe, new_fse = moments(
            flat(gl), flat(ml), flat(wel), flat(sel))

        def split(fvec):
            out, off = [], 0
            for p in p_leaves:
                out.append(fvec[off:off + p.size].reshape(p.shape))
                off += p.size
            return out

        nm, gred = split(new_fm), split(fgred)
        # v per leaf, outside the cond: frozen -> unchanged (gred is 0
        # there, but where() keeps the exact old buffer)
        nv = [jnp.where(frozen, v_, beta2 * v_ + (1.0 - beta2) * g_ * g_)
              for v_, g_ in zip(vl, gred)]
        new_p = [upd(p, m_, v_) for p, m_, v_ in zip(p_leaves, nm, nv)]
        unflat = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
        return unflat(new_p), {"step": step, "exp_avg": unflat(nm),
                               "exp_avg_sq": unflat(nv),
                               "worker_error": unflat(split(new_fwe)),
                               "server_error": unflat(split(new_fse))}

    def state_dict(self):
        return {"param_groups": self.param_groups,
                "freeze_step": self.freeze_step}

    def load_state_dict(self, sd):
        self.param_groups = sd["param_groups"]
        self.freeze_step = sd.get("freeze_step", self.freeze_step)
