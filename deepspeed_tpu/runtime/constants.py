"""JSON config keys + defaults.

The JSON schema is API surface shared with the reference
(/root/reference/deepspeed/runtime/constants.py) so user configs port
unchanged. TPU-specific additions are marked; CUDA-only knobs are accepted
and treated as no-ops by the engine.
"""

#############################################
# Routes (reference constants.py ROUTE_*)
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size triple
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                        ONEBIT_LAMB_OPTIMIZER]
# optimizer params key (reference fp16/onebit + adam configs)
ADAM_W_MODE = "adam_w_mode"
ADAM_W_MODE_DEFAULT = True

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

#############################################
# Misc engine knobs
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Gradient-reduction wire (TPU-specific addition; see
# runtime/comm/bucketing.py and docs/tutorials/comm_tuning.md).
# FP32_ALLREDUCE is the reference key (engine fp32_allreduce option):
# when true the wire dtype is forced to fp32 regardless of COMM_WIRE_DTYPE.
#############################################
COMM = "comm"
COMM_GRADIENT_REDUCTION = "gradient_reduction"
COMM_GRADIENT_REDUCTION_DEFAULT = "implicit"  # or "bucketed"
COMM_GRADIENT_REDUCTION_MODES = ("implicit", "bucketed")
COMM_WIRE_DTYPE = "wire_dtype"
COMM_WIRE_DTYPE_DEFAULT = "fp32"  # fp32 | bf16 | split | int8 | int4
COMM_REDUCE_BUCKET_SIZE = "reduce_bucket_size"  # elements; falls back to
                                                # zero_optimization's knob
# Two-level (intra/inter fabric) reduction over a factored data axis:
#   "hierarchy": "none" | "auto" | <outer int> | {"outer": <int>}
# "auto" derives one outer group per jax process; an explicit outer must
# divide the dp size.  Only meaningful with gradient_reduction=bucketed.
COMM_HIERARCHY = "hierarchy"
COMM_HIERARCHY_DEFAULT = "none"
# Per-level wire overrides (default: wire_dtype for both levels; the
# inner level is scatter-structured, so the gather-structured wires
# (split/int8/int4) cannot run there — an explicit quantized inner
# request is a ValueError, an inherited one lowers to fp32).
COMM_WIRE_DTYPE_INNER = "wire_dtype_inner"
COMM_WIRE_DTYPE_OUTER = "wire_dtype_outer"
# Blockwise quantization granularity for the int8/int4 wires and the
# qwZ parameter gather: elements per fp16 scale (positive even int).
COMM_QUANT_BLOCK_SIZE = "quant_block_size"
COMM_QUANT_BLOCK_SIZE_DEFAULT = 256
# Comm/compute overlap (runtime/comm/overlap.py + step_builder.py):
#   "none"  serial wire (default)
#   "auto"  overlap where the engine can serve it (bucketed wire at
#           stage<3, qwZ gather at stage 3), logged fallback otherwise
#   true / "on"  demand overlap; unservable configs (onebit, Infinity,
#           offload, pipe-parallel stages, no overlappable wire) fall
#           back to the serial path with a WARNING — never silently
COMM_OVERLAP = "overlap"
COMM_OVERLAP_DEFAULT = "none"
COMM_OVERLAP_MODES = ("none", "auto", "on")
# How long a step may block on one in-flight exchange before the wait
# fails (ExchangeTicket deadline).  Size BELOW the StepWatchdog
# deadline (faults.watchdog.deadline_s, default 600 s): the ticket
# timeout is the named, actionable failure — the watchdog's stack
# snapshot is the backstop for hangs nobody sized a deadline for.
COMM_OVERLAP_TIMEOUT_MS = "overlap_timeout_ms"
COMM_OVERLAP_TIMEOUT_MS_DEFAULT = 300_000
# Self-healing budget for a dropped exchange connection: dial attempts
# with bounded exponential backoff (0 = never reconnect, go straight
# to the KV fallback + coordinated demotion), and the TOTAL time
# budget on both sides — the dialer's whole redial loop and the
# accepting side's wait for the peer's re-dial are each bounded by the
# window, so keep it below overlap_timeout_ms: a blackholed peer must
# reach the KV fallback before an in-flight ticket deadline fires.
COMM_OVERLAP_RECONNECT_ATTEMPTS = "overlap_reconnect_attempts"
COMM_OVERLAP_RECONNECT_ATTEMPTS_DEFAULT = 8
COMM_OVERLAP_RECONNECT_WINDOW_MS = "overlap_reconnect_window_ms"
COMM_OVERLAP_RECONNECT_WINDOW_MS_DEFAULT = 60_000
# Sender-worker keepalive cadence: a dead connection surfaces within
# ~one interval even between submits (idle wires otherwise only learn
# about a dead peer at the next data frame).
COMM_OVERLAP_KEEPALIVE_MS = "overlap_keepalive_ms"
COMM_OVERLAP_KEEPALIVE_MS_DEFAULT = 5_000
FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False
# MoE token movement (moe/dispatch.py; validated by parse_moe_config —
# every key is rejected at config time naming the key + valid set):
#   "moe": {
#     "dispatch": "dense" | "sorted",   # default dense (the seed path);
#                                       # defaults to sorted when an a2a
#                                       # wire dtype is requested
#     "a2a_wire_dtype": null | "fp32" | "bf16" | "int8" | "int4",
#                      # null = exchange left implicit to XLA; a dtype
#                      # selects the EXPLICIT shard_map all-to-all wire
#     "a2a_wire_dtype_inner": ...,      # per-level overrides on a
#     "a2a_wire_dtype_outer": ...,      # factored (hierarchical) mesh
#     "placement": "auto" | "data" | "inner",
#                      # "inner" pins experts to data_inner (replicated
#                      # across outer groups): the exchange never leaves
#                      # the fast fabric.  "auto" = inner when factored.
#     "dropless": false,                # second-pass overflow bucket
#     "overflow_factor": 0.25,          # bucket = ceil(f * k * tokens)
#     "quant_block_size": <even int>,   # default: comm.quant_block_size
#     "overlap": "none" | "auto" | "on",  # accepted; falls back LOGGED
#     "counters": true                  # moe.* callback counters
#   }
COMM_MOE = "moe"

#############################################
# Async input pipeline (TPU-specific addition; see runtime/dataloader.py
# PrefetchLoader, engine._DeviceFeed and docs/tutorials/data_pipeline.md).
# Default ON: host collate runs on background thread(s) and batch N+1's
# H2D transfer overlaps step N's compute.  The batch stream and loss
# curve are byte-identical with the pipeline off (pinned in
# tests/test_data_pipeline.py).
#############################################
DATA_PIPELINE = "data_pipeline"
DATA_PIPELINE_ENABLED = "enabled"
DATA_PIPELINE_ENABLED_DEFAULT = True
DATA_PIPELINE_PREFETCH_DEPTH = "prefetch_depth"   # bounded-queue batches
DATA_PIPELINE_PREFETCH_DEPTH_DEFAULT = 2
DATA_PIPELINE_NUM_WORKERS = "num_workers"         # parallel collate threads
DATA_PIPELINE_NUM_WORKERS_DEFAULT = 1
DATA_PIPELINE_DEVICE_PREFETCH = "device_prefetch"  # double-buffer H2D
DATA_PIPELINE_DEVICE_PREFETCH_DEFAULT = True

#############################################
# Chaos-ready runtime (TPU-specific addition; see runtime/resilience.py
# and docs/tutorials/resilience.md).  `rules` drive deterministic fault
# INJECTION (gated on `enabled`, default on iff rules are present);
# `retry` tunes the transient-fault backoff applied to hostwire KV
# traffic and checkpoint file IO; `watchdog` arms the in-process hang
# detector that snapshots + escalates to the elasticity supervisor.
#############################################
FAULTS = "faults"
FAULTS_ENABLED = "enabled"
FAULTS_SEED = "seed"
FAULTS_SEED_DEFAULT = 0
FAULTS_RULES = "rules"
FAULTS_RETRY = "retry"
FAULTS_RETRY_MAX_ATTEMPTS = "max_attempts"
FAULTS_RETRY_MAX_ATTEMPTS_DEFAULT = 4
FAULTS_RETRY_BASE_DELAY_MS = "base_delay_ms"
FAULTS_RETRY_BASE_DELAY_MS_DEFAULT = 50.0
FAULTS_RETRY_MAX_DELAY_MS = "max_delay_ms"
FAULTS_RETRY_MAX_DELAY_MS_DEFAULT = 2000.0
FAULTS_RETRY_JITTER = "jitter"
FAULTS_RETRY_JITTER_DEFAULT = 0.25
FAULTS_WATCHDOG = "watchdog"
FAULTS_WATCHDOG_ENABLED = "enabled"
FAULTS_WATCHDOG_ENABLED_DEFAULT = False
FAULTS_WATCHDOG_DEADLINE_S = "deadline_s"
FAULTS_WATCHDOG_DEADLINE_S_DEFAULT = 600.0
FAULTS_WATCHDOG_POLL_S = "poll_s"
FAULTS_WATCHDOG_POLL_S_DEFAULT = 1.0
FAULTS_WATCHDOG_SNAPSHOT_DIR = "snapshot_dir"
FAULTS_WATCHDOG_FIRST_BEAT_MULT = "first_beat_mult"
# grace multiplier on the deadline BEFORE the first step-boundary beat:
# an elastic shrink/grow restart pays a full recompile at the new mesh
# shape, which legitimately lands between construction and beat 1
FAULTS_WATCHDOG_FIRST_BEAT_MULT_DEFAULT = 4.0

#############################################
# Precision: fp16 section doubles as the precision section via "type"
# (EleutherAI fork: PRECISION, runtime/constants.py:127-161)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_TYPE = "type"
FP16_TYPE_DEFAULT = "fp16"
PRECISION_TYPES = ("fp16", "float16", "half", "bf16", "bfloat16", "fp32",
                   "float32", "float")
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# TensorBoard
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_MODES = ("ignore", "warn", "fail")
CHECKPOINT_TAG_VALIDATION_DEFAULT = "warn"
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = False
CHECKPOINT_COMMIT_TIMEOUT_MS = "commit_timeout_ms"
CHECKPOINT_COMMIT_TIMEOUT_MS_DEFAULT = 300_000
# Preemption safety: when set, the engine installs a SIGTERM handler
# honoring the supervisor's "SIGTERM = save-if-possible" contract — an
# emergency checkpoint is saved into this directory at the next step
# boundary, committed through the two-phase barrier, and the process
# exits cleanly so the relaunch resumes from the preemption point.
CHECKPOINT_PREEMPT_SAVE_DIR = "preempt_save_dir"
CHECKPOINT_PREEMPT_SAVE_DIR_DEFAULT = None

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Autotune: the self-tuning runtime (runtime/autotune/)
#
# "autotune": {
#   "enabled": false,          # arm the runtime (search on demand)
#   "probe_steps": 2,          # timed engine steps per candidate probe
#   "probe_warmup": 1,         # compile/warm steps before timing
#   "budget_s": null,          # wall budget across one search (null =
#                              # unbounded; exhausted => skipped probes,
#                              # and a degraded probe set is never cached)
#   "cache_path": null,        # fingerprint-keyed winner cache JSON
#   "ledger_path": null,       # default: <monitor run dir>/autotune.jsonl
#   "apply_winner": true,      # swap the engine onto the search winner
#   "min_improvement": 0.03,   # swap only if winner ms/step beats the
#                              # incumbent by this fraction
#   "wire_dtypes": [...],      # candidate wire dtypes
#   "bucket_sizes": [],        # extra reduce_bucket_size candidates
#   "include_overlap": true,   # include comm.overlap flips
#   "online": {                # the live retune loop
#     "enabled": false, "window": 5, "baseline_steps": 5,
#     "threshold": 1.5,        # sustained ms/step ratio over baseline
#     "exposed_threshold_ms": 0.0,  # exposed-wire creep trigger (0=off)
#     "cooldown_steps": 20,    # no re-trigger right after a retune
#     "check_every": 1,        # rank-consensus cadence (boundaries)
#     "radius": 1,             # knob-distance of the re-probe set
#     "safe_only": true        # online swaps keep bitwise loss parity
#   }
# }
#############################################
AUTOTUNE = "autotune"
AUTOTUNE_ENABLED = "enabled"
AUTOTUNE_ENABLED_DEFAULT = False
AUTOTUNE_PROBE_STEPS = "probe_steps"
AUTOTUNE_PROBE_STEPS_DEFAULT = 2
AUTOTUNE_PROBE_WARMUP = "probe_warmup"
AUTOTUNE_PROBE_WARMUP_DEFAULT = 1
AUTOTUNE_BUDGET_S = "budget_s"
AUTOTUNE_BUDGET_S_DEFAULT = None
AUTOTUNE_CACHE_PATH = "cache_path"
AUTOTUNE_CACHE_PATH_DEFAULT = None
AUTOTUNE_LEDGER_PATH = "ledger_path"
AUTOTUNE_LEDGER_PATH_DEFAULT = None
AUTOTUNE_APPLY_WINNER = "apply_winner"
AUTOTUNE_APPLY_WINNER_DEFAULT = True
AUTOTUNE_MIN_IMPROVEMENT = "min_improvement"
AUTOTUNE_MIN_IMPROVEMENT_DEFAULT = 0.03
AUTOTUNE_WIRE_DTYPES = "wire_dtypes"
AUTOTUNE_WIRE_DTYPES_DEFAULT = ("fp32", "bf16", "int8")
AUTOTUNE_BUCKET_SIZES = "bucket_sizes"
AUTOTUNE_BUCKET_SIZES_DEFAULT = ()
AUTOTUNE_INCLUDE_OVERLAP = "include_overlap"
AUTOTUNE_INCLUDE_OVERLAP_DEFAULT = True
AUTOTUNE_ONLINE = "online"
AUTOTUNE_ONLINE_ENABLED = "enabled"
AUTOTUNE_ONLINE_ENABLED_DEFAULT = False
AUTOTUNE_ONLINE_WINDOW = "window"
AUTOTUNE_ONLINE_WINDOW_DEFAULT = 5
AUTOTUNE_ONLINE_BASELINE_STEPS = "baseline_steps"
AUTOTUNE_ONLINE_BASELINE_STEPS_DEFAULT = 5
AUTOTUNE_ONLINE_THRESHOLD = "threshold"
AUTOTUNE_ONLINE_THRESHOLD_DEFAULT = 1.5
AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS = "exposed_threshold_ms"
AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS_DEFAULT = 0.0
AUTOTUNE_ONLINE_COOLDOWN_STEPS = "cooldown_steps"
AUTOTUNE_ONLINE_COOLDOWN_STEPS_DEFAULT = 20
AUTOTUNE_ONLINE_CHECK_EVERY = "check_every"
AUTOTUNE_ONLINE_CHECK_EVERY_DEFAULT = 1
AUTOTUNE_ONLINE_RADIUS = "radius"
AUTOTUNE_ONLINE_RADIUS_DEFAULT = 1
AUTOTUNE_ONLINE_SAFE_ONLY = "safe_only"
AUTOTUNE_ONLINE_SAFE_ONLY_DEFAULT = True

#############################################
# Serving (deepspeed_tpu.serving) — inference-side knobs the autotuner's
# "serve" scope searches over. No reference analogue (the reference
# inference engine arrived in later versions).
# "serving": {
#   "kv_dtype": null,          # null = param dtype | "bf16"|"int8"|"int4"
#   "speculative": {
#     "enabled": false,        # arm self-speculative n-gram decoding
#     "draft_len": 4,          # candidate tokens per verify step
#     "ngram": 3               # suffix-match length of the host drafter
#   },
#   "prefix_cache": {
#     "enabled": true,         # block-level prefix sharing + sessions
#     "min_match_blocks": 1,   # shortest chain worth aliasing
#     "session_ttl_s": 120.0   # pinned-session residency window
#   },
#   "fleet": {
#     "replicas": 1,           # in-process ServeEngine replicas
#     "queue_limit": 64,       # per-replica waiting-queue cap
#     "session_affinity": true # pinned sessions land on their replica
#   }
# }
#############################################
SERVING = "serving"
SERVING_KV_DTYPE = "kv_dtype"
SERVING_KV_DTYPE_DEFAULT = None
SERVING_SPECULATIVE = "speculative"
SERVING_SPEC_ENABLED = "enabled"
SERVING_SPEC_ENABLED_DEFAULT = False
SERVING_SPEC_DRAFT_LEN = "draft_len"
SERVING_SPEC_DRAFT_LEN_DEFAULT = 4
SERVING_SPEC_NGRAM = "ngram"
SERVING_SPEC_NGRAM_DEFAULT = 3
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_ENABLED = "enabled"
SERVING_PREFIX_ENABLED_DEFAULT = True
SERVING_PREFIX_MIN_MATCH_BLOCKS = "min_match_blocks"
SERVING_PREFIX_MIN_MATCH_BLOCKS_DEFAULT = 1
SERVING_PREFIX_SESSION_TTL_S = "session_ttl_s"
SERVING_PREFIX_SESSION_TTL_S_DEFAULT = 120.0
SERVING_FLEET = "fleet"
SERVING_FLEET_REPLICAS = "replicas"
SERVING_FLEET_REPLICAS_DEFAULT = 1
SERVING_FLEET_QUEUE_LIMIT = "queue_limit"
SERVING_FLEET_QUEUE_LIMIT_DEFAULT = 64
SERVING_FLEET_SESSION_AFFINITY = "session_affinity"
SERVING_FLEET_SESSION_AFFINITY_DEFAULT = True

#############################################
# Kernels (deepspeed_tpu.kernels) — the Pallas hot-loop op registry
# (reference analogue: the op_builder CUDA-extension switches).
# "kernels": {
#   "impl": "auto",            # global default: auto|pallas|jnp
#   "ops": {},                 # per-op override, e.g. {"quant_codec": "pallas"}
#   "interpret": false,        # let forced pallas run off-TPU (interpreter)
#   "counters": true           # kernel.dispatches / kernel.fallbacks
# }
#############################################
KERNELS = "kernels"
KERNELS_IMPL = "impl"
KERNELS_IMPL_DEFAULT = "auto"
KERNELS_OPS = "ops"
KERNELS_INTERPRET = "interpret"
KERNELS_INTERPRET_DEFAULT = False
KERNELS_COUNTERS = "counters"
KERNELS_COUNTERS_DEFAULT = True

#############################################
# TPU-specific additions (no reference analogue)
#############################################
MESH = "mesh"  # {"data": -1, "model": 1, "pipe": 1, "seq": 1}
MESH_DEFAULT = None
REMAT = "rematerialization"  # {"enabled": bool, "policy": "dots"|"nothing"|"everything"}
