"""Runtime utilities: partition solvers, grad norms, overflow checks,
memory telemetry.

Reference: deepspeed/runtime/utils.py (partition_uniform :333,
partition_balanced :399 with binary-search _rb_partition_balanced :383,
CheckOverflow :65, get_grad_norm :192, see_memory_usage :569).
Norm/overflow logic is redesigned as pure jittable pytree reductions;
collectives over mesh axes replace torch.distributed allreduces.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger


# ---------------------------------------------------------------------------
# Partition solvers (used by pipeline stage assignment; pure python)
# ---------------------------------------------------------------------------

def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out = []
    total = 0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """num_parts+1 boundaries splitting num_items as evenly as possible
    (reference runtime/utils.py:333)."""
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items % num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunksize + (1 if p <= residual else 0)
    return parts


def _lprobe(weights_csum: List[float], num_parts: int, bottleneck: float):
    """Greedy probe: can we split so every part's weight <= bottleneck?
    Each part takes as many items as fit. Returns (parts, success)."""
    n = len(weights_csum)
    parts = [0] * (num_parts + 1)
    start, base = 0, 0.0
    tol = 1e-9 * max(1.0, weights_csum[-1])
    for p in range(1, num_parts):
        end = bisect_right(weights_csum, base + bottleneck + tol, lo=start)
        if end == start:  # a single item exceeds the bottleneck
            return parts, False
        parts[p] = end
        start = end
        if start >= n:  # everything placed; trailing parts empty
            for q in range(p + 1, num_parts + 1):
                parts[q] = n
            return parts, True
        base = weights_csum[start - 1]
    parts[num_parts] = n
    return parts, (weights_csum[-1] - base) <= bottleneck + tol


def partition_balanced(weights: Sequence[float], num_parts: int,
                       eps: float = 1e-3) -> List[int]:
    """Boundaries minimizing the max part weight, via binary search over the
    bottleneck (reference _rb_partition_balanced :383 + partition_balanced
    :399)."""
    weights = list(weights)
    if not weights:
        return [0] * (num_parts + 1)
    csum = prefix_sum_inc(weights)
    total, biggest = csum[-1], max(weights)
    lo, hi = max(biggest, total / num_parts), total
    while hi - lo > eps * max(1.0, total):
        mid = (lo + hi) / 2
        _, ok = _lprobe(csum, num_parts, mid)
        if ok:
            hi = mid
        else:
            lo = mid
    parts, ok = _lprobe(csum, num_parts, hi)
    if not ok:  # fall back: hi == total always succeeds with 1 big part
        parts, _ = _lprobe(csum, num_parts, total)
    return parts


# ---------------------------------------------------------------------------
# Overflow / norms (jittable)
# ---------------------------------------------------------------------------

def has_overflow(grads, axes: Optional[Sequence[str]] = None):
    """True if any grad is inf/nan, reduced over the given mesh axes
    (reference CheckOverflow: allreduce MAX over dp+mp groups)."""
    leaves = jax.tree_util.tree_leaves(grads)
    local = jnp.asarray(False)
    for g in leaves:
        local = jnp.logical_or(local,
                               jnp.logical_not(jnp.all(jnp.isfinite(g))))
    if axes:
        f = local.astype(jnp.float32)
        for ax in axes:
            f = lax.pmax(f, ax)
        local = f > 0
    return local


def global_grad_norm_sq(grads, model_axes: Optional[Sequence[str]] = None):
    """Sum of squared grad entries; psum over model-parallel axes so each
    shard sees the full-model norm (reference get_grad_norm :192 mp-aware
    path)."""
    total = jnp.asarray(0.0, jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    if model_axes:
        for ax in model_axes:
            total = lax.psum(total, ax)
    return total


def clip_grad_norm(grads, max_norm: float,
                   model_axes: Optional[Sequence[str]] = None,
                   norm_sq=None):
    """Global-norm clipping as one fused scale (reference
    clip_grad_norm_ semantics). Returns (clipped_grads, pre_clip_norm)."""
    if norm_sq is None:
        norm_sq = global_grad_norm_sq(grads, model_axes)
    norm = jnp.sqrt(norm_sq)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype), grads), norm


def get_global_norm(norm_list):
    """sqrt of sum of squares (reference get_global_norm)."""
    total = 0.0
    for n in norm_list:
        total += n ** 2.0
    return total ** 0.5


# ---------------------------------------------------------------------------
# Memory telemetry
# ---------------------------------------------------------------------------

def see_memory_usage(message: str, force: bool = False):
    """Device-memory snapshot (reference see_memory_usage :569 reports CUDA
    allocator stats; here XLA per-device stats). Silent unless force=True,
    matching the reference's early-return guard."""
    if not force:
        return
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        in_use = stats.get("bytes_in_use", 0) / (1024 ** 3)
        peak = stats.get("peak_bytes_in_use", 0) / (1024 ** 3)
        limit = stats.get("bytes_limit", 0) / (1024 ** 3)
        logger.info(f"{message} | MemUse {in_use:.2f} GB peak {peak:.2f} GB "
                    f"limit {limit:.2f} GB")
    except Exception:
        logger.info(f"{message} | memory stats unavailable on this backend")


class ThroughputTimer:
    """samples/sec reporting (reference utils/timer.py:105)."""

    def __init__(self, batch_size, num_workers=1, start_step=2,
                 steps_per_output=50, monitor_memory=False, logging_fn=None):
        import time

        self._time = time
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0.0
        self.start_time = 0.0

    def start(self):
        if not self.initialized:
            self.initialized = True
        self.start_time = self._time.time()

    def stop(self, report_speed=True):
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count <= self.start_step:
            return  # skip warmup/compile steps
        duration = self._time.time() - self.start_time
        self.total_elapsed_time += duration
        if report_speed and self.local_step_count % self.steps_per_output == 0:
            self.logging(
                f"step={self.total_step_count}, "
                f"samples/sec={self.avg_samples_per_sec():.1f}")

    def avg_samples_per_sec(self):
        counted = self.total_step_count - self.start_step
        if counted > 0 and self.total_elapsed_time > 0:
            return self.batch_size * self.num_workers * counted / \
                self.total_elapsed_time
        return 0.0
