"""Runtime utilities: partition solvers, grad norms, overflow checks,
memory telemetry.

Reference: deepspeed/runtime/utils.py (partition_uniform :333,
partition_balanced :399 with binary-search _rb_partition_balanced :383,
CheckOverflow :65, get_grad_norm :192, see_memory_usage :569).
Norm/overflow logic is redesigned as pure jittable pytree reductions;
collectives over mesh axes replace torch.distributed allreduces.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger


# ---------------------------------------------------------------------------
# Partition solvers (used by pipeline stage assignment; pure python)
# ---------------------------------------------------------------------------

def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out = []
    total = 0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """num_parts+1 boundaries splitting num_items as evenly as possible
    (reference runtime/utils.py:333)."""
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items % num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunksize + (1 if p <= residual else 0)
    return parts


def _lprobe(weights_csum: List[float], num_parts: int, bottleneck: float):
    """Greedy probe: can we split so every part's weight <= bottleneck?
    Each part takes as many items as fit. Returns (parts, success)."""
    n = len(weights_csum)
    parts = [0] * (num_parts + 1)
    start, base = 0, 0.0
    tol = 1e-9 * max(1.0, weights_csum[-1])
    for p in range(1, num_parts):
        end = bisect_right(weights_csum, base + bottleneck + tol, lo=start)
        if end == start:  # a single item exceeds the bottleneck
            return parts, False
        parts[p] = end
        start = end
        if start >= n:  # everything placed; trailing parts empty
            for q in range(p + 1, num_parts + 1):
                parts[q] = n
            return parts, True
        base = weights_csum[start - 1]
    parts[num_parts] = n
    return parts, (weights_csum[-1] - base) <= bottleneck + tol


def partition_balanced(weights: Sequence[float], num_parts: int,
                       eps: float = 1e-3) -> List[int]:
    """Boundaries minimizing the max part weight, via binary search over the
    bottleneck (reference _rb_partition_balanced :383 + partition_balanced
    :399)."""
    weights = list(weights)
    if not weights:
        return [0] * (num_parts + 1)
    csum = prefix_sum_inc(weights)
    total, biggest = csum[-1], max(weights)
    lo, hi = max(biggest, total / num_parts), total
    while hi - lo > eps * max(1.0, total):
        mid = (lo + hi) / 2
        _, ok = _lprobe(csum, num_parts, mid)
        if ok:
            hi = mid
        else:
            lo = mid
    parts, ok = _lprobe(csum, num_parts, hi)
    if not ok:  # fall back: hi == total always succeeds with 1 big part
        parts, _ = _lprobe(csum, num_parts, total)
    return parts


# ---------------------------------------------------------------------------
# Overflow / norms (jittable)
# ---------------------------------------------------------------------------

def has_overflow(grads, axes: Optional[Sequence[str]] = None):
    """True if any grad is inf/nan, reduced over the given mesh axes
    (reference CheckOverflow: allreduce MAX over dp+mp groups)."""
    leaves = jax.tree_util.tree_leaves(grads)
    local = jnp.asarray(False)
    for g in leaves:
        local = jnp.logical_or(local,
                               jnp.logical_not(jnp.all(jnp.isfinite(g))))
    if axes:
        f = local.astype(jnp.float32)
        for ax in axes:
            f = lax.pmax(f, ax)
        local = f > 0
    return local


def global_grad_norm_sq(grads, model_axes: Optional[Sequence[str]] = None):
    """Sum of squared grad entries; psum over model-parallel axes so each
    shard sees the full-model norm (reference get_grad_norm :192 mp-aware
    path)."""
    total = jnp.asarray(0.0, jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    if model_axes:
        for ax in model_axes:
            total = lax.psum(total, ax)
    return total


def clip_grad_norm(grads, max_norm: float,
                   model_axes: Optional[Sequence[str]] = None,
                   norm_sq=None):
    """Global-norm clipping as one fused scale (reference
    clip_grad_norm_ semantics). Returns (clipped_grads, pre_clip_norm)."""
    if norm_sq is None:
        norm_sq = global_grad_norm_sq(grads, model_axes)
    norm = jnp.sqrt(norm_sq)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype), grads), norm


def get_global_norm(norm_list):
    """sqrt of sum of squares (reference get_global_norm)."""
    total = 0.0
    for n in norm_list:
        total += n ** 2.0
    return total ** 0.5


# ---------------------------------------------------------------------------
# Memory telemetry
# ---------------------------------------------------------------------------

def see_memory_usage(message: str, force: bool = False):
    """Device-memory snapshot (reference see_memory_usage :569 reports CUDA
    allocator stats; here XLA per-device stats). Silent unless force=True,
    matching the reference's early-return guard."""
    if not force:
        return
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        in_use = stats.get("bytes_in_use", 0) / (1024 ** 3)
        peak = stats.get("peak_bytes_in_use", 0) / (1024 ** 3)
        limit = stats.get("bytes_limit", 0) / (1024 ** 3)
        logger.info(f"{message} | MemUse {in_use:.2f} GB peak {peak:.2f} GB "
                    f"limit {limit:.2f} GB")
    except Exception:
        logger.info(f"{message} | memory stats unavailable on this backend")


class ThroughputTimer:
    """samples/sec reporting (reference utils/timer.py:105)."""

    def __init__(self, batch_size, num_workers=1, start_step=2,
                 steps_per_output=50, monitor_memory=False, logging_fn=None):
        import time

        self._time = time
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False
        self.epoch_count = 0
        self.local_step_count = 0
        self.total_step_count = 0
        self.total_elapsed_time = 0.0
        self.start_time = 0.0

    def start(self):
        if not self.initialized:
            self.initialized = True
        self.start_time = self._time.time()

    def stop(self, report_speed=True):
        self.total_step_count += 1
        self.local_step_count += 1
        if self.total_step_count <= self.start_step:
            return  # skip warmup/compile steps
        duration = self._time.time() - self.start_time
        self.total_elapsed_time += duration
        if report_speed and self.local_step_count % self.steps_per_output == 0:
            self.logging(
                f"step={self.total_step_count}, "
                f"samples/sec={self.avg_samples_per_sec():.1f}")

    def avg_samples_per_sec(self):
        counted = self.total_step_count - self.start_step
        if counted > 0 and self.total_elapsed_time > 0:
            return self.batch_size * self.num_workers * counted / \
                self.total_elapsed_time
        return 0.0


# ---------------------------------------------------------------------------
# PartitionedTensor (reference runtime/utils.py:417) — flat 1/N slices of a
# tensor across a process/axis group with a meta handshake, used by the
# reference pipeline to shard activations across MP ranks in flight. Here
# the SPMD pipeline shards via sharding constraints, so this is the host-
# side parity utility (explicit num_parts/rank; a mesh axis name supplies
# defaults).
# ---------------------------------------------------------------------------

class PartitionedTensor:
    def __init__(self, tensor, group: Optional[str] = None,
                 num_parts: Optional[int] = None, rank: Optional[int] = None):
        self.group = group
        if num_parts is None:
            if group is None:
                num_parts = 1  # single-controller default: trivial partition
            else:
                from ..comm.mesh import peek_mesh

                info = peek_mesh()
                if info is None or group not in info.mesh.shape:
                    raise ValueError(
                        f"group {group!r} is not an axis of the current "
                        f"mesh; pass num_parts explicitly")
                num_parts = info.mesh.shape[group]
        self.num_parts = num_parts
        if rank is None:
            if self.num_parts != 1:
                raise ValueError(
                    "PartitionedTensor needs an explicit rank when "
                    "num_parts > 1 (single-controller processes have no "
                    "implicit per-axis rank)")
            rank = 0
        self.rank = rank
        self.orig_size = list(tensor.shape)
        flat = jnp.ravel(tensor)
        self.partition = partition_uniform(flat.size, self.num_parts)
        start = self.partition[self.rank]
        end = self.partition[self.rank + 1]
        self.local_data = flat[start:end]

    def to_meta(self):
        """[ndims, *shape, num_parts, rank, *boundaries] int32 vector
        (reference encodes the same fields :454-476)."""
        return jnp.asarray(
            [len(self.orig_size)] + self.orig_size +
            [self.num_parts, self.rank] + list(self.partition), jnp.int32)

    @classmethod
    def from_meta(cls, meta, local_part, group: Optional[str] = None):
        meta = [int(x) for x in meta]
        nd = meta[0]
        obj = cls.__new__(cls)
        obj.group = group
        obj.orig_size = meta[1:1 + nd]
        obj.num_parts = meta[1 + nd]
        obj.rank = meta[2 + nd]
        obj.partition = meta[3 + nd:]
        obj.local_data = local_part
        return obj

    def data(self):
        return self.local_data

    def local_size(self):
        return self.local_data.size

    def full(self, parts: Optional[Sequence] = None):
        """Reassemble. In multi-process mode callers pass the gathered
        parts (one per rank, e.g. via comm.all_gather of local_data);
        single-controller callers omit `parts` only when num_parts == 1."""
        if parts is None:
            if self.num_parts != 1:
                raise ValueError(
                    "full() without parts requires num_parts == 1; gather "
                    "the per-rank local_data slices and pass them in")
            parts = [self.local_data]
        flat = jnp.concatenate([jnp.ravel(p) for p in parts])
        return flat.reshape(self.orig_size)


# ---------------------------------------------------------------------------
# Gradient noise scale (reference runtime/utils.py:618): "An Empirical
# Model of Large-Batch Training" estimator from per-micro-batch gradients.
# ---------------------------------------------------------------------------

class GradientNoiseScale:
    """Feed per-micro-batch flattened gradients via update(); every
    n_batches updates it compares |g_small|^2 (one micro batch) with
    |g_big|^2 (mean of the window) and EMA-smooths the scale/noise
    estimates exactly as the reference does."""

    def __init__(self, batch_size_small: int, n_batches: int,
                 beta: float = 0.99):
        self.batch_size_small = batch_size_small
        self.batch_size_large = batch_size_small * n_batches
        self.n_batches = n_batches
        self.beta = beta
        self.buffer = []
        self.ema_scale = None
        self.ema_noise = None
        self.scale = None
        self.noise = None
        self.noise_scale = None
        self.n_updates = 0

    def _ema(self, avg, yi, i):
        if avg is None:
            avg = 0.0
        avg = self.beta * avg + (1 - self.beta) * yi
        return avg, avg / (1 - self.beta ** (i + 1))

    @staticmethod
    def flatten_grads(grads) -> jnp.ndarray:
        leaves = [jnp.ravel(l) for l in jax.tree_util.tree_leaves(grads)]
        return jnp.concatenate(leaves)

    def update(self, grads):
        curr = self.flatten_grads(grads)
        self.buffer.append(curr)
        if self.n_updates % self.n_batches == self.n_batches - 1:
            past = jnp.stack(self.buffer, axis=1)
            self.buffer = []
            big = past.mean(axis=1)
            g_big = float(jnp.mean(big ** 2))
            g_small = float(jnp.mean(curr ** 2))
            bs, bl = self.batch_size_small, self.batch_size_large
            noise = (bl * g_big - bs * g_small) / (bl - bs)
            scale = (g_small - g_big) / ((1.0 / bs) - (1.0 / bl))
            self.ema_scale, scale = self._ema(self.ema_scale, scale,
                                              self.n_updates)
            self.ema_noise, noise = self._ema(self.ema_noise, noise,
                                              self.n_updates)
            self.scale = scale
            self.noise = noise
            self.noise_scale = scale / noise if noise else None
        self.n_updates += 1
        return self.noise_scale
