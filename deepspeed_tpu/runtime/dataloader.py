"""Data loading: DeepSpeedDataLoader + RepeatingLoader + PrefetchLoader.

Reference: deepspeed/runtime/dataloader.py:10,33 (torch DataLoader +
DistributedSampler). TPU-native redesign: single-controller JAX wants the
GLOBAL batch assembled on host and sharded over the mesh's data axis by the
engine, so the loader yields global numpy batches; in multi-process mode
each process reads its own slice (process_index-strided sampling), matching
DistributedSampler semantics.

PrefetchLoader is the TPU-native answer to the reference's
`DataLoader(num_workers, pin_memory)`: the per-sample fetch + collate loop
runs on background thread(s) feeding a bounded queue, so the host
assembles batch N+1 while the device executes step N.  Batch ORDER is
deterministic regardless of worker count (round-robin task assignment +
in-order consumption), threads shut down cleanly on close()/GC/
StopIteration, and worker exceptions re-raise at the consumer — after a
bounded respawn-with-backoff budget: an index-protocol worker that dies
is replaced by a fresh thread resuming at the exact batch it died on
(`input.worker_respawns` counts them), so one transient worker death no
longer kills training (runtime/resilience.py chaos campaigns pin this).
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Optional

import numpy as np

import jax

from ..monitor.counters import COUNTERS
from ..utils.logging import logger
from .resilience import fault_point

# a dead prefetch worker no longer kills training: the consumer
# respawns it (resuming at the exact failed batch, so order and content
# are unchanged) up to MAX_RESPAWNS times per epoch, with doubling
# backoff between respawns.  After the budget the original exception
# re-raises — a deterministically failing dataset must still surface.
WORKER_MAX_RESPAWNS = 2
WORKER_RESPAWN_BACKOFF_S = 0.05


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10-31).
    Advances the wrapped loader's epoch on each wrap so shuffling loaders
    re-shuffle instead of replaying one permutation.  The counter seeds
    from the wrapped loader's CURRENT epoch (when it exposes one), so a
    loader restored mid-run from a sample cursor keeps its shuffle
    schedule instead of snapping back to epoch 1 on the first wrap."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._epoch = int(getattr(loader, "epoch", 0) or 0)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self._epoch += 1
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(self._epoch)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _default_collate(items):
    """Stack a list of samples into a batch pytree of numpy arrays."""
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([it[i] for it in items])
                           for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class DeepSpeedDataLoader:
    """Batched, optionally shuffled, process-sharded loader
    (reference :33-101).

    dataset: any indexable (len + __getitem__) of samples (arrays, tuples,
    dicts). Yields GLOBAL per-process batches as numpy pytrees; the engine
    shards dim 0 over the data mesh axis.

    drop_last=False pads the tail batch to full size by WRAPPING around
    this shard's sample order (DistributedSampler-style): a short tail
    would fall into the engine's replicate-over-data-axis fallback and
    cost dp x compute for that batch, so the few duplicated samples are
    the cheaper trade.  The duplicates slightly overweight the wrapped
    samples in that batch's loss — acceptable for training; for exact
    evaluation sums, account for `len(dataset)` yourself.
    """

    def __init__(self, dataset, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 local_rank: int = -1, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True, data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        # single-controller: every process loads its slice of the global batch
        self.num_shards = (data_parallel_world_size
                           if data_parallel_world_size is not None
                           else jax.process_count())
        self.shard_id = (data_parallel_rank if data_parallel_rank is not None
                         else jax.process_index())
        self.epoch = 0
        if self.batch_size % max(1, self.num_shards) == 0:
            self._per_shard = self.batch_size // max(1, self.num_shards)
        else:
            raise ValueError(
                f"batch_size {batch_size} not divisible by data shards "
                f"{self.num_shards}")
        # every shard sees the SAME number of samples (wraparound padding,
        # DistributedSampler-style) — unequal counts would desync lockstep
        # SPMD processes and hang collectives
        import math

        self._samples_per_shard = math.ceil(len(dataset) /
                                            max(1, self.num_shards))
        self.len = self._samples_per_shard // self._per_shard
        if not self.drop_last and self._samples_per_shard % self._per_shard:
            self.len += 1
        # sample cursor (elastic exactly-once stream): the CONSUMED-side
        # position — batches the training loop actually trained on, NOT
        # batches a prefetch worker produced ahead.  The engine advances
        # it per trained batch (record_consumed), checkpoints it in the
        # commit marker's meta (sample_cursor), and a restored loader —
        # possibly at a DIFFERENT shard count after an elastic shrink —
        # resumes the epoch at `_start_batch`.  Positions count GLOBAL
        # batches, which are width-independent: at any shard count W,
        # batch k of an epoch consumes exactly positions [k*B, (k+1)*B)
        # of the epoch's padded sample order (rank-strided slicing
        # commutes with the per-shard batch boundaries), so skipping k
        # batches at a new width skips exactly the samples the old
        # width already consumed.
        self._consumed_epoch = 0
        self._consumed_position = 0
        self._start_batch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.len

    # -- sample cursor (elastic exactly-once stream) -------------------

    def record_consumed(self, n: int = 1) -> None:
        """Advance the consumed-side cursor by `n` trained batches
        (engine-called at train_batch boundaries)."""
        per_epoch = max(1, self.len)
        self._consumed_position += int(n)
        while self._consumed_position >= per_epoch:
            self._consumed_position -= per_epoch
            self._consumed_epoch += 1

    def sample_cursor(self) -> dict:
        """The checkpointable cursor: everything a restoring run (at
        any shard count) needs to regenerate the exact remaining sample
        stream."""
        return {
            "epoch": self._consumed_epoch,
            "position": self._consumed_position,
            "seed": int(self.seed),
            "shuffle": bool(self.shuffle),
            "batch_size": int(self.batch_size),
            "drop_last": bool(self.drop_last),
            "dataset_len": len(self.dataset),
        }

    def load_sample_cursor(self, cursor: dict) -> None:
        """Shard-aware restore of a `sample_cursor()` snapshot, possibly
        at a different shard count / global batch size than it was saved
        at.  The saving run's (seed, shuffle) are ADOPTED — the epoch
        permutation must match or samples would drop/duplicate — and a
        position in old-batch units converts through the sample count
        (loud error when the old progress doesn't land on a new batch
        boundary).  A position past this width's epoch length (padding
        differences across widths) rolls into the next epoch."""
        try:
            epoch = int(cursor["epoch"])
            position = int(cursor["position"])
            saved_bs = int(cursor.get("batch_size", self.batch_size))
        except (KeyError, TypeError, ValueError):
            raise ValueError(
                f"sample cursor is malformed (needs integer epoch/"
                f"position): {cursor!r}")
        if epoch < 0 or position < 0 or saved_bs < 1:
            raise ValueError(f"sample cursor out of range: {cursor!r}")
        if "seed" in cursor and int(cursor["seed"]) != self.seed:
            logger.warning(
                f"sample cursor: adopting the saving run's shuffle seed "
                f"{cursor['seed']} (this loader was built with "
                f"{self.seed}) — the epoch permutation must match for "
                f"an exactly-once stream")
            self.seed = int(cursor["seed"])
        if "shuffle" in cursor and bool(cursor["shuffle"]) != self.shuffle:
            logger.warning(
                f"sample cursor: adopting the saving run's "
                f"shuffle={bool(cursor['shuffle'])} (this loader was "
                f"built with {self.shuffle})")
            self.shuffle = bool(cursor["shuffle"])
        if cursor.get("dataset_len") is not None and \
                int(cursor["dataset_len"]) != len(self.dataset):
            logger.warning(
                f"sample cursor: dataset length changed "
                f"({cursor['dataset_len']} -> {len(self.dataset)}) — "
                f"the exactly-once guarantee only holds over an "
                f"unchanged dataset")
        if saved_bs != self.batch_size:
            samples = position * saved_bs
            if samples % self.batch_size:
                raise ValueError(
                    f"sample cursor: {position} batches of {saved_bs} "
                    f"({samples} samples) do not land on a batch "
                    f"boundary of the new global batch size "
                    f"{self.batch_size} — keep the global batch "
                    f"constant across elastic transitions (or resume "
                    f"at a divisible point)")
            position = samples // self.batch_size
        per_epoch = max(1, self.len)
        if position >= per_epoch:
            # a different width's padding gave the saved epoch more
            # batches than this width has: the overflow is the next
            # epoch's head
            epoch += position // per_epoch
            position %= per_epoch
        self.epoch = epoch
        self._consumed_epoch = epoch
        self._consumed_position = position
        self._start_batch = position

    def _batch_indices(self):
        """Yield this shard's per-batch sample-index arrays for the
        CURRENT epoch, skipping the first `_start_batch` batches after
        a sample-cursor restore (consumed once; later epochs start at
        0).  Pure numpy (cheap) — the expensive part (dataset[j] +
        collate) lives in _materialize, so PrefetchLoader workers can
        collate different batches in parallel while this generator
        fixes the deterministic order."""
        start, self._start_batch = self._start_batch, 0
        for i, ids in enumerate(self._epoch_batch_indices()):
            if i >= start:
                yield ids

    def _epoch_batch_indices(self):
        """The full epoch's batch-index stream (no cursor skip)."""
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        # DistributedSampler semantics: pad to equal length by wrapping, then
        # rank-strided slice — all shards yield the same batch count
        total = self._samples_per_shard * self.num_shards
        if total > n:
            order = np.concatenate([order, order[:total - n]])
        shard_idx = order[self.shard_id::self.num_shards]
        for i in range(0, len(shard_idx) - self._per_shard + 1, self._per_shard):
            yield shard_idx[i:i + self._per_shard]
        if not self.drop_last:
            tail = len(shard_idx) % self._per_shard
            if tail:
                # wraparound pad to _per_shard: a full-size tail keeps the
                # batch on the sharded (not replicated) engine path.
                # np.resize TILES the shard order, so even a shard with
                # fewer samples than _per_shard pads to full size
                ids = shard_idx[len(shard_idx) - tail:]
                pad = np.resize(shard_idx, self._per_shard - tail)
                yield np.concatenate([ids, pad])

    def _materialize(self, batch_ids):
        """Sample fetch + collate for one index array (the per-batch unit
        of work PrefetchLoader parallelizes)."""
        return self.collate_fn([self.dataset[int(j)] for j in batch_ids])

    def __iter__(self):
        for batch_ids in self._batch_indices():
            yield self._materialize(batch_ids)


# ---------------------------------------------------------------------------
# PrefetchLoader — background fetch+collate with a bounded queue
# ---------------------------------------------------------------------------

_DONE = object()   # producer sentinel: the underlying stream is exhausted


class _WorkerError:
    """Exception carrier: re-raised at the consumer, in order."""

    def __init__(self, exc):
        self.exc = exc


def _shutdown(stop: threading.Event, queues, threads) -> None:
    """Module-level so weakref.finalize holds no reference to the
    iterator: signal stop, drain the queues (unblocking producers stuck
    on a full put), and join the threads."""
    stop.set()
    for q in queues:
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
    me = threading.current_thread()
    for t in threads:
        if t is not me:  # GC may run the finalizer on a producer itself
            t.join(timeout=5.0)


def _bounded_put(stop: threading.Event, q: queue.Queue, item) -> bool:
    """Bounded put that aborts promptly on shutdown."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


# producer bodies are MODULE-LEVEL: a bound-method thread target would
# keep the iterator alive from its own worker threads (a cycle that
# defers GC teardown and can run the finalizer on a producer)

def _index_producer(stop, loader, tasks, worker_id, n_workers, q,
                    start=0):
    """`start` skips this worker's first `start` tasks — a RESPAWNED
    worker resumes at exactly the batch its predecessor died on (the
    consumer counts what each queue delivered), so the batch stream
    stays byte-identical through a worker death."""
    try:
        for i in range(worker_id + n_workers * start, len(tasks),
                       n_workers):
            if stop.is_set():
                return
            fault_point("dataloader.worker")
            if not _bounded_put(stop, q, loader._materialize(tasks[i])):
                return
    except BaseException as e:  # noqa: BLE001 — carried to the consumer
        _bounded_put(stop, q, _WorkerError(e))
        return
    _bounded_put(stop, q, _DONE)


def _stream_producer(stop, it, q):
    try:
        while not stop.is_set():
            fault_point("dataloader.worker")
            try:
                item = next(it)
            except StopIteration:
                break
            if not _bounded_put(stop, q, item):
                return
    except BaseException as e:  # noqa: BLE001
        _bounded_put(stop, q, _WorkerError(e))
        return
    _bounded_put(stop, q, _DONE)


class _PrefetchIterator:
    """One epoch of prefetched batches.  Two producer layouts:

    * index mode (the wrapped loader exposes _batch_indices/_materialize,
      i.e. DeepSpeedDataLoader): batch i is collated by worker
      i % num_workers, each worker feeding its own bounded queue; the
      consumer pops queue i % num_workers — parallel collate, identical
      order.
    * stream mode (any other iterable): iteration is inherently serial,
      so ONE producer thread pulls next() into a single bounded queue.
    """

    def __init__(self, loader, depth: int, num_workers: int,
                 max_respawns: int = WORKER_MAX_RESPAWNS,
                 respawn_backoff_s: float = WORKER_RESPAWN_BACKOFF_S):
        self._stop = threading.Event()
        self._exhausted = False
        indexable = (hasattr(loader, "_batch_indices")
                     and hasattr(loader, "_materialize"))
        workers = max(1, int(num_workers)) if indexable else 1
        depth = max(1, int(depth))
        if num_workers > 1 and not indexable:
            logger.warning(
                "PrefetchLoader: num_workers > 1 needs an index-protocol "
                "loader (DeepSpeedDataLoader); falling back to one "
                "producer thread for a generic iterable")
        # total buffered batches across workers stays ~depth
        per_q = max(1, -(-depth // workers))
        self._queues = [queue.Queue(maxsize=per_q) for _ in range(workers)]
        self._next_q = 0
        # worker-death recovery (index mode only: a stream iterator's
        # position dies with its thread): budget + doubling backoff,
        # plus the per-queue delivered counts a respawn resumes from
        self._loader = loader if indexable else None
        self._tasks = None
        self._n_workers = workers
        self._delivered = [0] * workers
        self._respawns_left = max(0, int(max_respawns))
        self._respawns_done = 0
        self._respawn_backoff_s = float(respawn_backoff_s)
        if indexable:
            # snapshot the epoch's batch order ONCE (cheap numpy) so every
            # worker agrees on the task list even if set_epoch races later
            tasks = list(loader._batch_indices())
            self._tasks = tasks
            self._threads = [
                threading.Thread(
                    target=_index_producer,
                    args=(self._stop, loader, tasks, w, workers,
                          self._queues[w]),
                    name=f"dstpu-prefetch-{w}", daemon=True)
                for w in range(workers)]
        else:
            self._threads = [threading.Thread(
                target=_stream_producer,
                args=(self._stop, iter(loader), self._queues[0]),
                name="dstpu-prefetch-0", daemon=True)]
        for t in self._threads:
            t.start()
        self._finalizer = weakref.finalize(
            self, _shutdown, self._stop, self._queues, self._threads)

    def _respawn_worker(self, w: int, exc: BaseException) -> bool:
        """Replace dead index-mode worker `w` with a fresh thread that
        resumes at the batch it died on.  Returns False when recovery
        is off the table (stream mode / budget exhausted / shut down)."""
        if self._loader is None or self._respawns_left <= 0 or \
                self._stop.is_set():
            return False
        self._respawns_left -= 1
        backoff = self._respawn_backoff_s * (2 ** self._respawns_done)
        self._respawns_done += 1
        COUNTERS.add("input.worker_respawns")
        logger.warning(
            f"PrefetchLoader: worker {w} died ({type(exc).__name__}: "
            f"{exc}); respawning at batch offset {self._delivered[w]} in "
            f"{backoff * 1000:.0f} ms ({self._respawns_left} respawn(s) "
            f"left)")
        time.sleep(backoff)
        t = threading.Thread(
            target=_index_producer,
            args=(self._stop, self._loader, self._tasks, w,
                  self._n_workers, self._queues[w]),
            kwargs={"start": self._delivered[w]},
            name=f"dstpu-prefetch-{w}r", daemon=True)
        self._threads[w] = t
        # the finalizer must join the CURRENT thread set
        self._finalizer.detach()
        self._finalizer = weakref.finalize(
            self, _shutdown, self._stop, self._queues, self._threads)
        t.start()
        return True

    # -- consumer ----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        q = self._queues[self._next_q]
        # observability: queue depth at pop time — how far ahead the
        # producers are running (input.queue_depth mean = bytes/calls)
        COUNTERS.add("input.queue_depth", sum(x.qsize()
                                              for x in self._queues))
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = q.get(timeout=0.5)
                break
            except queue.Empty:
                # producer may have died without a sentinel (interpreter
                # teardown): fail closed instead of hanging forever —
                # with one last non-blocking pop to close the window
                # where the item landed between timeout and the check
                if not any(t.is_alive() for t in self._threads):
                    try:
                        item = q.get_nowait()
                        break
                    except queue.Empty:
                        self.close()
                        raise StopIteration
        if item is _DONE:
            # round-robin invariant: the FIRST _DONE (always on the queue
            # owning the next batch index) means no later batch exists on
            # any other queue — drain and stop
            self.close()
            raise StopIteration
        if isinstance(item, _WorkerError):
            # a dead worker stops at its failed batch with everything
            # before it already delivered in order — respawn it to
            # RETRY that batch (bounded budget + doubling backoff) so
            # one transient worker death no longer kills training
            if self._respawn_worker(self._next_q, item.exc):
                return self.__next__()
            self.close()
            raise item.exc
        self._delivered[self._next_q] += 1
        self._next_q = (self._next_q + 1) % len(self._queues)
        return item

    def close(self):
        """Idempotent: stop producers, drain queues, join threads."""
        self._exhausted = True
        if self._finalizer.alive:
            self._finalizer()


class PrefetchLoader:
    """Run a loader's fetch+collate on background thread(s) with a
    bounded queue (`prefetch_depth` batches buffered, `num_workers`
    parallel collate threads when the wrapped loader supports it).

    Transparent: same batches, same order, same dtypes — `train_batch`
    parity with the unwrapped loader is pinned byte-exact in
    tests/test_data_pipeline.py.  Forwards len()/set_epoch so it can
    wrap DeepSpeedDataLoader under RepeatingLoader unchanged."""

    def __init__(self, loader: Iterable[Any], prefetch_depth: int = 2,
                 num_workers: int = 1,
                 max_respawns: int = WORKER_MAX_RESPAWNS,
                 respawn_backoff_s: float = WORKER_RESPAWN_BACKOFF_S):
        if prefetch_depth < 1:
            raise ValueError(
                f"PrefetchLoader: prefetch_depth must be >= 1, "
                f"got {prefetch_depth}")
        if num_workers < 1:
            raise ValueError(
                f"PrefetchLoader: num_workers must be >= 1, "
                f"got {num_workers}")
        self.loader = loader
        self.prefetch_depth = int(prefetch_depth)
        self.num_workers = int(num_workers)
        self.max_respawns = int(max_respawns)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self._live_iter: Optional[weakref.ReferenceType] = None

    def __len__(self):
        return len(self.loader)

    @property
    def epoch(self):
        """The wrapped loader's current epoch (RepeatingLoader seeds
        its wrap counter from this, so a cursor-restored loader keeps
        its shuffle schedule through the prefetch wrapper)."""
        return getattr(self.loader, "epoch", 0)

    def set_epoch(self, epoch: int):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self):
        # one live epoch at a time: iterating again tears the previous
        # iterator's threads down first (RepeatingLoader re-iters per epoch)
        prev = self._live_iter() if self._live_iter is not None else None
        if prev is not None:
            prev.close()
        it = _PrefetchIterator(self.loader, self.prefetch_depth,
                               self.num_workers,
                               max_respawns=self.max_respawns,
                               respawn_backoff_s=self.respawn_backoff_s)
        self._live_iter = weakref.ref(it)
        return it

    def close(self):
        prev = self._live_iter() if self._live_iter is not None else None
        if prev is not None:
            prev.close()


def timed_next(data_iter, tracer=None, step=None):
    """next(data_iter) with the host-blocked wall time recorded as
    `input.host_wait_ms` (stored in integer microseconds; the report
    renders ms).  Every engine-side pull from a host iterator goes
    through here so prefetch-on/off lanes measure the same thing.
    `tracer` (a monitor/tracing.py TraceRecorder, already gated by the
    engine's per-step sampling) additionally lands the same wait as an
    `input_wait` span on the trace timeline."""
    t0 = time.perf_counter()
    batch = next(data_iter)
    dt_us = int((time.perf_counter() - t0) * 1e6)
    COUNTERS.add("input.host_wait_ms", dt_us)
    if tracer is not None:
        tracer.add_complete("input_wait", "input", dur_us=dt_us,
                            **({} if step is None else {"step": step}))
    return batch
