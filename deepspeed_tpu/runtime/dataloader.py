"""Data loading: DeepSpeedDataLoader + RepeatingLoader.

Reference: deepspeed/runtime/dataloader.py:10,33 (torch DataLoader +
DistributedSampler). TPU-native redesign: single-controller JAX wants the
GLOBAL batch assembled on host and sharded over the mesh's data axis by the
engine, so the loader yields global numpy batches; in multi-process mode
each process reads its own slice (process_index-strided sampling), matching
DistributedSampler semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

import jax


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10-31).
    Advances the wrapped loader's epoch on each wrap so shuffling loaders
    re-shuffle instead of replaying one permutation."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._epoch = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self._epoch += 1
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(self._epoch)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _default_collate(items):
    """Stack a list of samples into a batch pytree of numpy arrays."""
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([it[i] for it in items])
                           for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class DeepSpeedDataLoader:
    """Batched, optionally shuffled, process-sharded loader
    (reference :33-101).

    dataset: any indexable (len + __getitem__) of samples (arrays, tuples,
    dicts). Yields GLOBAL per-process batches as numpy pytrees; the engine
    shards dim 0 over the data mesh axis.
    """

    def __init__(self, dataset, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 local_rank: int = -1, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True, data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        # single-controller: every process loads its slice of the global batch
        self.num_shards = (data_parallel_world_size
                           if data_parallel_world_size is not None
                           else jax.process_count())
        self.shard_id = (data_parallel_rank if data_parallel_rank is not None
                         else jax.process_index())
        self.epoch = 0
        if self.batch_size % max(1, self.num_shards) == 0:
            self._per_shard = self.batch_size // max(1, self.num_shards)
        else:
            raise ValueError(
                f"batch_size {batch_size} not divisible by data shards "
                f"{self.num_shards}")
        # every shard sees the SAME number of samples (wraparound padding,
        # DistributedSampler-style) — unequal counts would desync lockstep
        # SPMD processes and hang collectives
        import math

        self._samples_per_shard = math.ceil(len(dataset) /
                                            max(1, self.num_shards))
        self.len = self._samples_per_shard // self._per_shard
        if not self.drop_last and self._samples_per_shard % self._per_shard:
            self.len += 1

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.len

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        # DistributedSampler semantics: pad to equal length by wrapping, then
        # rank-strided slice — all shards yield the same batch count
        total = self._samples_per_shard * self.num_shards
        if total > n:
            order = np.concatenate([order, order[:total - n]])
        shard_idx = order[self.shard_id::self.num_shards]
        for i in range(0, len(shard_idx) - self._per_shard + 1, self._per_shard):
            batch_ids = shard_idx[i:i + self._per_shard]
            yield self.collate_fn([self.dataset[int(j)] for j in batch_ids])
        if not self.drop_last:
            tail = len(shard_idx) % self._per_shard
            if tail:
                batch_ids = shard_idx[len(shard_idx) - tail:]
                yield self.collate_fn([self.dataset[int(j)] for j in batch_ids])
