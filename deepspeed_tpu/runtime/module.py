"""Model protocol for the engine.

The reference wraps a torch.nn.Module whose __call__ returns the loss
(reference engine.py:959 self.module(*inputs)). JAX has no stateful modules,
so the engine's contract is a small protocol:

    class MyModel(TrainModule):
        def init(self, rng) -> params-pytree
        def loss(self, params, batch, rng=None, train=True) -> scalar
              (or (scalar, aux-dict))
        # optional:
        param_specs: pytree of jax.sharding.PartitionSpec for TP/SP layout
        def apply(self, params, batch, rng=None, train=False) -> outputs

Flax modules adapt via `from_flax`; plain functions via `from_functions`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class TrainModule:
    """Base class; subclasses implement init() and loss()."""

    #: optional pytree of PartitionSpec matching the params tree (TP/SP)
    param_specs = None

    def init(self, rng):
        raise NotImplementedError

    def loss(self, params, batch, rng=None, train=True):
        raise NotImplementedError

    def apply(self, params, batch, rng=None, train=False):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement apply()")


class _FnModule(TrainModule):
    def __init__(self, init_fn, loss_fn, apply_fn=None, param_specs=None):
        self._init = init_fn
        self._loss = loss_fn
        self._apply = apply_fn
        self.param_specs = param_specs

    def init(self, rng):
        return self._init(rng)

    def loss(self, params, batch, rng=None, train=True):
        return self._loss(params, batch, rng=rng, train=train)

    def apply(self, params, batch, rng=None, train=False):
        if self._apply is None:
            return super().apply(params, batch, rng=rng, train=train)
        return self._apply(params, batch, rng=rng, train=train)


def from_functions(init_fn: Callable, loss_fn: Callable,
                   apply_fn: Optional[Callable] = None,
                   param_specs: Any = None) -> TrainModule:
    """Build a TrainModule from pure functions.

    loss_fn signature: (params, batch, rng=None, train=True) -> loss[, aux].
    """
    return _FnModule(init_fn, loss_fn, apply_fn, param_specs)


def from_flax(module, loss_fn: Callable, example_batch=None,
              param_specs: Any = None) -> TrainModule:
    """Adapt a flax.linen Module. loss_fn receives (apply_fn, variables,
    batch, rng, train) and returns the scalar loss."""

    def init_fn(rng):
        if example_batch is None:
            raise ValueError("from_flax requires example_batch for init()")
        return module.init(rng, example_batch)

    def loss_wrap(params, batch, rng=None, train=True):
        return loss_fn(module.apply, params, batch, rng, train)

    def apply_fn(params, batch, rng=None, train=False):
        kwargs = {}
        if rng is not None:
            kwargs["rngs"] = {"dropout": rng}
        return module.apply(params, batch, **kwargs)

    return _FnModule(init_fn, loss_wrap, apply_fn, param_specs)
