"""Live candidate probing on a RUNNING engine.

A probe applies a candidate comm config through the same rebuild path
`engine.allreduce_gradients(bucket_size=...)` and the PR-10 runtime
demotion already exercise (BucketPlan + overlap + StepBuilder program
rebuild), then times a few steps — but on COPIES of the training state:

* params/optimizer/scaler are device-copied once per probe (one fused
  jitted copy program, the async-checkpoint snapshot trick), so the
  donated step programs invalidate probe buffers, never the run's
* the probe batch is the last real batch the engine trained on
  (`engine._autotune_batch`, stashed by the forward paths), replayed
  with a FIXED rng — probe steps never consume training data and never
  advance the engine's rng stream
* probe dispatches go through the RAW jitted programs (`CountedFn.fn`,
  the flops-analysis discipline), so `grad_wire.*` per-dispatch
  counters are not bumped by probe traffic; the probe's own cost lands
  in `autotune.probes`
* afterwards the previous build products (plan, step fns, overlap
  mode) are restored BY REFERENCE — the incumbent config's compiled
  programs come back without a recompile

The engine's global_steps / micro_steps / rng / scheduler / monitor
are untouched: a probed run continues bitwise as if the probe never
happened (pinned in tests/test_autotune.py)."""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ...utils.logging import log_dist
from .space import Candidate

# build products swapped wholesale around a probe; the overlap EXCHANGE
# is deliberately absent — it survives rebuilds by design (engine.
# _build_overlap) and is reused by later probes/swaps
_BUILD_ATTRS = ("bucket_plan", "_overlap_mode", "_step_fns",
                "_overlap_payload_nbytes", "_overlap_matrix_sharding",
                "_qwz_overlap")


def capture_build(engine) -> Dict[str, Any]:
    state = {attr: getattr(engine, attr, None) for attr in _BUILD_ATTRS}
    state["comm_config"] = engine._config.comm_config
    return state


def restore_build(engine, state: Dict[str, Any]) -> None:
    engine._config.comm_config = state["comm_config"]
    for attr in _BUILD_ATTRS:
        setattr(engine, attr, state[attr])
    engine._overlap_pending = []


def apply_candidate(engine, candidate: Candidate) -> None:
    """Re-parse the candidate's comm fragment through the REAL config
    validator (relative to the current config: bucket size, quant block
    and the mesh's factorization are inherited where unspecified), then
    rebuild plan/overlap/step programs — the allreduce_gradients retune
    path, generalized to every live knob."""
    from .. import constants as c
    from ..config import DeepSpeedCommConfig

    if candidate.scope != "live":
        raise ValueError(
            f"candidate {candidate.name!r} is scope={candidate.scope!r}: "
            "the data-axis factorization is the mesh layout and is fixed "
            "at initialize() — rebuild-scope candidates only probe "
            "through an engine factory (tools/autotune_bench.py)")
    cc_old = engine._config.comm_config
    merged = dict(candidate.comm)
    merged.setdefault("reduce_bucket_size", cc_old.reduce_bucket_size)
    merged.setdefault("quant_block_size", cc_old.quant_block_size)
    outer = engine.mesh_info.data_outer_size
    if outer > 1:
        merged.setdefault("hierarchy", {"outer": int(outer)})
    pd: Dict[str, Any] = {"comm": merged}
    if cc_old.fp32_allreduce:
        pd[c.FP32_ALLREDUCE] = True
    new_cc = DeepSpeedCommConfig(pd, engine._config.zero_config,
                                 world_size=engine.dp_world_size)
    # process-global selections made at initialize() carry over: the
    # MoE wire is installed before params placement, and the overlap
    # transport knobs are fabric properties, not search knobs
    new_cc.moe = cc_old.moe
    for k in ("overlap_timeout_ms", "overlap_reconnect_attempts",
              "overlap_reconnect_window_ms", "overlap_keepalive_ms"):
        setattr(new_cc, k, getattr(cc_old, k))

    # settle in-flight overlapped exchanges against the CURRENT plan's
    # combine before it is replaced (the allreduce_gradients invariant:
    # never drop already-dispatched micro gradients)
    engine._drain_overlap()
    engine._config.comm_config = new_cc
    engine.bucket_plan = engine._build_bucket_plan()
    engine._overlap_mode = engine._resolve_overlap()
    engine._build_overlap()
    engine._step_fns = engine._build_step_fns()
    engine._register_exchange_watchdog()
    log_dist(f"autotune: applied {candidate.describe()}", ranks=[0])


class EngineProber:
    """Times candidates on a live engine without touching training
    state.  Construct at a step boundary (no pending micro gradients);
    `probe()` restores the incumbent build before returning."""

    def __init__(self, engine, steps: int = 2, warmup: int = 1):
        if getattr(engine, "_overlap_pending", None):
            raise RuntimeError(
                "autotune probe: in-flight overlapped exchanges — probes "
                "run at step boundaries only")
        if engine._qwz_overlap is not None or engine._offload is not None \
                or engine._infinity is not None:
            raise RuntimeError(
                "autotune live probing covers the device step paths "
                "(stage < 3, no offload/Infinity) — tune those runs "
                "through the engine-factory search instead")
        self.engine = engine
        self.steps = int(steps)
        self.warmup = int(warmup)
        self._copy_fn = None
        batch = getattr(engine, "_autotune_batch", None)
        if batch is None:
            raise RuntimeError(
                "autotune probe: no probe batch stashed yet — run at "
                "least one forward()/train_batch() first (or pass "
                "batch= to autotune_search)")
        self.batch = batch

    # -- state copies ---------------------------------------------------

    def _copies(self):
        import jax
        import jax.numpy as jnp

        # ONE jitted copy program per prober: jit caches by function
        # identity, so a per-call lambda would retrace every probe
        copy = self._copy_fn
        if copy is None:
            copy = self._copy_fn = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t))
        eng = self.engine
        return (copy(eng._params), copy(eng._opt_state),
                copy(eng._scaler_state))

    # -- one probe ------------------------------------------------------

    def probe(self, candidate: Candidate) -> Dict[str, Any]:
        """Apply, time `steps` real engine steps on state copies,
        restore.  Returns {"step_ms", "exposed_ms", "loss", ...}."""
        eng = self.engine
        saved = capture_build(eng)
        try:
            apply_candidate(eng, candidate)
            return self._time_steps()
        finally:
            restore_build(eng, saved)

    def probe_current(self) -> Dict[str, Any]:
        """Time the INCUMBENT config with the same harness — the
        baseline a retune decision compares against (same probe batch,
        same step count, same raw-program dispatch)."""
        return self._time_steps()

    # -- the composition-aware runner -----------------------------------

    def _time_steps(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        eng = self.engine
        fns = eng._step_fns
        gas = eng.gradient_accumulation_steps()
        params, opt, scaler = self._copies()
        rng = jax.random.PRNGKey(0)
        theta = jnp.asarray(1.0, jnp.float32)
        cur_lr = eng._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        batch = self.batch
        stacked = None
        if "full_scan" in fns:
            stacked = eng._shard_batch_stacked(jax.tree_util.tree_map(
                lambda x: jnp.stack([x] * gas), batch))
            rngs = jax.random.split(rng, gas)

        times = []
        exposed_us_total = 0
        loss = None
        for i in range(self.warmup + self.steps):
            t0 = time.perf_counter()
            exposed_us = 0
            if "full" in fns:
                (params, opt, scaler, loss, _ovf, _gn, _ex) = \
                    fns["full"].fn(params, opt, scaler, batch, rng, lr,
                                   theta)
            elif "full_scan" in fns:
                (params, opt, scaler, loss, _ovf, _gn, _ex) = \
                    fns["full_scan"].fn(params, opt, scaler, stacked,
                                        rngs, lr, theta)
            elif "grads" in fns:
                acc = eng._zero_grad_acc()
                pending = []
                for _m in range(gas):
                    loss, payload = fns["grads"].fn(
                        params, batch, rng, scaler["cur_scale"], theta)
                    pending.append(eng._overlap_submit(payload))
                jax.block_until_ready(loss)
                for ticket in pending:
                    before = ticket.wait_us
                    mat = ticket.wait(eng._overlap_timeout_s)
                    exposed_us += ticket.wait_us - before
                    mdev = jax.device_put(mat, eng._overlap_matrix_sharding)
                    acc = fns["combine"].fn(acc, mdev)
                    eng._retire_ticket(ticket)
                (params, opt, scaler, _z, _ovf, _gn, _ex) = \
                    fns["apply"].fn(params, opt, scaler, acc, lr)
            else:
                acc = eng._zero_grad_acc()
                for _m in range(gas):
                    loss, acc, _ex = fns["micro"].fn(
                        params, acc, batch, rng, scaler["cur_scale"],
                        theta)
                (params, opt, scaler, _z, _ovf, _gn, _ex) = \
                    fns["apply"].fn(params, opt, scaler, acc, lr)
            jax.block_until_ready(loss)
            if i >= self.warmup:
                times.append(time.perf_counter() - t0)
                exposed_us_total += exposed_us
        times.sort()
        step_ms = times[len(times) // 2] * 1e3
        return {
            "step_ms": round(step_ms, 3),
            "exposed_ms": round(exposed_us_total / 1e3
                                / max(1, self.steps), 3),
            "loss": float(loss),
            "gas": gas,
        }
