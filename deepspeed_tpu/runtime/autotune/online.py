"""Sustained-regression detection for the online retune loop.

The detector watches two signals per optimizer step:

* wall ms/step (measured boundary-to-boundary by the runtime)
* exposed-wire µs/step creep (`grad_wire.exposed_ms` deltas — the
  overlap wire's non-hidden remainder; a healthy overlapped run keeps
  this near zero, so creep here flags a degrading exchange before the
  step time alone would)

A baseline is the median of the first `baseline_steps` observations
after (re)arming.  A regression is SUSTAINED when `window` consecutive
observations exceed `threshold` x baseline (or the exposed signal
exceeds `exposed_threshold_ms` for the window) — a single slow step
(GC pause, checkpoint, compile) never triggers.  After a retune the
caller `reset()`s: the detector re-baselines under the new config and
holds off for `cooldown_steps` so one fault burst cannot chain
retunes."""

from __future__ import annotations

from collections import deque
from typing import Optional


class RegressionDetector:
    def __init__(self, window: int = 5, baseline_steps: int = 5,
                 threshold: float = 1.5,
                 exposed_threshold_ms: float = 0.0,
                 cooldown_steps: int = 20):
        if window < 1 or baseline_steps < 1:
            raise ValueError("window and baseline_steps must be >= 1, got "
                             f"{window}/{baseline_steps}")
        if threshold <= 1.0:
            raise ValueError(
                f"threshold must be > 1.0 (a ratio over baseline), got "
                f"{threshold}")
        self.window = int(window)
        self.baseline_steps = int(baseline_steps)
        self.threshold = float(threshold)
        self.exposed_threshold_ms = float(exposed_threshold_ms)
        self.cooldown_steps = int(cooldown_steps)
        self.reset(cooldown=False)

    def reset(self, cooldown: bool = True) -> None:
        """Re-arm: forget the baseline (the config just changed), and
        optionally hold off `cooldown_steps` before observing again."""
        self._baseline: Optional[float] = None
        self._base_buf: deque = deque(maxlen=self.baseline_steps)
        self._hot_ms = 0       # consecutive step-time breaches
        self._hot_exposed = 0  # consecutive exposed-creep breaches
        self._cooldown = self.cooldown_steps if cooldown else 0

    @property
    def baseline_ms(self) -> Optional[float]:
        return self._baseline

    def observe(self, step_ms: float, exposed_ms: float = 0.0) -> bool:
        """Feed one step's signals; True = sustained regression (the
        caller should retune and reset())."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return False
        if self._baseline is None:
            self._base_buf.append(float(step_ms))
            if len(self._base_buf) == self.baseline_steps:
                ordered = sorted(self._base_buf)
                self._baseline = ordered[len(ordered) // 2]
            return False
        if step_ms > self.threshold * self._baseline:
            self._hot_ms += 1
        else:
            self._hot_ms = 0
        if self.exposed_threshold_ms > 0.0 and \
                exposed_ms > self.exposed_threshold_ms:
            self._hot_exposed += 1
        else:
            self._hot_exposed = 0
        return (self._hot_ms >= self.window
                or self._hot_exposed >= self.window)

    def describe_trigger(self, step_ms: float, exposed_ms: float) -> str:
        if self._hot_exposed >= self.window:
            return (f"exposed wire creep: {exposed_ms:.2f} ms/step > "
                    f"{self.exposed_threshold_ms:.2f} ms for "
                    f"{self._hot_exposed} consecutive steps")
        base = self._baseline or 0.0
        return (f"step time regression: {step_ms:.1f} ms/step > "
                f"{self.threshold:.2f} x baseline {base:.1f} ms for "
                f"{self._hot_ms} consecutive steps")
