"""(model shape, mesh, fabric) fingerprints for the winner cache.

ZeRO++ and the Frontier low-bandwidth study both show the winning
wire/partitioning config is a function of the FABRIC — so a cached
winner is only trustworthy for the exact (model shape, mesh layout,
fabric) it was probed on.  The fingerprint captures all three; the
cache treats it as an opaque equality key and `fingerprint_diff` names
what changed so a stale hit re-probes LOUDLY, never silently."""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List


def make_fingerprint(**sections) -> Dict[str, Any]:
    """Assemble a fingerprint from named sections (plain JSON values).
    A stable digest is attached for log lines and filenames; equality
    checks compare the full dict, not the digest."""
    fp = {k: sections[k] for k in sorted(sections)}
    blob = json.dumps(fp, sort_keys=True, default=str).encode()
    fp["digest"] = hashlib.md5(blob).hexdigest()[:16]
    return fp


def fingerprint_diff(a: Dict[str, Any], b: Dict[str, Any]) -> List[str]:
    """Dotted paths that differ between two fingerprints (digest
    excluded) — the 'what changed' a stale-cache log line names."""
    diffs: List[str] = []

    def walk(x, y, path):
        if isinstance(x, dict) and isinstance(y, dict):
            for k in sorted(set(x) | set(y)):
                if k == "digest" and not path:
                    continue
                walk(x.get(k), y.get(k), path + [str(k)])
        elif x != y:
            diffs.append(".".join(path) or "<root>")

    walk(a or {}, b or {}, [])
    return diffs


def fabric_section() -> Dict[str, Any]:
    """The fabric half of a fingerprint: backend, device kind, device
    count.  Kernel winners are keyed on exactly this — a Pallas-vs-jnp
    measurement transfers across shapes on the same fabric but never
    across a backend or device-kind change."""
    import jax

    devices = jax.devices()
    return {"backend": jax.default_backend(),
            "device_kind": devices[0].device_kind if devices else "?",
            "devices": len(devices)}


def kernel_fingerprint(op: str, shape=None, dtype=None) -> Dict[str, Any]:
    """Fingerprint one kernel-scope probe: which registered op was
    measured, the representative shape/dtype it was lapped on, and the
    fabric.  `registry.winner_for` honours a recorded winner only while
    the `fabric` section still matches `fabric_section()` — the same
    stale-loudly contract as the engine/serve winner caches."""
    return make_fingerprint(
        kernel={"op": str(op),
                "shape": list(shape) if shape is not None else None,
                "dtype": str(dtype) if dtype is not None else None},
        fabric=fabric_section(),
    )


def _model_section(params) -> Dict[str, Any]:
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(params)
    n_params = 0
    shape_hash = hashlib.md5()
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", "?"))
        n_params += int(np.prod(shape, dtype=np.int64)) if shape else 1
        shape_hash.update(f"{shape}:{dtype};".encode())
    return {"n_params": int(n_params), "n_leaves": len(leaves),
            "shapes": shape_hash.hexdigest()[:16]}


def serve_fingerprint(engine) -> Dict[str, Any]:
    """Fingerprint a live ServeEngine for the serve-scope winner cache:
    model shape, serving geometry (pool/block/batch sizing), the KV
    storage + speculation knobs being probed, and the fabric.  Same
    contract as `engine_fingerprint`: a cached serve winner is only
    trustworthy for the exact (model, geometry, fabric) it was lapped
    on — a different block size or device kind re-probes loudly."""
    c = engine.config
    return make_fingerprint(
        model=_model_section(engine.params),
        geometry={"block_size": c.block_size,
                  "num_blocks": c.num_blocks,
                  "max_batch": c.max_batch,
                  "prefill_chunk": c.prefill_chunk,
                  "max_seq_len": engine.max_seq_len,
                  "admission": c.admission},
        serving={"kv_dtype": engine.kv.quant_wire or
                 (str(c.kv_dtype) if c.kv_dtype is not None else "dense"),
                 "draft_len": int(c.draft_len),
                 "spec_ngram": int(c.spec_ngram),
                 "quantized_weights": c.quant_mode,
                 "prefix_cache": bool(c.prefix_cache),
                 "prefix_min_match_blocks": int(c.prefix_min_match_blocks),
                 "session_ttl_s": float(c.session_ttl_s)},
        fabric=fabric_section(),
    )


def engine_fingerprint(engine) -> Dict[str, Any]:
    """Fingerprint a live engine: model shape (leaf shapes/dtypes),
    batch geometry, precision/stage (the dtype config), the mesh layout
    including its data-axis factorization, and the fabric (backend,
    device kind, process topology)."""
    import jax

    mi = engine.mesh_info
    cfg = engine._config
    try:
        processes = jax.process_count()
    except Exception:
        processes = 1
    return make_fingerprint(
        model=_model_section(engine._params),
        batch={"micro": cfg.train_micro_batch_size_per_gpu,
               "gas": cfg.gradient_accumulation_steps,
               "train_batch": cfg.train_batch_size},
        dtypes={"precision": cfg.precision,
                "quantized_weights":
                    getattr(cfg.zero_config, "quantized_weights", None)},
        zero={"stage": cfg.zero_optimization_stage},
        mesh={"data": mi.axis_size("data"),
              "model": mi.axis_size("model"),
              "pipe": mi.axis_size("pipe"),
              "seq": mi.axis_size("seq"),
              "data_outer": mi.data_outer_size,
              "data_inner": mi.data_inner_size},
        fabric=dict(fabric_section(),
                    processes=processes,
                    topology="multi-process" if processes > 1
                             else "single-process"),
    )
