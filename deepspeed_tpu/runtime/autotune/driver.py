"""The generic search driver: a budgeted, failure-tolerant probe loop.

Deliberately knows nothing about engines — `probe_fn(candidate)` is any
callable returning a metrics dict, so the same driver serves bench.py's
model-shape search (candidates are (size, micro, remat) tuples probed
by building throwaway engines), tools/autotune_bench.py's synthetic
cost surface, and the engine runtime's live StepBuilder probes.

Probe discipline (inherited from bench.py's state machine, now owned
here once):

* a probe is OPTIONAL: any failure (OOM, lowering error, transport
  fault) records the candidate as failed and moves on — the search
  must never die on a probe when the incumbent config would have run
* the wall budget is checked BEFORE each probe; exhausted means the
  remaining candidates record as skipped, and a search with skipped or
  failed probes reports `complete=False` so callers never pin a future
  run to a degraded probe set
* every probe's wall time lands in `autotune.probes` (bytes = µs, the
  ckpt.stall_ms convention)

The default scorer combines achieved throughput with the monitor-side
exposure counters: two candidates within measurement noise on ms/step
rank by how much of their time is EXPOSED wire/host wait (the creep the
online retuner watches), so the search prefers configs whose cost is
hidden behind compute."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ...monitor.counters import COUNTERS
from ...utils.logging import logger

# exposure metrics folded into the default score when a probe reports
# them (all in milliseconds per step, like `step_ms`)
EXPOSURE_KEYS = ("exposed_ms", "host_wait_ms", "a2a_exposed_ms")


def combine_score(metrics: Dict[str, Any],
                  exposure_weight: float = 0.5) -> float:
    """Higher is better.  Throughput first: `tokens_s` when the probe
    reports it, else 1000/step_ms (steps/s).  The exposure counters
    then discount the score by the fraction of step time the host spent
    visibly blocked — a config that is fast BECAUSE its wire hides
    beats one equally fast with the wire on the critical path, and the
    gap widens exactly when a degrading fabric would widen it."""
    if metrics.get("tokens_s"):
        base = float(metrics["tokens_s"])
    elif metrics.get("step_ms"):
        base = 1000.0 / float(metrics["step_ms"])
    else:
        raise ValueError(
            "probe metrics need 'tokens_s' or 'step_ms' to score; got "
            f"keys {sorted(metrics)}")
    step_ms = float(metrics.get("step_ms") or 0.0)
    if step_ms <= 0.0:
        return base
    exposed = sum(float(metrics.get(k) or 0.0) for k in EXPOSURE_KEYS)
    frac = min(1.0, exposed / step_ms)
    return base * (1.0 - exposure_weight * frac)


class ProbeResult:
    """One probed (or skipped/failed) candidate."""

    __slots__ = ("candidate", "metrics", "score", "error", "oom",
                 "skipped", "elapsed_s")

    def __init__(self, candidate, metrics=None, score=None, error=None,
                 oom=False, skipped=None, elapsed_s=0.0):
        self.candidate = candidate
        self.metrics = metrics
        self.score = score
        self.error = error
        self.oom = oom
        self.skipped = skipped
        self.elapsed_s = elapsed_s

    @property
    def ok(self) -> bool:
        return self.metrics is not None and self.error is None \
            and self.skipped is None

    def _candidate_name(self) -> str:
        name = getattr(self.candidate, "name", None)
        return name if name is not None else str(self.candidate)

    def trace(self) -> Dict[str, Any]:
        """Ledger/artifact row for this probe."""
        row: Dict[str, Any] = {"candidate": self._candidate_name()}
        if self.skipped is not None:
            row["skipped"] = self.skipped
        elif self.error is not None:
            row["failed"] = self.error
            if self.oom:
                row["oom"] = True
        else:
            row.update({k: (round(v, 3) if isinstance(v, float) else v)
                        for k, v in (self.metrics or {}).items()})
            if self.score is not None:
                row["score"] = round(float(self.score), 4)
        return row


def _is_oom(exc: BaseException) -> bool:
    s = str(exc)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s


class SearchDriver:
    """Budgeted probe loop over candidates; keeps every result for the
    trace the cache/ledger/artifact records."""

    def __init__(self, probe_fn: Callable[[Any], Dict[str, Any]],
                 score_fn: Callable[[Dict[str, Any]], float] = combine_score,
                 budget_s: Optional[float] = None):
        self.probe_fn = probe_fn
        self.score_fn = score_fn
        self.budget_s = budget_s
        self._t0 = time.perf_counter()
        self.results: List[ProbeResult] = []

    # -- budget ----------------------------------------------------------

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def budget_exhausted(self) -> bool:
        return self.budget_s is not None and self.elapsed_s() > self.budget_s

    # -- probing ---------------------------------------------------------

    def probe(self, candidate) -> ProbeResult:
        """Probe one candidate (budget- and failure-guarded); records
        and returns the result."""
        if self.budget_exhausted():
            r = ProbeResult(candidate, skipped="budget")
            self.results.append(r)
            return r
        t0 = time.perf_counter()
        try:
            metrics = self.probe_fn(candidate)
            r = ProbeResult(candidate, metrics=metrics,
                            score=self.score_fn(metrics),
                            elapsed_s=time.perf_counter() - t0)
        except Exception as exc:
            r = ProbeResult(candidate, error=type(exc).__name__,
                            oom=_is_oom(exc),
                            elapsed_s=time.perf_counter() - t0)
            logger.warning(
                f"autotune probe {r._candidate_name()} failed "
                f"({type(exc).__name__}: {exc}) — candidate skipped, "
                "search continues")
        COUNTERS.add("autotune.probes", int(r.elapsed_s * 1e6), calls=1)
        self.results.append(r)
        return r

    def search(self, candidates) -> Optional[ProbeResult]:
        """Probe every candidate; return the best-scoring successful
        result (None when nothing probed cleanly)."""
        best: Optional[ProbeResult] = None
        for cand in candidates:
            r = self.probe(cand)
            if r.ok and (best is None or r.score > best.score):
                best = r
        return best

    # -- outcome ---------------------------------------------------------

    @property
    def complete(self) -> bool:
        """True when no probe failed or was budget-skipped — the only
        state a winner may be CACHED from (bench.py's 'never pin future
        rounds to a degraded probe' rule, now shared)."""
        return all(r.ok for r in self.results)

    def trace(self) -> List[Dict[str, Any]]:
        return [r.trace() for r in self.results]
