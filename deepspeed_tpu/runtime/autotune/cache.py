"""The persisted winner cache.

Two on-disk shapes, one API:

* mode="single" — the historical `bench_artifacts/autotune.json` shape:
  ONE flat entry `{**winner, "probes": [...], "fingerprint": {...}}`.
  bench.py keeps writing/reading this exact format through the shared
  driver, so committed bench artifacts stay comparable across rounds.
* mode="map" — the engine driver's shape: entries keyed by fingerprint
  digest, each `{"fingerprint", "winner", "trace", "written_unix"}`, so
  one file serves many (model, mesh, fabric) combinations.

Invalidation contract (tested): a lookup whose stored fingerprint
differs from the caller's NEVER pins the run — it logs WHAT changed
(`fingerprint_diff`) and reports a miss so the caller re-probes.  An
unreadable/foreign file is a miss too (a corrupt cache must never be
worth more than a probe)."""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ...utils.logging import logger
from .fingerprint import fingerprint_diff


class WinnerCache:
    def __init__(self, path: Optional[str], mode: str = "map"):
        if mode not in ("map", "single"):
            raise ValueError(
                f"WinnerCache mode must be 'map' or 'single', got {mode!r}")
        self.path = path
        self.mode = mode

    # -- IO ------------------------------------------------------------

    def _read(self) -> Optional[Dict[str, Any]]:
        if not self.path or not os.path.exists(self.path):
            return None
        try:
            with open(self.path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else None
        except Exception as e:
            logger.warning(
                f"autotune cache {self.path}: unreadable ({type(e).__name__}:"
                f" {e}) — treating as a miss and re-probing")
            return None

    def _write(self, data: Dict[str, Any]) -> None:
        if not self.path:
            return
        try:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=2, sort_keys=True, default=str)
            os.replace(tmp, self.path)
        except OSError as e:  # read-only checkout: probing still worked
            logger.warning(f"autotune cache {self.path}: write failed "
                           f"({e}); the winner applies but is not cached")

    # -- lookup/store ----------------------------------------------------

    def lookup(self, fingerprint: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """The cached winner for this exact fingerprint, or None.  A
        present-but-mismatched entry logs the changed fingerprint
        components and misses — the loud re-probe the invalidation
        tests pin."""
        data = self._read()
        if data is None:
            return None
        if self.mode == "single":
            stored = data.get("fingerprint")
            if stored == fingerprint:
                return data
            if stored is not None:
                changed = fingerprint_diff(stored, fingerprint)
                logger.warning(
                    "autotune cache: stale fingerprint (changed: "
                    f"{', '.join(changed) or 'structure'}) — cached winner "
                    "discarded, re-probing")
            return None
        digest = fingerprint.get("digest", "")
        entry = (data.get("entries") or {}).get(digest)
        if entry is None:
            # same digest-prefix collisions aside, also scan for a near
            # miss so the log can say WHAT invalidated the closest entry
            entries = list((data.get("entries") or {}).values())
            if entries:
                nearest = min(
                    entries,
                    key=lambda e: len(fingerprint_diff(
                        e.get("fingerprint") or {}, fingerprint)))
                changed = fingerprint_diff(
                    nearest.get("fingerprint") or {}, fingerprint)
                logger.warning(
                    "autotune cache: no winner for this (model, mesh, "
                    f"fabric) fingerprint (nearest entry differs in: "
                    f"{', '.join(changed) or 'structure'}) — probing")
            return None
        if entry.get("fingerprint") != fingerprint:
            changed = fingerprint_diff(entry.get("fingerprint") or {},
                                       fingerprint)
            logger.warning(
                "autotune cache: digest matched but the fingerprint "
                f"differs (changed: {', '.join(changed) or 'structure'}) — "
                "cached winner discarded, re-probing")
            return None
        return entry

    def store(self, fingerprint: Dict[str, Any], winner: Dict[str, Any],
              trace: Optional[List[Dict[str, Any]]] = None) -> None:
        if not self.path:
            return
        if self.mode == "single":
            self._write({**winner, "probes": trace or [],
                         "fingerprint": fingerprint})
            return
        data = self._read() or {}
        entries = data.get("entries") or {}
        entries[fingerprint.get("digest", "")] = {
            "fingerprint": fingerprint, "winner": winner,
            "trace": trace or [], "written_unix": time.time()}
        self._write({"schema_version": 1, "entries": entries})
