"""The engine attachment: search/retune orchestration.

One AutotuneRuntime hangs off each engine (config block "autotune").
It owns:

* `search()` — the fingerprinted, cached config search: winner-cache
  lookup first (a hit applies with ZERO probes and counts
  `autotune.cache_hits`; a fingerprint mismatch re-probes LOUDLY), else
  a budgeted live probe sweep over the legal candidate space, the
  winner applied through the StepBuilder rebuild and stored back keyed
  by (model shape, mesh, fabric)
* the ONLINE retune loop — `on_step_boundary()` (called from the
  engine's step() tail) feeds wall ms/step + exposed-wire creep into a
  RegressionDetector; a sustained regression re-probes a bounded
  1-knob neighborhood of the incumbent at the next boundary and swaps
  the winning program in live.  Online swaps default to
  numerics-safe candidates only (`online.safe_only`), so the loss
  stream stays BITWISE across a swap — the parity the chaos lane pins.
* multi-process agreement — step timing jitters per rank, so on a
  multi-process mesh the trigger verdict and the swap decision both
  ride a hostwire allgather (every `online.check_every` boundaries);
  every rank then probes the same candidates in the same order and
  applies rank 0's decision.  Divergent per-rank swaps would deadlock
  the next collective; this is the same lockstep discipline as the
  PR-10 demotion barrier, at the cadence of a KV allgather.
* the `autotune.jsonl` ledger (rank 0, monitor run dir) the report
  renders, and the `autotune.*` counters.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ...monitor.counters import COUNTERS
from ...utils.logging import log_dist, logger
from .cache import WinnerCache
from .driver import SearchDriver
from .fingerprint import engine_fingerprint
from .online import RegressionDetector
from .probe import EngineProber, apply_candidate
from .space import (Candidate, current_candidate, generate_candidates,
                    neighborhood)


class _Consensus:
    """Rank-agreement over the hostwire KV: single-process short-
    circuits, multi-process allgathers a small JSON payload.  Collective
    contract: every rank must call agree() at the same boundary."""

    def __init__(self, tag: str = "dstpu-autotune"):
        try:
            import jax

            self.world = jax.process_count()
        except Exception:
            self.world = 1
        self._wire = None
        self.tag = tag

    def agree(self, obj: Any) -> List[Any]:
        if self.world <= 1:
            return [obj]
        if self._wire is None:
            from ..comm.hostwire import HostWire

            self._wire = HostWire(tag=self.tag)
        payloads = self._wire.allgather_bytes(
            json.dumps(obj, default=str).encode())
        return [json.loads(p.decode()) for p in payloads]


class AutotuneRuntime:
    def __init__(self, engine, config):
        self.engine = engine
        self.config = config
        self.detector = RegressionDetector(
            window=config.online_window,
            baseline_steps=config.online_baseline_steps,
            threshold=config.online_threshold,
            exposed_threshold_ms=config.online_exposed_threshold_ms,
            cooldown_steps=config.online_cooldown_steps)
        self._consensus = _Consensus()
        self._last_boundary_t: Optional[float] = None
        self._exposed_snap = self._exposed_us()
        self._local_trigger: Optional[str] = None
        self.retunes = 0
        self._ledger_path = self._resolve_ledger_path()

    # -- plumbing --------------------------------------------------------

    def _resolve_ledger_path(self) -> Optional[str]:
        if self.config.ledger_path:
            return self.config.ledger_path
        rm = getattr(self.engine, "run_monitor", None)
        if rm is not None:
            return os.path.join(rm.run_dir, "autotune.jsonl")
        return None

    def _rank(self) -> int:
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    def ledger(self, event: str, **fields) -> None:
        """Append one ledger row (rank 0; the report renders these)."""
        if self._ledger_path is None or self._rank() != 0:
            return
        row = {"t": time.time(), "event": event,
               "step": self.engine.global_steps, **fields}
        try:
            with open(self._ledger_path, "a") as f:
                f.write(json.dumps(row, default=str) + "\n")
        except OSError as e:
            logger.warning(f"autotune ledger {self._ledger_path}: {e}")

    @staticmethod
    def _exposed_us() -> int:
        return COUNTERS.snapshot().get("grad_wire.exposed_ms", (0, 0))[1]

    # -- the candidate space ---------------------------------------------

    def candidates(self, live_only: bool = True,
                   safe_only: bool = False) -> List[Candidate]:
        eng = self.engine
        cands, rejected = generate_candidates(
            dp=eng.dp_world_size,
            stage=eng._config.zero_optimization_stage,
            current_outer=eng.mesh_info.data_outer_size,
            wire_dtypes=self.config.wire_dtypes,
            overlap=((False, True) if self.config.include_overlap
                     else (False,)),
            bucket_sizes=self.config.bucket_sizes)
        if rejected:
            COUNTERS.add("autotune.rejected", calls=rejected)
        if live_only:
            cands = [c for c in cands if c.scope == "live"]
        if safe_only:
            cands = [c for c in cands if c.safe_numerics]
        return cands

    # -- the fingerprinted search ----------------------------------------

    def search(self, batch=None, candidates: Optional[List[Candidate]] = None,
               force: bool = False,
               cache_path: Optional[str] = None) -> Dict[str, Any]:
        """Search the live candidate space and (by default) apply the
        winner.  Cache hit => ZERO probes.  Returns the outcome dict
        ({"winner", "cached", "probes", "trace", ...})."""
        eng = self.engine
        if batch is not None:
            eng._autotune_batch = eng._shard_batch(batch)
        fp = engine_fingerprint(eng)
        cache = WinnerCache(cache_path or self.config.cache_path,
                            mode="map")
        if not force:
            hit = cache.lookup(fp)
            if self._consensus.world > 1:
                # lockstep the cache decision: rank 0's lookup rules —
                # a torn/missing cache file on ONE rank must not send
                # it probing (collective step programs) while the
                # others early-return on their hit
                agreed = self._consensus.agree(
                    None if hit is None else hit["winner"])[0]
                hit = None if agreed is None else {"winner": agreed}
            if hit is not None:
                winner = hit["winner"]
                cand = Candidate(
                    name=winner["name"], comm=winner["comm"],
                    stage=winner.get("stage", 0), scope="live",
                    safe_numerics=bool(winner.get("safe_numerics", False)))
                COUNTERS.add("autotune.cache_hits", calls=1)
                self.ledger("cache_hit", candidate=cand.name,
                            fingerprint=fp["digest"])
                log_dist(
                    f"autotune: cache hit for fingerprint {fp['digest']} "
                    f"-> {cand.describe()} (zero probes)", ranks=[0])
                if self.config.apply_winner:
                    self._apply(cand, reason="cached winner")
                return {"winner": cand.name, "candidate": cand,
                        "cached": True, "probes": 0, "trace": [],
                        "fingerprint": fp}
        cands = candidates if candidates is not None else self.candidates()
        incumbent = current_candidate(eng)
        prober = EngineProber(eng, steps=self.config.probe_steps,
                              warmup=self.config.probe_warmup)
        driver = self._make_driver(prober)
        baseline = prober.probe_current()
        best = self._search(driver, cands)
        trace = driver.trace()
        self.ledger("search", fingerprint=fp["digest"],
                    probes=len(driver.results),
                    baseline_ms=baseline["step_ms"],
                    trace=trace)
        # one decision for every rank: rank 0's measurements rule
        decision = self._decide(incumbent, baseline, best)
        winner_cand = incumbent
        if decision["swap"]:
            winner_cand = next(c for c in cands
                               if c.name == decision["winner"])
            if self.config.apply_winner:
                self._apply(winner_cand,
                            reason=f"search winner ({decision['why']})")
        # never pin a future run to a degraded probe set; rank 0 writes
        # (every rank racing read-modify-write of one shared cache file
        # with rank-local traces would be last-writer-wins gibberish)
        if driver.complete and self._rank() == 0:
            cache.store(fp, {
                "name": winner_cand.name, "comm": winner_cand.comm,
                "stage": winner_cand.stage,
                "safe_numerics": winner_cand.safe_numerics,
                # the ms attributed to the STORED winner: the rejected
                # challenger's number must not masquerade as the
                # incumbent's
                "step_ms": (decision.get("winner_ms") if decision["swap"]
                            else baseline["step_ms"])}, trace)
        return {"winner": winner_cand.name, "candidate": winner_cand,
                "cached": False, "probes": len(driver.results),
                "baseline_ms": baseline["step_ms"],
                "winner_ms": decision.get("winner_ms"),
                "trace": trace, "complete": driver.complete,
                "fingerprint": fp}

    def _make_driver(self, prober: EngineProber) -> SearchDriver:
        """Single-process: the driver enforces its own wall budget.
        Multi-process: the budget check must be LOCKSTEPPED (a rank
        whose local clock trips mid-sweep would skip a probe whose
        collective step program the others still dispatch), so the
        driver runs unbudgeted and _search gates each probe on rank
        0's clock through the consensus wire."""
        budget = self.config.budget_s if self._consensus.world <= 1 \
            else None

        def probe(cand):
            # trace timeline: each candidate probe is an `autotune`
            # span, so probe time reads as probing instead of an
            # anonymous slow step.  NOT gated on the engine's per-step
            # sampling — probes are rare and always worth a span.
            tr = getattr(self.engine, "_tracer", None)
            if tr is None:
                return prober.probe(cand)
            with tr.span("autotune.probe", "autotune", cand=cand.name,
                         step=self.engine.global_steps):
                return prober.probe(cand)

        return SearchDriver(probe, budget_s=budget)

    def _search(self, driver: SearchDriver, cands) -> Optional[Any]:
        if self._consensus.world <= 1:
            return driver.search(cands)
        from .driver import ProbeResult

        t0 = time.perf_counter()
        budget = self.config.budget_s
        best = None
        for cand in cands:
            exhausted = bool(budget is not None
                             and time.perf_counter() - t0 > budget)
            if self._consensus.agree(exhausted)[0]:  # rank 0 rules
                driver.results.append(ProbeResult(cand, skipped="budget"))
                continue
            r = driver.probe(cand)
            if r.ok and (best is None or r.score > best.score):
                best = r
        return best

    def _decide(self, incumbent: Candidate, baseline: Dict[str, Any],
                best) -> Dict[str, Any]:
        """Swap decision, agreed across ranks (rank 0's numbers)."""
        local = {
            "winner": best.candidate.name if best is not None else None,
            "winner_ms": (best.metrics.get("step_ms")
                          if best is not None else None),
            "baseline_ms": baseline.get("step_ms"),
        }
        agreed = self._consensus.agree(local)[0]
        swap = False
        why = "no candidate beat the incumbent"
        if agreed["winner"] is not None and agreed["winner_ms"] is not None:
            need = (1.0 - self.config.min_improvement) * \
                float(agreed["baseline_ms"] or 0.0)
            if agreed["winner"] != incumbent.name and \
                    float(agreed["winner_ms"]) < need:
                swap = True
                why = (f"{agreed['winner_ms']:.1f} ms/step vs incumbent "
                       f"{agreed['baseline_ms']:.1f} ms/step")
        return {"swap": swap, "winner": agreed["winner"],
                "winner_ms": agreed["winner_ms"], "why": why}

    def _apply(self, candidate: Candidate, reason: str) -> None:
        apply_candidate(self.engine, candidate)
        COUNTERS.add("autotune.swaps", calls=1)
        self.ledger("swap", candidate=candidate.name, reason=reason,
                    knobs=candidate.knobs())
        logger.warning(
            f"autotune SWAP at step {self.engine.global_steps}: "
            f"{candidate.describe()} ({reason})")

    # -- the online retune loop ------------------------------------------

    def on_step_boundary(self) -> None:
        """Called from the engine's step() tail (a clean post-apply
        state — the only point programs may be rebuilt, like the PR-10
        demotion).  Cheap when online retuning is off."""
        if not self.config.online_enabled:
            return
        now = time.perf_counter()
        exposed = self._exposed_us()
        if self._last_boundary_t is not None:
            step_ms = (now - self._last_boundary_t) * 1e3
            exposed_ms = (exposed - self._exposed_snap) / 1e3
            if self.detector.observe(step_ms, exposed_ms) and \
                    self._local_trigger is None:
                self._local_trigger = self.detector.describe_trigger(
                    step_ms, exposed_ms)
        self._exposed_snap = exposed
        step = self.engine.global_steps
        if step > 0 and step % self.config.online_check_every == 0:
            verdicts = self._consensus.agree(self._local_trigger)
            reasons = [v for v in verdicts if v]
            if reasons:
                try:
                    self.retune(reason=reasons[0])
                except Exception as e:
                    # the BACKGROUND loop must never kill training: a
                    # failed retune logs, re-baselines, and the run
                    # continues on the incumbent config
                    logger.warning(
                        f"autotune online retune failed "
                        f"({type(e).__name__}: {e}); the incumbent "
                        "config stands and training continues")
                    self.detector.reset(cooldown=True)
                    self._last_boundary_t = None
            self._local_trigger = None
        # stamp AFTER any retune: probe time must not read as a slow step
        self._last_boundary_t = time.perf_counter()

    def retune(self, reason: str) -> Dict[str, Any]:
        """One bounded online retune: re-probe the incumbent + its
        1-knob neighborhood, swap if a candidate clearly wins, then
        re-baseline the detector under whatever config emerged."""
        eng = self.engine
        COUNTERS.add("autotune.retunes", calls=1)
        self.retunes += 1
        tr = getattr(eng, "_tracer", None)
        if tr is not None:
            tr.instant("autotune.retune", "autotune", reason=reason,
                       step=eng.global_steps)
        incumbent = current_candidate(eng)
        cands = self.candidates(live_only=True,
                                safe_only=self.config.online_safe_only)
        neigh = neighborhood(incumbent, cands,
                             radius=self.config.online_radius)
        logger.warning(
            f"autotune ONLINE RETUNE at step {eng.global_steps}: {reason} "
            f"— re-probing {len(neigh)} neighbor(s) of "
            f"{incumbent.name}")
        prober = EngineProber(eng, steps=self.config.probe_steps,
                              warmup=self.config.probe_warmup)
        driver = self._make_driver(prober)
        baseline = prober.probe_current()
        best = self._search(driver, neigh)
        decision = self._decide(incumbent, baseline, best)
        self.ledger("retune", reason=reason, incumbent=incumbent.name,
                    baseline_ms=baseline["step_ms"],
                    probes=len(driver.results), trace=driver.trace(),
                    swapped=decision["swap"], winner=decision["winner"])
        if decision["swap"]:
            winner = next(c for c in neigh
                          if c.name == decision["winner"])
            self._apply(winner, reason=f"online retune: {reason}")
        else:
            log_dist(
                f"autotune online retune: incumbent {incumbent.name} "
                f"stands ({decision['why']})", ranks=[0])
        # re-baseline under the (possibly new) config; cooldown so one
        # fault burst cannot chain retunes
        self.detector.reset(cooldown=True)
        self._last_boundary_t = None
        return decision
