"""runtime/autotune — the self-tuning runtime.

The repo grew ~15 interacting performance knobs (ZeRO stage, bucket
size, per-level wire dtypes, hierarchy factor, overlap mode, quant
block, gas/micro, remat, MoE dispatch/wire, prefetch depth) and the
winning combination is a property of the FABRIC, not the model (ZeRO++
arXiv:2306.10209; the Frontier low-bandwidth partitioning study
arXiv:2501.04266).  This package turns the knob space into a searched,
cached, live-retunable artifact:

  space.py        legal-candidate enumeration — every mutation is
                  validated through config.py's own parsers, so illegal
                  combos are pruned before a single probe runs
  fingerprint.py  (model shape, mesh, fabric) fingerprints keying the
                  winner cache — a cache probed on a different mesh
                  factorization, dtype config or world size must
                  re-probe loudly, never pin silently
  cache.py        the persisted winner cache (bench_artifacts/
                  autotune.json-style single-entry mode for bench.py,
                  fingerprint-keyed map mode for the engine driver)
  driver.py       the generic search driver: budgeted probe loop,
                  failure-tolerant (a probe that OOMs is skipped, never
                  fatal), scorer combining achieved throughput with the
                  monitor's exposed-time counters
  probe.py        live probing on a RUNNING engine: candidate applied
                  via a StepBuilder program rebuild (the PR-10 demotion
                  path proved mid-run rebuilds safe), a few steps run
                  on state COPIES so training state never moves
  online.py       sustained-regression detection (step-time +
                  exposed-wire creep) driving the online retune loop
  runtime.py      the engine attachment: search/retune orchestration,
                  `autotune.*` counters, the ledger the report renders

Counters (monitor/counters.py): `autotune.probes` (bytes = probe µs,
the ckpt.stall_ms convention), `autotune.cache_hits`,
`autotune.rejected`, `autotune.swaps`, `autotune.retunes` — all
excluded from the comm byte table and rendered as the report's
"Autotune" section beside the `autotune.jsonl` ledger.
"""

from .cache import WinnerCache
from .driver import ProbeResult, SearchDriver, combine_score
from .fingerprint import (engine_fingerprint, fingerprint_diff,
                          make_fingerprint, serve_fingerprint)
from .online import RegressionDetector
from .probe import EngineProber
from .runtime import AutotuneRuntime
from .space import (Candidate, current_candidate,
                    current_serve_candidate, generate_candidates,
                    generate_serve_candidates, knob_distance,
                    neighborhood)

__all__ = [
    "AutotuneRuntime",
    "Candidate",
    "EngineProber",
    "ProbeResult",
    "RegressionDetector",
    "SearchDriver",
    "WinnerCache",
    "combine_score",
    "current_candidate",
    "current_serve_candidate",
    "engine_fingerprint",
    "fingerprint_diff",
    "generate_candidates",
    "generate_serve_candidates",
    "knob_distance",
    "make_fingerprint",
    "neighborhood",
    "serve_fingerprint",
]
