"""Candidate generation over the validated comm/zero config space.

The generator composes knob mutations mechanically (cartesian product
over gradient reduction, per-level wire dtypes, hierarchy factors,
overlap, bucket size, quant block) and then runs EVERY composition
through `config.DeepSpeedCommConfig` — the same validator a user config
passes at initialize().  Whatever the validator rejects (an int8 inner
wire on the scatter level, a non-dividing hierarchy factor, a typo'd
dtype) is pruned before a single probe runs, and counted, so the search
space can never drift from what the engine actually accepts.

Candidate scopes:

  live    a StepBuilder program rebuild on a RUNNING engine can serve
          it (wire dtypes, bucket size, overlap on/off, implicit vs
          bucketed).  The PR-10 mid-run demotion path is the existence
          proof that live rebuilds are safe and bitwise.
  engine  needs a fresh engine build — the data-axis factorization IS
          the mesh layout every array placement derives from
          (engine.allreduce_gradients documents the same boundary), so
          hierarchy mutations only probe through an engine factory
          (tools/autotune_bench.py) and never online.
  serve   inference-side knobs (KV cache storage dtype, speculative
          draft length, prefix-cache enable / min match blocks /
          session TTL) for a ServeEngine.  The `comm` field carries a
          "serving"-block fragment instead, validated through the REAL
          `DeepSpeedServingConfig` by `generate_serve_candidates`; every
          serve candidate needs a fresh ServeEngine (the KV pool layout
          and the verify program are compile-time), so tools/serve_bench
          is the probe harness, never the online loop.
  kernel  per-op Pallas-vs-jnp implementation pins for the kernel
          registry (deepspeed_tpu.kernels).  The `comm` field carries a
          "kernels"-block fragment ({"ops": {op: impl}}), validated
          through the REAL `DeepSpeedKernelsConfig` by
          `generate_kernel_candidates`; the winning pin is applied by
          `kernels.registry.record_winner`, keyed to the fabric section
          of the fingerprint, so a cache hit on a different backend
          never forces a kernel the probe ran elsewhere.

`safe_numerics`: True when swapping to the candidate preserves the
repo's bitwise loss contract on this fabric — every wire level fp32
(implicit psum == bucketed fold == overlap combine, elementwise, pinned
since PR 3/9; bucket size only re-partitions the same elementwise
fold).  Compressed wires (bf16/split/int8/int4) change rounding and are
probe-only by default for the ONLINE retune loop, which pins loss
parity across its swaps.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

# knob fields a 1-knob neighborhood distance is measured over
_KNOB_FIELDS = ("gradient_reduction", "wire_dtype", "wire_dtype_inner",
                "wire_dtype_outer", "hierarchy", "overlap",
                "reduce_bucket_size", "quant_block_size")

# the serve scope's knob fields (Candidate.comm carries a "serving"
# fragment there; see generate_serve_candidates)
_SERVE_KNOB_FIELDS = ("kv_dtype", "draft_len", "prefix_cache",
                      "min_match_blocks", "session_ttl_s")

# the kernel scope's knob view: one synthetic field holding the sorted
# (op, impl) pin tuple, so distance counts per-op pin differences
_KERNEL_KNOB_FIELDS = ("kernel_ops",)


class Candidate(NamedTuple):
    """One point in the legal config space."""

    name: str
    comm: Dict            # "comm"-block fragment the engine applies
    #                       ("serving" fragment when scope == "serve")
    stage: int = 0        # ZeRO stage the legality check ran against
    scope: str = "live"   # "live" | "engine" | "serve" | "kernel"
    safe_numerics: bool = True

    def knobs(self) -> Dict:
        """Comparable knob view (absent keys normalized) — the
        neighborhood distance and ledger entries read this."""
        c = self.comm
        if self.scope == "kernel":
            ops = c.get("ops") or {}
            return {"kernel_ops": tuple(sorted(ops.items()))}
        if self.scope == "serve":
            spec = c.get("speculative") or {}
            pfx = c.get("prefix_cache") or {}
            return {
                "kv_dtype": c.get("kv_dtype") or "dense",
                "draft_len": (int(spec.get("draft_len", 0))
                              if spec.get("enabled") else 0),
                "prefix_cache": bool(pfx.get("enabled", True)),
                "min_match_blocks": int(pfx.get("min_match_blocks", 1)),
                "session_ttl_s": float(pfx.get("session_ttl_s", 120.0)),
            }
        hier = c.get("hierarchy", "none")
        if isinstance(hier, dict):
            hier = hier.get("outer", 1)
        return {
            "gradient_reduction": c.get("gradient_reduction", "implicit"),
            "wire_dtype": c.get("wire_dtype", "fp32"),
            "wire_dtype_inner": c.get("wire_dtype_inner"),
            "wire_dtype_outer": c.get("wire_dtype_outer"),
            "hierarchy": hier,
            "overlap": c.get("overlap", "none"),
            "reduce_bucket_size": c.get("reduce_bucket_size"),
            "quant_block_size": c.get("quant_block_size"),
        }

    def describe(self) -> str:
        k = self.knobs()
        if self.scope == "kernel":
            pins = ", ".join(f"{op}={impl}"
                             for op, impl in k["kernel_ops"]) or "auto"
            return f"{self.name}: {pins}"
        if self.scope == "serve":
            parts = [f"kv {k['kv_dtype']}"]
            if k["draft_len"]:
                parts.append(f"spec draft {k['draft_len']}")
            if not k["prefix_cache"]:
                parts.append("prefix off")
            elif k["min_match_blocks"] != 1:
                parts.append(f"prefix match>={k['min_match_blocks']}")
            return f"{self.name}: " + ", ".join(parts)
        parts = [k["gradient_reduction"]]
        if k["gradient_reduction"] == "bucketed":
            if k["hierarchy"] not in ("none", 1):
                parts.append(f"hier outer={k['hierarchy']} "
                             f"{k['wire_dtype_inner'] or k['wire_dtype']}/"
                             f"{k['wire_dtype_outer'] or k['wire_dtype']}")
            else:
                parts.append(f"wire {k['wire_dtype']}")
            if k["reduce_bucket_size"]:
                parts.append(f"bucket {k['reduce_bucket_size']}")
        if k["overlap"] not in ("none", None):
            parts.append("overlap")
        return f"{self.name}: " + ", ".join(parts)


# knobs where None means "inherit the incumbent's value" (probe.
# apply_candidate setdefaults them) — a wildcard, not a difference
_OPTIONAL_KNOBS = ("wire_dtype_inner", "wire_dtype_outer",
                   "reduce_bucket_size", "quant_block_size")


def _scope_family(c: Candidate) -> str:
    """Knob-space family: "serve" and "kernel" candidates each live in
    their own space; "live"/"engine" share the train-side comm space."""
    return c.scope if c.scope in ("serve", "kernel") else "train"


def knob_distance(a: Candidate, b: Candidate) -> int:
    """How many knob fields differ between two candidates.  Optional
    knobs compare as equal when either side leaves them unspecified
    (None = inherit)."""
    if _scope_family(a) != _scope_family(b):
        # candidates from different scope families live in disjoint
        # spaces — farther apart than any same-family pair can be
        return len(_KNOB_FIELDS) + len(_SERVE_KNOB_FIELDS)
    ka, kb = a.knobs(), b.knobs()
    if a.scope == "kernel":
        # one unit per op whose pin differs (absent = "auto")
        da, db = dict(ka["kernel_ops"]), dict(kb["kernel_ops"])
        return sum(1 for op in set(da) | set(db)
                   if da.get(op, "auto") != db.get(op, "auto"))
    if a.scope == "serve":
        return sum(1 for f in _SERVE_KNOB_FIELDS if ka[f] != kb[f])
    dist = 0
    for f in _KNOB_FIELDS:
        if f in _OPTIONAL_KNOBS and (ka[f] is None or kb[f] is None):
            continue
        if ka[f] != kb[f]:
            dist += 1
    return dist


def neighborhood(current: Candidate, candidates: Sequence[Candidate],
                 radius: int = 1) -> List[Candidate]:
    """The bounded re-probe set the online retune loop walks: every
    candidate within `radius` knob mutations of `current` (current
    itself excluded — the retuner re-probes it separately as the
    baseline)."""
    return [c for c in candidates
            if c.name != current.name
            and knob_distance(current, c) <= radius]


def _is_legal(comm: Dict, stage: int, dp: Optional[int]) -> bool:
    """Run one composed comm block through the REAL config validator —
    the pruning the tentpole exists for.  Anything DeepSpeedCommConfig
    raises on at parse time is illegal here too."""
    from ..config import DeepSpeedCommConfig
    from ..zero.config import DeepSpeedZeroConfig

    zc = DeepSpeedZeroConfig({"zero_optimization": {"stage": stage}})
    try:
        DeepSpeedCommConfig({"comm": dict(comm)}, zc, world_size=dp)
    except ValueError:
        return False
    return True


def _name(reduction: str, wire: str, inner: Optional[str],
          outer_dtype: Optional[str], hier, overlap: bool,
          bucket: Optional[int], block: Optional[int]) -> str:
    if reduction == "implicit":
        return "implicit" + ("_overlap" if overlap else "")
    parts = []
    if hier in ("none", None, 1):
        parts.append(f"flat_{wire}")
    else:
        parts.append(f"hier{hier}_{inner or 'fp32'}_"
                     f"{outer_dtype or wire}")
    if bucket:
        parts.append(f"b{bucket}")
    if block:
        parts.append(f"q{block}")
    if overlap:
        parts.append("overlap")
    return "_".join(parts)


def _safe(wires: Sequence[Optional[str]]) -> bool:
    return all(w in (None, "fp32") for w in wires)


def generate_candidates(
        dp: int,
        stage: int = 0,
        current_outer: int = 1,
        wire_dtypes: Sequence[str] = ("fp32", "bf16", "int8"),
        inner_dtypes: Sequence[Optional[str]] = (None,),
        outers: Optional[Sequence[int]] = None,
        overlap: Sequence[bool] = (False, True),
        include_implicit: bool = True,
        bucket_sizes: Sequence[int] = (),
        quant_blocks: Sequence[int] = (),
) -> Tuple[List[Candidate], int]:
    """Enumerate the legal candidate set for a dp-wide data axis.

    Returns (candidates, n_rejected) where n_rejected counts the
    compositions the config validators pruned (the `autotune.rejected`
    counter).  `outers=None` derives every proper divisor of `dp`;
    hierarchy factors other than `current_outer` come out scope
    "engine" (the factorization is the mesh layout — live rebuilds
    cannot change it).  Structural no-ops are skipped rather than
    rejected: overlap over the implicit wire would fall back with a
    log, not probe anything new."""
    if outers is None:
        outers = [d for d in range(2, dp) if dp % d == 0]
    hierarchies: List = ["none"] + [o for o in outers if o > 1]

    seen = set()
    out: List[Candidate] = []
    rejected = 0

    def add(reduction, wire, inner, outer_dtype, hier, ov, bucket, block):
        nonlocal rejected
        comm: Dict = {"gradient_reduction": reduction}
        if reduction == "bucketed":
            comm["wire_dtype"] = wire
            if hier != "none":
                comm["hierarchy"] = {"outer": int(hier)}
                if inner is not None:
                    comm["wire_dtype_inner"] = inner
                if outer_dtype is not None:
                    comm["wire_dtype_outer"] = outer_dtype
            if bucket is not None:
                comm["reduce_bucket_size"] = int(bucket)
            if block is not None:
                comm["quant_block_size"] = int(block)
        comm["overlap"] = "on" if ov else "none"
        name = _name(reduction, wire, inner, outer_dtype, hier, ov,
                     bucket, block)
        if name in seen:
            return
        seen.add(name)
        if not _is_legal(comm, stage, dp):
            rejected += 1
            return
        hier_outer = 1 if hier == "none" else int(hier)
        scope = "live" if hier_outer == int(current_outer) else "engine"
        out.append(Candidate(
            name=name, comm=comm, stage=stage, scope=scope,
            safe_numerics=_safe((wire, inner, outer_dtype))))

    if include_implicit:
        # the naive default: one psum per leaf, nothing overlapped —
        # the config every search is expected to beat (or honestly
        # confirm on fabrics where XLA's in-program psum wins)
        add("implicit", "fp32", None, None, "none", False, None, None)

    buckets: List[Optional[int]] = [None] + [int(b) for b in bucket_sizes]
    blocks: List[Optional[int]] = [None] + [int(q) for q in quant_blocks]
    for wire in wire_dtypes:
        for hier in hierarchies:
            inner_set = inner_dtypes if hier != "none" else (None,)
            outer_set = ([wire] if hier != "none" else [None])
            for inner in inner_set:
                for outer_dtype in outer_set:
                    # on hierarchical candidates the SLOW hop carries
                    # the compression and the fast hop defaults exact —
                    # wire_dtype itself stays fp32 there so the flat
                    # fallback (if hierarchy disengages) is the safe one
                    flat_wire = "fp32" if hier != "none" else wire
                    for ov in overlap:
                        for bucket in buckets:
                            for block in blocks:
                                if block is not None and not any(
                                        w in ("int8", "int4") for w in
                                        (flat_wire, inner, outer_dtype)):
                                    continue  # block only moves quant wires
                                add("bucketed", flat_wire, inner,
                                    outer_dtype, hier, ov, bucket, block)
    return out, rejected


def _serve_fragment(kv_dtype, draft_len: int, prefix_cache: bool = True,
                    min_match_blocks: int = 1,
                    session_ttl_s: float = 120.0) -> Dict:
    """The "serving"-block fragment a serve-scope knob point maps to —
    the exact dict a user would write under "serving" in their config,
    so validating it validates the real surface."""
    frag: Dict = {"kv_dtype": kv_dtype}
    if draft_len > 0:
        frag["speculative"] = {"enabled": True,
                               "draft_len": int(draft_len)}
    else:
        frag["speculative"] = {"enabled": False}
    frag["prefix_cache"] = {"enabled": bool(prefix_cache),
                            "min_match_blocks": int(min_match_blocks),
                            "session_ttl_s": float(session_ttl_s)}
    return frag


def generate_serve_candidates(
        head_dim: int,
        kv_dtypes: Sequence[Optional[str]] = (None, "bf16", "int8",
                                              "int4"),
        draft_lens: Sequence[int] = (0, 2, 4),
        prefix_modes: Sequence[bool] = (True, False),
        min_matches: Sequence[int] = (1,),
        session_ttls: Sequence[float] = (120.0,),
) -> Tuple[List[Candidate], int]:
    """Enumerate the serve-scope candidate set: the cartesian product
    of KV storage modes, speculative draft lengths, and prefix-cache
    knobs (enabled, min match blocks, session TTL), each composition
    run through the REAL `DeepSpeedServingConfig` validator (same
    pruning contract as the comm space: a typo'd dtype or a negative
    draft_len is rejected and counted, never probed).  `head_dim` gates
    int4 — the packed nibble payload needs an even head_dim, so int4
    points are pruned (and counted rejected) on odd-head_dim models,
    mirroring PagedKVCache's own constructor check.  Disabled prefix
    points collapse min_match/ttl to their defaults (the knobs are
    inert with the cache off — enumerating them would duplicate).

    `safe_numerics` is True only for kv_dtype None/"fp32" (bit-exact
    vs `generate()`); draft_len alone never flips it — speculation is
    token-identical at matched kv_dtype by construction, it changes
    WHEN tokens arrive, never WHICH — and the prefix cache never flips
    it either: aliased blocks are bitwise-identical to recompute by
    the exactness contract (docs/tutorials/serving.md)."""
    from ..config import DeepSpeedServingConfig

    out: List[Candidate] = []
    rejected = 0

    def pfx_points():
        for on in prefix_modes:
            if not on:
                yield (False, 1, 120.0)
                continue
            for mm in min_matches:
                for ttl in session_ttls:
                    yield (True, int(mm), float(ttl))

    for kv in kv_dtypes:
        for draft in draft_lens:
            for on, mm, ttl in pfx_points():
                if kv == "int4" and int(head_dim) % 2 != 0:
                    rejected += 1
                    continue
                frag = _serve_fragment(kv, int(draft), on, mm, ttl)
                try:
                    DeepSpeedServingConfig({"serving": frag})
                except ValueError:
                    rejected += 1
                    continue
                name = f"serve_{kv or 'dense'}_d{int(draft)}"
                if not on:
                    name += "_nopfx"
                else:
                    if mm != 1:
                        name += f"_m{mm}"
                    if ttl != 120.0:
                        name += f"_ttl{int(ttl)}"
                out.append(Candidate(
                    name=name, comm=frag, scope="serve",
                    safe_numerics=kv in (None, "fp32", "float32")))
    return out, rejected


def generate_kernel_candidates(
        op_names: Optional[Sequence[str]] = None,
        impls: Sequence[str] = ("pallas", "jnp"),
) -> Tuple[List[Candidate], int]:
    """Enumerate the kernel-scope candidate set: one candidate per
    (op, impl) pin over the registered kernel ops, each fragment run
    through the REAL `DeepSpeedKernelsConfig` validator (the same
    pruning contract as the comm and serve spaces: a typo'd op name or
    impl value is rejected and counted, never probed).  `op_names=None`
    enumerates every registered op; passing an explicit list lets a
    bench sweep one op's pins — including invalid names, which prune
    instead of raising, so the `autotune.rejected` counter stays the
    single source of truth for space drift.

    `safe_numerics` is True only for `quant_codec` pins: the codec's
    Pallas path is pinned BIT-exact against its jnp oracle (both
    variants), so swapping its pin preserves the bitwise wire contract.
    Attention ops and the MoE combine are tolerance-bounded (FMA
    fusion / reduction-order rounding), so their pins are probe-only
    for the numerics-pinning online loop."""
    from ..config import DeepSpeedKernelsConfig, DeepSpeedConfigError

    if op_names is None:
        from ...kernels.registry import KERNEL_OPS

        op_names = sorted(KERNEL_OPS)
    out: List[Candidate] = []
    rejected = 0
    for op in op_names:
        for impl in impls:
            frag = {"ops": {op: impl}}
            try:
                DeepSpeedKernelsConfig({"kernels": frag})
            except (DeepSpeedConfigError, ValueError):
                rejected += 1
                continue
            out.append(Candidate(
                name=f"kern_{op}_{impl}", comm=frag, scope="kernel",
                safe_numerics=(op == "quant_codec")))
    return out, rejected


def current_serve_candidate(engine) -> Candidate:
    """The serve candidate describing a live ServeEngine's config —
    the baseline a serve-scope sweep measures lanes against."""
    c = engine.config
    kv = engine.kv.quant_wire  # "int8"/"int4" or None (dense)
    if kv is None and c.kv_dtype is not None:
        kv = str(c.kv_dtype)
    frag = _serve_fragment(kv, int(c.draft_len), bool(c.prefix_cache),
                           int(c.prefix_min_match_blocks),
                           float(c.session_ttl_s))
    name = f"serve_{kv or 'dense'}_d{int(c.draft_len)}"
    if not c.prefix_cache:
        name += "_nopfx"
    else:
        if int(c.prefix_min_match_blocks) != 1:
            name += f"_m{int(c.prefix_min_match_blocks)}"
        if float(c.session_ttl_s) != 120.0:
            name += f"_ttl{int(c.session_ttl_s)}"
    return Candidate(
        name=name, comm=frag, scope="serve",
        safe_numerics=kv in (None, "fp32", "float32"))


def current_candidate(engine) -> Candidate:
    """The candidate describing an engine's CURRENT effective config —
    the baseline the online retuner re-probes and measures swaps
    against."""
    cc = engine._config.comm_config
    plan = engine.bucket_plan
    outer = engine.mesh_info.data_outer_size
    hier = "none" if outer <= 1 else outer
    comm: Dict = {"gradient_reduction":
                  "bucketed" if plan is not None else "implicit"}
    wires: List[Optional[str]] = []
    if plan is not None:
        comm["wire_dtype"] = cc.wire_dtype
        wires.append(cc.wire_dtype)
        comm["reduce_bucket_size"] = plan.bucket_elems
        if hier != "none":
            comm["hierarchy"] = {"outer": outer}
            comm["wire_dtype_inner"] = cc.wire_dtype_inner
            comm["wire_dtype_outer"] = cc.wire_dtype_outer
            wires = [cc.wire_dtype_inner, cc.wire_dtype_outer]
    ov = engine._overlap_mode is not None
    comm["overlap"] = "on" if ov else "none"
    name = _name(comm["gradient_reduction"], comm.get("wire_dtype", "fp32"),
                 comm.get("wire_dtype_inner"), comm.get("wire_dtype_outer"),
                 hier, ov, None, None)
    return Candidate(name=name, comm=comm,
                     stage=engine._config.zero_optimization_stage,
                     scope="live", safe_numerics=_safe(wires))
