"""Activation checkpointing — TPU-native rematerialisation.

Reference: deepspeed/runtime/activation_checkpointing/checkpointing.py
(CheckpointFunction :418-472, configure :687-746, partitioning math
:240-292, CUDA RNG tracker :98-197). The reference re-implements
torch.utils.checkpoint with three extras: (a) saved inputs partitioned
across model-parallel ranks, (b) optional CPU offload of the saved
tensors, (c) a fork-able CUDA RNG tracker so dropout patterns match
between the original forward and the recompute.

TPU mapping:
* checkpoint(fn, *args) -> jax.checkpoint: XLA re-runs the forward in the
  backward pass; "what to save" is a remat policy, not autograd surgery.
* partition_activations -> the saved inputs get a sharding constraint over
  the `model` mesh axis (each rank materialises 1/mp of every saved
  activation — same memory effect as reference :240-292's scatter +
  backward all-gather, but XLA inserts the collectives).
* cpu_checkpointing -> remat policy offloading saved residuals to
  pinned_host memory (TPU runtime streams them back for the backward).
* RNG correctness is free: jax.checkpoint replays the SAME functional
  PRNG keys in the recompute, so the reference's CudaRNGStatesTracker
  machinery (:98-197) has no TPU equivalent to build. A tracker-shaped
  shim is provided for API parity.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...comm.mesh import MODEL_AXIS, peek_mesh
from ...utils.logging import logger

# module-level configuration (reference keeps the same globals :60-96)
_CONFIG = {
    "configured": False,
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,  # accepted no-op: XLA layout
    "synchronize": False,                     # accepted no-op: XLA ordering
    "profile": False,
    "num_checkpoints": None,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """reference checkpointing.py:687-746 (same keyword surface)."""
    cfg = None
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing_config",
                      None)
    if cfg is not None:
        _CONFIG.update(
            partition_activations=cfg.partition_activations,
            cpu_checkpointing=cfg.cpu_checkpointing,
            contiguous_memory_optimization=cfg.contiguous_memory_optimization,
            synchronize=cfg.synchronize_checkpoint_boundary,
            profile=cfg.profile,
            num_checkpoints=cfg.number_checkpoints)
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization",
                      contiguous_checkpointing),
                     ("num_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize),
                     ("profile", profile)):
        if val is not None:
            _CONFIG[key] = val
    _CONFIG["configured"] = True


def is_configured() -> bool:
    return _CONFIG["configured"]


def reset():
    """reference checkpointing.py:668-684 (buffer reset; here: config)."""
    _CONFIG.update(configured=False, partition_activations=False,
                   cpu_checkpointing=False, num_checkpoints=None)


def partition_activations_in_checkpoint(partition_activation):
    """Toggle activation partitioning at runtime (reference
    checkpointing.py:699-703)."""
    _CONFIG["partition_activations"] = bool(partition_activation)


def _partition_spec_for(x) -> Optional[PartitionSpec]:
    """Shard the largest divisible dim over the model axis (the reference
    flattens and scatters 1/mp per rank, :240-292; sharding a whole dim is
    the XLA-friendly equivalent)."""
    info = peek_mesh()
    mesh = info.mesh if info is not None else None
    if mesh is None or MODEL_AXIS not in mesh.shape:
        return None
    mp = mesh.shape[MODEL_AXIS]
    if mp <= 1 or x.ndim == 0:
        return None
    for dim in range(x.ndim):
        if x.shape[dim] % mp == 0 and x.shape[dim] >= mp:
            spec = [None] * x.ndim
            spec[dim] = MODEL_AXIS
            return PartitionSpec(*spec)
    return None


def _constrain_tree(tree):
    def put(x):
        if not hasattr(x, "ndim"):
            return x
        spec = _partition_spec_for(x)
        if spec is None:
            return x
        sharding = NamedSharding(peek_mesh().mesh, spec)
        return jax.lax.with_sharding_constraint(x, sharding)

    return jax.tree_util.tree_map(put, tree)


def _remat_policy():
    if _CONFIG["cpu_checkpointing"]:
        try:
            # offload the expensive residuals (matmul outputs) to host
            # memory instead of keeping them in HBM; everything else is
            # rematerialised. This is the policy that actually moves bytes
            # — name-based offload would require checkpoint_name tags the
            # user's model doesn't have.
            return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                "device", "pinned_host")
        except Exception:  # pragma: no cover - older jax
            logger.warning("cpu_checkpointing: offload policy unavailable; "
                           "falling back to full rematerialisation")
    return None  # default policy: save inputs only, recompute the rest


def checkpoint(function, *args):
    """reference checkpointing.py:748-759 `checkpoint(function, *args)`.

    Returns function(*args) with rematerialisation in the backward pass.
    With partition_activations configured, the checkpoint boundary inputs
    (= the saved tensors) carry a model-axis sharding constraint.
    """
    fn = function
    if _CONFIG["partition_activations"]:
        inner = function

        def fn(*a):  # noqa: F811 - deliberate wrapper
            return inner(*_constrain_tree(a))

        args = _constrain_tree(args)
    policy = _remat_policy()
    kwargs = {"policy": policy} if policy is not None else {}
    return jax.checkpoint(fn, **kwargs)(*args)


def checkpoint_wrapper(function):
    """Decorator form: returns a rematerialising version of `function`."""

    def wrapped(*args):
        return checkpoint(function, *args)

    return wrapped


# ---------------------------------------------------------------------------
# RNG tracker shims (reference :98-237). JAX PRNG keys are explicit values
# replayed identically during recompute, so these exist for API parity and
# for deriving distinct-but-deterministic per-model-parallel-rank keys.
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG = "model-parallel-rng"


class RNGStatesTracker:
    """Key registry keyed by name (reference CudaRNGStatesTracker :110)."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name=_MODEL_PARALLEL_RNG):
        """Split the named key; returns the fresh subkey (functional analog
        of the reference's context-manager fork :166-197)."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:  # parity name
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int):
    """reference checkpointing.py:198-237: seed the model-parallel stream
    offset by the mp rank so parallel regions (dropout) differ per rank
    while the default stream stays identical."""
    info = peek_mesh()
    mp_rank = 0
    if info is not None and MODEL_AXIS in info.mesh.shape:
        # single-controller: derive rank 0's offset; per-device offsets come
        # from folding the axis index inside shard_map'd code
        mp_rank = 0
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718 + mp_rank)
    return _RNG_TRACKER


def model_parallel_rng_key(base_key, axis: str = MODEL_AXIS):
    """Inside shard_map/jit: per-model-parallel-rank key (fold in the axis
    index) — the functional version of the reference's per-rank seed."""
    try:
        idx = jax.lax.axis_index(axis)
    except NameError:
        idx = 0
    return jax.random.fold_in(base_key, idx)
