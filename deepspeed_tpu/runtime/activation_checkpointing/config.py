"""Activation checkpointing config
(reference: deepspeed/runtime/activation_checkpointing/config.py:769-850).

On TPU, `partition_activations` maps to sharding saved activations over the
model axis inside `jax.checkpoint` policies; `cpu_checkpointing` maps to
host offload of residuals; `contiguous_memory_optimization` and
`synchronize_checkpoint_boundary` are accepted no-ops (XLA owns layout and
ordering).
"""

from ..config_utils import DeepSpeedConfigObject, get_scalar_param

ACTIVATION_CHKPT = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"


class DeepSpeedActivationCheckpointingConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(ACTIVATION_CHKPT, {}) or {}
        self.partition_activations = get_scalar_param(
            d, ACT_CHKPT_PARTITION_ACTIVATIONS, False)
        self.contiguous_memory_optimization = get_scalar_param(
            d, ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION, False)
        self.cpu_checkpointing = get_scalar_param(
            d, ACT_CHKPT_CPU_CHECKPOINTING, False)
        self.number_checkpoints = get_scalar_param(
            d, ACT_CHKPT_NUMBER_CHECKPOINTS, None)
        self.profile = get_scalar_param(d, ACT_CHKPT_PROFILE, False)
        self.synchronize_checkpoint_boundary = get_scalar_param(
            d, ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY, False)
