"""DeepSpeedConfig — JSON config parsing + validation.

Schema-compatible with the reference (deepspeed/runtime/config.py:536):
user configs written for DeepSpeed parse unchanged. The batch triple
(train_batch_size = micro_batch * gradient_accumulation_steps * dp_world)
solver mirrors reference config.py:681-752. TPU additions: a "mesh"
section selecting parallel axis sizes.
"""

import json

from ..elasticity import (
    ElasticityConfigError,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)
from ..elasticity import constants as ec
from ..monitor.config import DeepSpeedMonitorConfig
from ..profiling.config import DeepSpeedFlopsProfilerConfig
from ..utils.logging import logger
from . import constants as c
from .activation_checkpointing.config import DeepSpeedActivationCheckpointingConfig
from .config_utils import (
    DeepSpeedConfigObject,
    dict_raise_error_on_duplicate_keys,
    get_scalar_param,
)
from .zero.config import DeepSpeedZeroConfig


class DeepSpeedConfigError(Exception):
    pass


TORCH_DTYPES = {
    "fp16": "float16", "float16": "float16", "half": "float16",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp32": "float32", "float32": "float32", "float": "float32",
}


class DeepSpeedConfigWriter(DeepSpeedConfigObject):
    pass


def parse_comm_hierarchy(value):
    """Normalize the `comm.hierarchy` knob to "none" | "auto" | int
    (the explicit outer factor).  Shared by the config validator and the
    engine's mesh construction (which runs before full config parsing)."""
    if value is None:
        value = c.COMM_HIERARCHY_DEFAULT
    if isinstance(value, dict):
        unknown = set(value) - {"outer"}
        if unknown:
            raise ValueError(
                f"comm.hierarchy: unknown key(s) {sorted(unknown)}; "
                "expected {'outer': <int>}")
        value = value.get("outer", 1)
    if isinstance(value, str):
        mode = value.lower()
        if mode in ("none", "flat", "off"):
            return "none"
        if mode == "auto":
            return "auto"
        raise ValueError(
            "comm.hierarchy must be 'none', 'auto', an int outer factor, "
            f"or {{'outer': <int>}}, got {value!r}")
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(
            "comm.hierarchy must be 'none', 'auto', an int outer factor, "
            f"or {{'outer': <int>}}, got {value!r}")
    if value < 1:
        raise ValueError(
            f"comm.hierarchy outer factor must be >= 1, got {value}")
    return "none" if value == 1 else value


def check_hierarchy_divides(outer: int, dp_size: int) -> None:
    """An explicit outer factor must factor the dp size exactly — raise
    a shape-level ValueError naming the axis sizes instead of letting a
    jitted reshape/scatter trace into an opaque shape error."""
    if dp_size % outer != 0:
        raise ValueError(
            f"comm.hierarchy: data_outer={outer} does not divide the "
            f"data-parallel axis size {dp_size} (data_inner would be "
            f"{dp_size / outer:g}); pick an outer factor from the "
            f"divisors of {dp_size}")


def parse_comm_overlap(value):
    """Normalize the `comm.overlap` knob to "none" | "auto" | "on".
    Booleans are accepted (the reference `overlap_comm` style): true
    means "on" (demand overlap; unservable configs fall back with a
    warning), false means "none"."""
    if value is None:
        value = c.COMM_OVERLAP_DEFAULT
    if isinstance(value, bool):
        return "on" if value else "none"
    if isinstance(value, str):
        mode = value.lower()
        if mode in ("none", "off", "false"):
            return "none"
        if mode == "auto":
            return "auto"
        if mode in ("on", "true"):
            return "on"
    raise ValueError(
        f"comm.{c.COMM_OVERLAP} must be one of {c.COMM_OVERLAP_MODES} "
        f"(or a bool), got {value!r}")


class DeepSpeedCommConfig(DeepSpeedConfigObject):
    """Gradient-reduction wire selection (runtime/comm/bucketing.py).

    "comm": {
      "gradient_reduction": "implicit" | "bucketed",
      "wire_dtype": "fp32" | "bf16" | "split" | "int8" | "int4",
      "reduce_bucket_size": <elements>,  # default: zero_optimization's knob
      "hierarchy": "none" | "auto" | <outer> | {"outer": <outer>},
      "wire_dtype_inner": ...,           # per-level overrides (hierarchy)
      "wire_dtype_outer": ...,
      "quant_block_size": <elements per fp16 scale>   # int8/int4 wires
    }

    `implicit` (default) leaves DP reduction to XLA's psum at the
    loss-mean boundary — right on ICI, where XLA overlaps the per-leaf
    psums with the backward.  `bucketed` concatenates grads into the
    BucketPlan's fused buckets, one collective per bucket — measured 2x+
    faster on serialization-bound fabrics (BENCH.md grad-wire rounds).
    The reference's top-level `fp32_allreduce` key forces wire_dtype to
    fp32 (the engine's `allreduce_always_fp32()` reflects the result).

    `hierarchy` factors the data axis for the two-level wire (ZeRO++
    arXiv:2306.10209 recipe): intra-group reduce-scatter, inter-group
    collective on the 1/inner shard, intra-group all-gather.  Per-level
    wire dtypes let the slow hop compress (bf16/split, or the blockwise
    int8/int4 quantized gathers — qgZ, comm/quant.py) while the fast
    hop stays exact.  The inner level is scatter-structured and cannot
    carry the gather-structured wires: a "split" request there lowers
    to fp32 with a log line (legacy behaviour), an EXPLICIT
    "wire_dtype_inner": "int8"/"int4" raises — a psum_scatter has no
    way to carry the per-block scales, and silently dropping a
    requested quantization would misreport the wire.
    """

    def __init__(self, param_dict, zero_config, world_size=None):
        super().__init__()
        d = param_dict.get(c.COMM) or {}
        self.gradient_reduction = str(get_scalar_param(
            d, c.COMM_GRADIENT_REDUCTION,
            c.COMM_GRADIENT_REDUCTION_DEFAULT)).lower()
        if self.gradient_reduction not in c.COMM_GRADIENT_REDUCTION_MODES:
            raise ValueError(
                f"comm.gradient_reduction must be one of "
                f"{c.COMM_GRADIENT_REDUCTION_MODES}, "
                f"got {self.gradient_reduction!r}")
        self.fp32_allreduce = bool(get_scalar_param(
            param_dict, c.FP32_ALLREDUCE, c.FP32_ALLREDUCE_DEFAULT))
        from .comm.bucketing import GATHER_WIRES, WIRE_MODES
        from .comm.quant import QUANT_WIRES, validate_block_size

        def wire_param(key, default):
            w = get_scalar_param(d, key, default)
            if w is None:
                return None
            w = str(w).lower()
            if w not in WIRE_MODES:
                # name the offending level AND the full valid set here —
                # a typo'd dtype must never fall through to a jit-time
                # failure inside the traced step program
                raise ValueError(f"comm.{key} must be one of {WIRE_MODES}, "
                                 f"got {w!r}")
            return "fp32" if self.fp32_allreduce else w

        self.wire_dtype = wire_param(c.COMM_WIRE_DTYPE,
                                     c.COMM_WIRE_DTYPE_DEFAULT)
        self.hierarchy = parse_comm_hierarchy(
            get_scalar_param(d, c.COMM_HIERARCHY, c.COMM_HIERARCHY_DEFAULT))
        if isinstance(self.hierarchy, int) and world_size is not None:
            check_hierarchy_divides(self.hierarchy, int(world_size))
        # per-level overrides default to the single-level wire; the
        # inner level can't carry the gather-structured split wire
        # (BucketPlan would re-materialize the full bucket), so it
        # falls back to exact fp32 there — the fast hop staying exact
        # is the recommended placement anyway (comm_tuning.md)
        inner_override = wire_param(c.COMM_WIRE_DTYPE_INNER, None)
        self.wire_dtype_inner = inner_override or self.wire_dtype
        self.wire_dtype_outer = wire_param(c.COMM_WIRE_DTYPE_OUTER, None) \
            or self.wire_dtype
        if inner_override in QUANT_WIRES:
            # an explicitly requested quantized inner wire cannot be
            # honored (the scatter level has nowhere to put the
            # per-block scales) and silently lowering it would
            # misreport the compression — reject, naming the level
            raise ValueError(
                f"comm.{c.COMM_WIRE_DTYPE_INNER} = {inner_override!r}: "
                "the int8/int4 wires are gather-structured (per-block "
                "scales cannot ride a psum_scatter) and cannot run the "
                "intra-group scatter level; use fp32 or bf16 for "
                f"{c.COMM_WIRE_DTYPE_INNER} and put the quantized wire "
                f"on {c.COMM_WIRE_DTYPE_OUTER}")
        if self.wire_dtype_inner in GATHER_WIRES:
            if inner_override is not None:
                # warn only on an EXPLICIT inner-split request; when it
                # is merely inherited from wire_dtype the flat path may
                # still run the split wire unchanged (hierarchy "auto"
                # can resolve flat), and on a factored mesh the engine's
                # BucketPlan log shows the effective per-level wires
                logger.warning(
                    "comm: the split wire is gather-structured and cannot "
                    "run the intra-group scatter level; wire_dtype_inner "
                    "lowers to fp32")
            self.wire_dtype_inner = "fp32"
        self.overlap = parse_comm_overlap(
            get_scalar_param(d, c.COMM_OVERLAP, c.COMM_OVERLAP_DEFAULT))

        def overlap_int(key, default, minimum=1):
            v = get_scalar_param(d, key, default)
            try:
                iv = int(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"comm.{key} must be an integer >= {minimum}, "
                    f"got {v!r}")
            if iv < minimum:
                raise ValueError(
                    f"comm.{key} must be >= {minimum}, got {iv}")
            return iv

        # the ticket deadline must fire BEFORE the hang watchdog does —
        # a named exchange timeout beats an anonymous stack snapshot
        # (StepWatchdog deadline guidance, docs/tutorials/resilience.md)
        self.overlap_timeout_ms = overlap_int(
            c.COMM_OVERLAP_TIMEOUT_MS, c.COMM_OVERLAP_TIMEOUT_MS_DEFAULT)
        self.overlap_reconnect_attempts = overlap_int(
            c.COMM_OVERLAP_RECONNECT_ATTEMPTS,
            c.COMM_OVERLAP_RECONNECT_ATTEMPTS_DEFAULT, minimum=0)
        self.overlap_reconnect_window_ms = overlap_int(
            c.COMM_OVERLAP_RECONNECT_WINDOW_MS,
            c.COMM_OVERLAP_RECONNECT_WINDOW_MS_DEFAULT)
        self.overlap_keepalive_ms = overlap_int(
            c.COMM_OVERLAP_KEEPALIVE_MS,
            c.COMM_OVERLAP_KEEPALIVE_MS_DEFAULT)
        self.reduce_bucket_size = int(get_scalar_param(
            d, c.COMM_REDUCE_BUCKET_SIZE, zero_config.reduce_bucket_size))
        block = get_scalar_param(d, c.COMM_QUANT_BLOCK_SIZE,
                                 c.COMM_QUANT_BLOCK_SIZE_DEFAULT)
        try:
            self.quant_block_size = validate_block_size(block)
        except ValueError as e:
            raise ValueError(f"comm.{c.COMM_QUANT_BLOCK_SIZE}: {e}")
        # MoE token movement: sorted dispatch + the explicit expert
        # all-to-all wire (moe/dispatch.py).  Parsed eagerly so a bad
        # sub-key fails at config time; the engine installs the result
        # process-globally at initialize().
        from ..moe.dispatch import parse_moe_config

        self.moe = parse_moe_config(d.get(c.COMM_MOE),
                                    default_block=self.quant_block_size)


class DeepSpeedDataPipelineConfig(DeepSpeedConfigObject):
    """Async input pipeline (runtime/dataloader.py PrefetchLoader +
    engine._DeviceFeed).

    "data_pipeline": {
      "enabled": true,          # master switch (default ON)
      "prefetch_depth": 2,      # bounded host queue, in batches
      "num_workers": 1,         # parallel collate threads
      "device_prefetch": true   # device_put batch N+1 during step N
    }

    Defaults ON: background collate + device double-buffering hide the
    host-side gap between step dispatches.  Correctness is unchanged —
    batch order is deterministic and the loss sequence is byte-identical
    with the pipeline off (tests/test_data_pipeline.py pins it across
    all three jitted step paths).  `prefetch_depth: 0` disables host
    prefetch while keeping device double-buffering, and vice versa.
    """

    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(c.DATA_PIPELINE) or {}
        known = {c.DATA_PIPELINE_ENABLED, c.DATA_PIPELINE_PREFETCH_DEPTH,
                 c.DATA_PIPELINE_NUM_WORKERS, c.DATA_PIPELINE_DEVICE_PREFETCH}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"data_pipeline: unknown key(s) {sorted(unknown)}; "
                f"expected a subset of {sorted(known)}")
        self.enabled = bool(get_scalar_param(
            d, c.DATA_PIPELINE_ENABLED, c.DATA_PIPELINE_ENABLED_DEFAULT))
        depth = get_scalar_param(d, c.DATA_PIPELINE_PREFETCH_DEPTH,
                                 c.DATA_PIPELINE_PREFETCH_DEPTH_DEFAULT)
        workers = get_scalar_param(d, c.DATA_PIPELINE_NUM_WORKERS,
                                   c.DATA_PIPELINE_NUM_WORKERS_DEFAULT)
        for name, val, lo in ((c.DATA_PIPELINE_PREFETCH_DEPTH, depth, 0),
                              (c.DATA_PIPELINE_NUM_WORKERS, workers, 1)):
            if isinstance(val, bool) or not isinstance(val, int) or val < lo:
                raise ValueError(
                    f"data_pipeline.{name} must be an int >= {lo}, "
                    f"got {val!r}")
        self.prefetch_depth = int(depth)
        self.num_workers = int(workers)
        self.device_prefetch = bool(get_scalar_param(
            d, c.DATA_PIPELINE_DEVICE_PREFETCH,
            c.DATA_PIPELINE_DEVICE_PREFETCH_DEFAULT))

    @property
    def host_prefetch(self) -> bool:
        """True when the background-thread host loop should engage."""
        return self.enabled and self.prefetch_depth > 0

    @property
    def device_feed(self) -> bool:
        """True when the engine should double-buffer batches on device."""
        return self.enabled and self.device_prefetch


class DeepSpeedFaultsConfig(DeepSpeedConfigObject):
    """Chaos-ready runtime (runtime/resilience.py).

    "faults": {
      "seed": 0,
      "enabled": true,          # injection gate; default: rules present
      "rules": [{"site": ..., "kind": "raise"|"delay_ms"|"corrupt"|
                 "hang"|"kill", ...schedule...}],
      "retry": {"max_attempts": 4, "base_delay_ms": 50,
                "max_delay_ms": 2000, "jitter": 0.25},
      "watchdog": {"enabled": false, "deadline_s": 600, "poll_s": 1.0,
                   "first_beat_mult": 4.0,  # pre-first-beat grace
                   "snapshot_dir": null}   # default: the monitor run dir
    }

    `rules` drive deterministic fault injection (every rule is validated
    here — a typo'd site key or kind fails at config time, never inside
    a training step); `retry` and `watchdog` are HARDENING knobs that
    apply whether or not injection is enabled.  The engine installs the
    plan/policy process-globally at initialize() and arms the watchdog
    beside the run monitor."""

    def __init__(self, param_dict):
        super().__init__()
        from .resilience import FaultPlan, RetryPolicy

        d = param_dict.get(c.FAULTS) or {}
        known = {c.FAULTS_ENABLED, c.FAULTS_SEED, c.FAULTS_RULES,
                 c.FAULTS_RETRY, c.FAULTS_WATCHDOG}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"faults: unknown key(s) {sorted(unknown)}; expected a "
                f"subset of {sorted(known)}")
        self.seed = int(get_scalar_param(d, c.FAULTS_SEED,
                                         c.FAULTS_SEED_DEFAULT))
        rules = d.get(c.FAULTS_RULES) or []
        if not isinstance(rules, list):
            raise ValueError(
                f"faults.{c.FAULTS_RULES} must be a list of rule objects, "
                f"got {type(rules).__name__}")
        enabled = d.get(c.FAULTS_ENABLED)
        try:
            # parse eagerly: rule validation errors belong to config time
            self.plan = FaultPlan.from_config(
                rules, seed=self.seed,
                enabled=None if enabled is None else bool(enabled))
        except ValueError as e:
            raise ValueError(f"faults.{c.FAULTS_RULES}: {e}")
        self.enabled = self.plan.enabled

        r = d.get(c.FAULTS_RETRY) or {}
        known_r = {c.FAULTS_RETRY_MAX_ATTEMPTS, c.FAULTS_RETRY_BASE_DELAY_MS,
                   c.FAULTS_RETRY_MAX_DELAY_MS, c.FAULTS_RETRY_JITTER}
        unknown = set(r) - known_r
        if unknown:
            raise ValueError(
                f"faults.{c.FAULTS_RETRY}: unknown key(s) {sorted(unknown)}; "
                f"expected a subset of {sorted(known_r)}")
        try:
            self.retry_policy = RetryPolicy(
                max_attempts=get_scalar_param(
                    r, c.FAULTS_RETRY_MAX_ATTEMPTS,
                    c.FAULTS_RETRY_MAX_ATTEMPTS_DEFAULT),
                base_delay_ms=get_scalar_param(
                    r, c.FAULTS_RETRY_BASE_DELAY_MS,
                    c.FAULTS_RETRY_BASE_DELAY_MS_DEFAULT),
                max_delay_ms=get_scalar_param(
                    r, c.FAULTS_RETRY_MAX_DELAY_MS,
                    c.FAULTS_RETRY_MAX_DELAY_MS_DEFAULT),
                jitter=get_scalar_param(r, c.FAULTS_RETRY_JITTER,
                                        c.FAULTS_RETRY_JITTER_DEFAULT))
        except ValueError as e:
            raise ValueError(f"faults.{c.FAULTS_RETRY}: {e}")

        w = d.get(c.FAULTS_WATCHDOG) or {}
        known_w = {c.FAULTS_WATCHDOG_ENABLED, c.FAULTS_WATCHDOG_DEADLINE_S,
                   c.FAULTS_WATCHDOG_POLL_S, c.FAULTS_WATCHDOG_SNAPSHOT_DIR,
                   c.FAULTS_WATCHDOG_FIRST_BEAT_MULT}
        unknown = set(w) - known_w
        if unknown:
            raise ValueError(
                f"faults.{c.FAULTS_WATCHDOG}: unknown key(s) "
                f"{sorted(unknown)}; expected a subset of {sorted(known_w)}")
        self.watchdog_enabled = bool(get_scalar_param(
            w, c.FAULTS_WATCHDOG_ENABLED, c.FAULTS_WATCHDOG_ENABLED_DEFAULT))
        self.watchdog_deadline_s = float(get_scalar_param(
            w, c.FAULTS_WATCHDOG_DEADLINE_S,
            c.FAULTS_WATCHDOG_DEADLINE_S_DEFAULT))
        self.watchdog_poll_s = float(get_scalar_param(
            w, c.FAULTS_WATCHDOG_POLL_S, c.FAULTS_WATCHDOG_POLL_S_DEFAULT))
        self.watchdog_snapshot_dir = get_scalar_param(
            w, c.FAULTS_WATCHDOG_SNAPSHOT_DIR, None)
        # grace multiplier on the deadline before the FIRST beat: covers
        # first-step compile — including an elastic restart's recompile
        # at the new mesh shape (StepWatchdog docstring).  An explicit
        # null selects the legacy mode: not armed at all until beat 1.
        fbm = (w[c.FAULTS_WATCHDOG_FIRST_BEAT_MULT]
               if c.FAULTS_WATCHDOG_FIRST_BEAT_MULT in w
               else c.FAULTS_WATCHDOG_FIRST_BEAT_MULT_DEFAULT)
        try:
            self.watchdog_first_beat_mult = (None if fbm is None
                                             else float(fbm))
        except (TypeError, ValueError):
            raise ValueError(
                f"faults.watchdog.{c.FAULTS_WATCHDOG_FIRST_BEAT_MULT} "
                f"must be a number >= 1 or null (null: never armed "
                f"before the first beat), got {fbm!r}")
        if self.watchdog_first_beat_mult is not None and \
                self.watchdog_first_beat_mult < 1.0:
            raise ValueError(
                f"faults.watchdog.{c.FAULTS_WATCHDOG_FIRST_BEAT_MULT} "
                f"must be >= 1 (a sub-1 multiplier would make the "
                f"compile window stricter than steady state), got "
                f"{self.watchdog_first_beat_mult}")
        if self.watchdog_enabled and self.watchdog_deadline_s <= 0:
            raise ValueError(
                f"faults.watchdog.{c.FAULTS_WATCHDOG_DEADLINE_S} must be "
                f"> 0, got {self.watchdog_deadline_s}")
        if self.watchdog_enabled and self.watchdog_poll_s <= 0:
            # poll_s 0 would busy-spin the daemon thread on a core
            raise ValueError(
                f"faults.watchdog.{c.FAULTS_WATCHDOG_POLL_S} must be "
                f"> 0, got {self.watchdog_poll_s}")


class DeepSpeedAutotuneConfig(DeepSpeedConfigObject):
    """The self-tuning runtime (runtime/autotune/).

    "autotune": {"enabled": false, "probe_steps": 2, "probe_warmup": 1,
                 "budget_s": null, "cache_path": null, "ledger_path":
                 null, "apply_winner": true, "min_improvement": 0.03,
                 "wire_dtypes": ["fp32","bf16","int8"],
                 "bucket_sizes": [], "include_overlap": true,
                 "online": {"enabled": false, "window": 5,
                            "baseline_steps": 5, "threshold": 1.5,
                            "exposed_threshold_ms": 0.0,
                            "cooldown_steps": 20, "check_every": 1,
                            "radius": 1, "safe_only": true}}

    `enabled` arms the runtime (engine.autotune_search() probes the
    legal candidate space, winner-cached by (model shape, mesh, fabric)
    fingerprint); `online.enabled` additionally watches every step
    boundary for sustained regression and live-retunes a bounded knob
    neighborhood.  Every knob is validated HERE so a typo fails at
    config time, not inside a probe."""

    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(c.AUTOTUNE) or {}
        known = {c.AUTOTUNE_ENABLED, c.AUTOTUNE_PROBE_STEPS,
                 c.AUTOTUNE_PROBE_WARMUP, c.AUTOTUNE_BUDGET_S,
                 c.AUTOTUNE_CACHE_PATH, c.AUTOTUNE_LEDGER_PATH,
                 c.AUTOTUNE_APPLY_WINNER, c.AUTOTUNE_MIN_IMPROVEMENT,
                 c.AUTOTUNE_WIRE_DTYPES, c.AUTOTUNE_BUCKET_SIZES,
                 c.AUTOTUNE_INCLUDE_OVERLAP, c.AUTOTUNE_ONLINE}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"autotune: unknown key(s) {sorted(unknown)}; expected a "
                f"subset of {sorted(known)}")
        self.enabled = bool(get_scalar_param(
            d, c.AUTOTUNE_ENABLED, c.AUTOTUNE_ENABLED_DEFAULT))

        def pos_int(key, default, minimum=1):
            v = get_scalar_param(d, key, default)
            if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
                raise ValueError(
                    f"autotune.{key} must be an int >= {minimum}, got {v!r}")
            return int(v)

        self.probe_steps = pos_int(c.AUTOTUNE_PROBE_STEPS,
                                   c.AUTOTUNE_PROBE_STEPS_DEFAULT)
        self.probe_warmup = pos_int(c.AUTOTUNE_PROBE_WARMUP,
                                    c.AUTOTUNE_PROBE_WARMUP_DEFAULT,
                                    minimum=0)
        budget = get_scalar_param(d, c.AUTOTUNE_BUDGET_S,
                                  c.AUTOTUNE_BUDGET_S_DEFAULT)
        if budget is not None:
            try:
                budget = float(budget)
            except (TypeError, ValueError):
                raise ValueError(
                    f"autotune.{c.AUTOTUNE_BUDGET_S} must be a positive "
                    f"number of seconds or null, got {budget!r}")
            if budget <= 0:
                raise ValueError(
                    f"autotune.{c.AUTOTUNE_BUDGET_S} must be > 0, "
                    f"got {budget}")
        self.budget_s = budget
        for key, attr in ((c.AUTOTUNE_CACHE_PATH, "cache_path"),
                          (c.AUTOTUNE_LEDGER_PATH, "ledger_path")):
            v = get_scalar_param(d, key, None)
            if v is not None and not isinstance(v, str):
                raise ValueError(
                    f"autotune.{key} must be a path string or null, "
                    f"got {v!r}")
            setattr(self, attr, v)
        self.apply_winner = bool(get_scalar_param(
            d, c.AUTOTUNE_APPLY_WINNER, c.AUTOTUNE_APPLY_WINNER_DEFAULT))
        mi = get_scalar_param(d, c.AUTOTUNE_MIN_IMPROVEMENT,
                              c.AUTOTUNE_MIN_IMPROVEMENT_DEFAULT)
        try:
            mi = float(mi)
        except (TypeError, ValueError):
            raise ValueError(
                f"autotune.{c.AUTOTUNE_MIN_IMPROVEMENT} must be a "
                f"fraction in [0, 1), got {mi!r}")
        if not 0.0 <= mi < 1.0:
            raise ValueError(
                f"autotune.{c.AUTOTUNE_MIN_IMPROVEMENT} must be a "
                f"fraction in [0, 1), got {mi}")
        self.min_improvement = mi
        from .comm.bucketing import WIRE_MODES

        wires = d.get(c.AUTOTUNE_WIRE_DTYPES,
                      list(c.AUTOTUNE_WIRE_DTYPES_DEFAULT))
        if not isinstance(wires, (list, tuple)) or not wires or \
                any(str(w).lower() not in WIRE_MODES for w in wires):
            raise ValueError(
                f"autotune.{c.AUTOTUNE_WIRE_DTYPES} must be a non-empty "
                f"list drawn from {WIRE_MODES}, got {wires!r}")
        self.wire_dtypes = tuple(str(w).lower() for w in wires)
        buckets = d.get(c.AUTOTUNE_BUCKET_SIZES,
                        list(c.AUTOTUNE_BUCKET_SIZES_DEFAULT))
        if not isinstance(buckets, (list, tuple)) or any(
                isinstance(b, bool) or not isinstance(b, int) or b < 1
                for b in buckets):
            raise ValueError(
                f"autotune.{c.AUTOTUNE_BUCKET_SIZES} must be a list of "
                f"positive element counts, got {buckets!r}")
        self.bucket_sizes = tuple(int(b) for b in buckets)
        self.include_overlap = bool(get_scalar_param(
            d, c.AUTOTUNE_INCLUDE_OVERLAP,
            c.AUTOTUNE_INCLUDE_OVERLAP_DEFAULT))

        o = d.get(c.AUTOTUNE_ONLINE) or {}
        known_o = {c.AUTOTUNE_ONLINE_ENABLED, c.AUTOTUNE_ONLINE_WINDOW,
                   c.AUTOTUNE_ONLINE_BASELINE_STEPS,
                   c.AUTOTUNE_ONLINE_THRESHOLD,
                   c.AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS,
                   c.AUTOTUNE_ONLINE_COOLDOWN_STEPS,
                   c.AUTOTUNE_ONLINE_CHECK_EVERY, c.AUTOTUNE_ONLINE_RADIUS,
                   c.AUTOTUNE_ONLINE_SAFE_ONLY}
        unknown = set(o) - known_o
        if unknown:
            raise ValueError(
                f"autotune.{c.AUTOTUNE_ONLINE}: unknown key(s) "
                f"{sorted(unknown)}; expected a subset of {sorted(known_o)}")

        def online_int(key, default, minimum=1):
            v = get_scalar_param(o, key, default)
            if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
                raise ValueError(
                    f"autotune.online.{key} must be an int >= {minimum}, "
                    f"got {v!r}")
            return int(v)

        self.online_enabled = bool(get_scalar_param(
            o, c.AUTOTUNE_ONLINE_ENABLED, c.AUTOTUNE_ONLINE_ENABLED_DEFAULT))
        self.online_window = online_int(c.AUTOTUNE_ONLINE_WINDOW,
                                        c.AUTOTUNE_ONLINE_WINDOW_DEFAULT)
        self.online_baseline_steps = online_int(
            c.AUTOTUNE_ONLINE_BASELINE_STEPS,
            c.AUTOTUNE_ONLINE_BASELINE_STEPS_DEFAULT)
        thr = get_scalar_param(o, c.AUTOTUNE_ONLINE_THRESHOLD,
                               c.AUTOTUNE_ONLINE_THRESHOLD_DEFAULT)
        try:
            thr = float(thr)
        except (TypeError, ValueError):
            raise ValueError(
                f"autotune.online.{c.AUTOTUNE_ONLINE_THRESHOLD} must be a "
                f"ratio > 1.0, got {thr!r}")
        if thr <= 1.0:
            raise ValueError(
                f"autotune.online.{c.AUTOTUNE_ONLINE_THRESHOLD} must be "
                f"> 1.0 (a ratio over the step-time baseline), got {thr}")
        self.online_threshold = thr
        exp = get_scalar_param(o, c.AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS,
                               c.AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS_DEFAULT)
        try:
            exp = float(exp)
        except (TypeError, ValueError):
            raise ValueError(
                f"autotune.online.{c.AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS} "
                f"must be a millisecond count >= 0 (0 disables), got {exp!r}")
        if exp < 0:
            raise ValueError(
                f"autotune.online.{c.AUTOTUNE_ONLINE_EXPOSED_THRESHOLD_MS} "
                f"must be >= 0 (0 disables the exposed trigger), got {exp}")
        self.online_exposed_threshold_ms = exp
        self.online_cooldown_steps = online_int(
            c.AUTOTUNE_ONLINE_COOLDOWN_STEPS,
            c.AUTOTUNE_ONLINE_COOLDOWN_STEPS_DEFAULT, minimum=0)
        self.online_check_every = online_int(
            c.AUTOTUNE_ONLINE_CHECK_EVERY,
            c.AUTOTUNE_ONLINE_CHECK_EVERY_DEFAULT)
        self.online_radius = online_int(c.AUTOTUNE_ONLINE_RADIUS,
                                        c.AUTOTUNE_ONLINE_RADIUS_DEFAULT)
        self.online_safe_only = bool(get_scalar_param(
            o, c.AUTOTUNE_ONLINE_SAFE_ONLY,
            c.AUTOTUNE_ONLINE_SAFE_ONLY_DEFAULT))


# accepted serving.kv_dtype spellings; must stay a superset of what
# serving.kv_cache.resolve_kv_dtype() resolves (kept local so the
# training-side config never imports the jax-heavy serving package)
SERVING_KV_DTYPES = ("bf16", "bfloat16", "fp16", "float16", "fp32",
                     "float32", "int8", "int4")


class DeepSpeedServingConfig(DeepSpeedConfigObject):
    """Inference-side knobs (deepspeed_tpu.serving).

    "serving": {"kv_dtype": null,
                "speculative": {"enabled": false, "draft_len": 4,
                                "ngram": 3},
                "prefix_cache": {"enabled": true, "min_match_blocks": 1,
                                 "session_ttl_s": 120.0},
                "fleet": {"replicas": 1, "queue_limit": 64,
                          "session_affinity": true}}

    `kv_dtype` selects the paged KV cache's storage mode: null stores
    at the param dtype; "bf16"/"fp16"/"fp32" store dense at that dtype;
    "int8"/"int4" store per-(row, head) quantized payload + fp16 scale
    pairs (runtime/comm/quant.py row kernels).  `speculative.enabled`
    arms self-speculative n-gram decoding: `draft_len` candidate tokens
    drafted host-side per verify step by an `ngram`-suffix match over
    the request's own context (no extra model).  `prefix_cache` governs
    block-level KV sharing (serving/kv_cache.py chain hashes) and the
    pinned-session residency window; `fleet` sizes the multi-replica
    router (serving/router.py).  Every knob is validated HERE so a typo
    fails at config time, not mid-serve; the autotuner's "serve" scope
    re-validates its candidate fragments through this class so the
    search space can never propose an illegal config."""

    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(c.SERVING) or {}
        known = {c.SERVING_KV_DTYPE, c.SERVING_SPECULATIVE,
                 c.SERVING_PREFIX_CACHE, c.SERVING_FLEET}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"serving: unknown key(s) {sorted(unknown)}; expected a "
                f"subset of {sorted(known)}")
        kv = get_scalar_param(d, c.SERVING_KV_DTYPE,
                              c.SERVING_KV_DTYPE_DEFAULT)
        if kv is not None:
            if not isinstance(kv, str) or \
                    kv.lower() not in SERVING_KV_DTYPES:
                raise ValueError(
                    f"serving.{c.SERVING_KV_DTYPE} must be null or one of "
                    f"{SERVING_KV_DTYPES}, got {kv!r}")
            kv = kv.lower()
        self.kv_dtype = kv

        s = d.get(c.SERVING_SPECULATIVE) or {}
        known_s = {c.SERVING_SPEC_ENABLED, c.SERVING_SPEC_DRAFT_LEN,
                   c.SERVING_SPEC_NGRAM}
        unknown = set(s) - known_s
        if unknown:
            raise ValueError(
                f"serving.{c.SERVING_SPECULATIVE}: unknown key(s) "
                f"{sorted(unknown)}; expected a subset of {sorted(known_s)}")
        self.spec_enabled = bool(get_scalar_param(
            s, c.SERVING_SPEC_ENABLED, c.SERVING_SPEC_ENABLED_DEFAULT))

        def spec_int(key, default, minimum=1):
            v = get_scalar_param(s, key, default)
            if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
                raise ValueError(
                    f"serving.speculative.{key} must be an int >= "
                    f"{minimum}, got {v!r}")
            return int(v)

        self.spec_draft_len = spec_int(c.SERVING_SPEC_DRAFT_LEN,
                                       c.SERVING_SPEC_DRAFT_LEN_DEFAULT)
        self.spec_ngram = spec_int(c.SERVING_SPEC_NGRAM,
                                   c.SERVING_SPEC_NGRAM_DEFAULT)

        p = d.get(c.SERVING_PREFIX_CACHE) or {}
        known_p = {c.SERVING_PREFIX_ENABLED,
                   c.SERVING_PREFIX_MIN_MATCH_BLOCKS,
                   c.SERVING_PREFIX_SESSION_TTL_S}
        unknown = set(p) - known_p
        if unknown:
            raise ValueError(
                f"serving.{c.SERVING_PREFIX_CACHE}: unknown key(s) "
                f"{sorted(unknown)}; expected a subset of {sorted(known_p)}")
        self.prefix_enabled = bool(get_scalar_param(
            p, c.SERVING_PREFIX_ENABLED, c.SERVING_PREFIX_ENABLED_DEFAULT))
        mm = get_scalar_param(p, c.SERVING_PREFIX_MIN_MATCH_BLOCKS,
                              c.SERVING_PREFIX_MIN_MATCH_BLOCKS_DEFAULT)
        if isinstance(mm, bool) or not isinstance(mm, int) or mm < 1:
            raise ValueError(
                f"serving.prefix_cache.{c.SERVING_PREFIX_MIN_MATCH_BLOCKS} "
                f"must be an int >= 1, got {mm!r}")
        self.prefix_min_match_blocks = int(mm)
        ttl = get_scalar_param(p, c.SERVING_PREFIX_SESSION_TTL_S,
                               c.SERVING_PREFIX_SESSION_TTL_S_DEFAULT)
        try:
            ttl = float(ttl)
        except (TypeError, ValueError):
            ttl = -1.0
        if ttl <= 0:
            raise ValueError(
                f"serving.prefix_cache.{c.SERVING_PREFIX_SESSION_TTL_S} "
                f"must be a second count > 0, got "
                f"{p.get(c.SERVING_PREFIX_SESSION_TTL_S)!r}")
        self.session_ttl_s = ttl

        f = d.get(c.SERVING_FLEET) or {}
        known_f = {c.SERVING_FLEET_REPLICAS, c.SERVING_FLEET_QUEUE_LIMIT,
                   c.SERVING_FLEET_SESSION_AFFINITY}
        unknown = set(f) - known_f
        if unknown:
            raise ValueError(
                f"serving.{c.SERVING_FLEET}: unknown key(s) "
                f"{sorted(unknown)}; expected a subset of {sorted(known_f)}")

        def fleet_int(key, default):
            v = get_scalar_param(f, key, default)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"serving.fleet.{key} must be an int >= 1, got {v!r}")
            return int(v)

        self.fleet_replicas = fleet_int(c.SERVING_FLEET_REPLICAS,
                                        c.SERVING_FLEET_REPLICAS_DEFAULT)
        self.fleet_queue_limit = fleet_int(
            c.SERVING_FLEET_QUEUE_LIMIT, c.SERVING_FLEET_QUEUE_LIMIT_DEFAULT)
        self.fleet_session_affinity = bool(get_scalar_param(
            f, c.SERVING_FLEET_SESSION_AFFINITY,
            c.SERVING_FLEET_SESSION_AFFINITY_DEFAULT))

    def to_serve_kwargs(self):
        """The ServeConfig fragment this block selects: feed as
        `ServeConfig(**cfg.serving_config.to_serve_kwargs(), ...)`.
        Disabled speculation maps to draft_len=0 (the engine's plain
        decode path), not a missing key, so the serve-scope autotuner
        can diff candidate fragments field-for-field."""
        return {
            "kv_dtype": self.kv_dtype,
            "draft_len": self.spec_draft_len if self.spec_enabled else 0,
            "spec_ngram": self.spec_ngram,
            "prefix_cache": self.prefix_enabled,
            "prefix_min_match_blocks": self.prefix_min_match_blocks,
            "session_ttl_s": self.session_ttl_s,
        }

    def to_fleet_kwargs(self):
        """The FleetRouter sizing this block selects: feed as
        `FleetRouter(build_fleet(..., replicas=k['replicas']),
        queue_limit=k['queue_limit'], ...)`."""
        return {
            "replicas": self.fleet_replicas,
            "queue_limit": self.fleet_queue_limit,
            "session_affinity": self.fleet_session_affinity,
        }


class DeepSpeedKernelsConfig(DeepSpeedConfigObject):
    """The Pallas kernel registry's selection block
    (deepspeed_tpu.kernels — reference analogue: op_builder's
    DS_BUILD_* extension switches).

    "kernels": {"impl": "auto", "ops": {}, "interpret": false,
                "counters": true}

    Validation delegates to `kernels.registry.parse_kernels_config` —
    THE validator the registry's own context manager and the
    autotuner's "kernel" scope also use, so an unknown op name or impl
    value fails at config time naming the registered set, never inside
    a traced program.  The engine installs the parsed `KernelConfig`
    process-globally at initialize()."""

    def __init__(self, param_dict):
        super().__init__()
        from ..kernels.registry import parse_kernels_config

        try:
            self.config = parse_kernels_config(
                param_dict.get(c.KERNELS) or {})
        except ValueError as e:
            raise DeepSpeedConfigError(str(e))
        self.impl = self.config.impl
        self.ops = dict(self.config.ops)
        self.interpret = self.config.interpret
        self.counters = self.config.counters


def get_fp16_enabled(param_dict):
    return get_scalar_param(param_dict.get(c.FP16, {}), c.FP16_ENABLED,
                            c.FP16_ENABLED_DEFAULT)


def get_precision(param_dict):
    """Return the compute dtype name. Two spellings are accepted: the
    EleutherAI fork's fp16 section with "type": "bfloat16" (reference
    runtime/constants.py:127-161, engine.py:613-620), and the top-level
    `{"bf16": {"enabled": true}}` section of later DeepSpeed versions —
    the latter was previously IGNORED (silently training in fp32)."""
    bf16 = param_dict.get("bf16", param_dict.get("bfloat16", {})) or {}
    if get_scalar_param(bf16, c.FP16_ENABLED, False):
        if get_fp16_enabled(param_dict):
            raise DeepSpeedConfigError(
                "bf16 and fp16 cannot both be enabled")
        return "bfloat16"
    if not get_fp16_enabled(param_dict):
        return "float32"
    raw = get_scalar_param(param_dict.get(c.FP16, {}), c.FP16_TYPE,
                           c.FP16_TYPE_DEFAULT)
    if raw not in TORCH_DTYPES:
        raise DeepSpeedConfigError(
            f"fp16.type must be one of {sorted(set(TORCH_DTYPES))}, got {raw!r}")
    return TORCH_DTYPES[raw]


class DeepSpeedConfig(DeepSpeedConfigObject):
    def __init__(self, json_file_or_dict, mpu=None, param_dict=None,
                 world_size=None):
        super().__init__()
        if param_dict is not None:
            self._param_dict = param_dict
        elif isinstance(json_file_or_dict, dict):
            self._param_dict = json_file_or_dict
        elif isinstance(json_file_or_dict, str):
            try:
                with open(json_file_or_dict) as f:
                    self._param_dict = json.load(
                        f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
            except FileNotFoundError:
                raise DeepSpeedConfigError(
                    f"DeepSpeed config file not found: {json_file_or_dict}")
        else:
            raise DeepSpeedConfigError(
                "config must be a dict or a path to a json file, got "
                f"{type(json_file_or_dict)}")

        # world size for the batch triple: dp size (reference uses dist world
        # / mp size; here it's device_count / (model*pipe*seq axes))
        if world_size is not None:
            self.world_size = int(world_size)
        elif mpu is not None:
            self.world_size = int(mpu.get_data_parallel_world_size())
        else:
            self.world_size = self._infer_dp_world_size()

        # Elasticity resolves the batch triple before parsing it
        # (reference runtime/config.py:537-614).
        self.elasticity_enabled = elasticity_enabled(self._param_dict)
        if self.elasticity_enabled:
            elastic_dict = self._param_dict[ec.ELASTICITY]
            ensure_immutable_elastic_config(elastic_dict)
            final_batch_size, valid_gpus, micro_batch = compute_elastic_config(
                self._param_dict, world_size=self.world_size)
            self.elastic_valid_world_sizes = valid_gpus
            ignore = elastic_dict.get(ec.IGNORE_NON_ELASTIC_BATCH_INFO,
                                      ec.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
            batch_keys = (c.TRAIN_BATCH_SIZE, c.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                          c.GRADIENT_ACCUMULATION_STEPS)
            if not ignore and any(k in self._param_dict for k in batch_keys):
                raise ElasticityConfigError(
                    f"batch size keys {batch_keys} must not be set when "
                    f"elasticity is enabled (set "
                    f"'{ec.IGNORE_NON_ELASTIC_BATCH_INFO}': true to override)")
            self._param_dict = dict(self._param_dict)
            self._param_dict[c.TRAIN_BATCH_SIZE] = final_batch_size
            self._param_dict[c.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch
            self._param_dict[c.GRADIENT_ACCUMULATION_STEPS] = (
                final_batch_size // (micro_batch * self.world_size))

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _infer_dp_world_size(self):
        mesh_dict = self._param_dict.get(c.MESH) or {}
        try:
            import jax

            n = jax.device_count()
        except Exception:
            n = 1
        non_dp = 1
        for axis in ("model", "pipe", "seq"):
            non_dp *= max(1, int(mesh_dict.get(axis, 1)))
        dp = mesh_dict.get("data", -1)
        if dp in (-1, None):
            dp = max(1, n // non_dp)
        return int(dp)

    # -- parsing ----------------------------------------------------------

    def _initialize_params(self, pd):
        self.train_batch_size = get_scalar_param(pd, c.TRAIN_BATCH_SIZE,
                                                 c.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            pd, c.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            c.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_scalar_param(
            pd, c.GRADIENT_ACCUMULATION_STEPS,
            c.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = get_scalar_param(pd, c.STEPS_PER_PRINT,
                                                c.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(pd, c.DUMP_STATE, c.DUMP_STATE_DEFAULT)
        self.disable_allgather = get_scalar_param(pd, c.DISABLE_ALLGATHER,
                                                  c.DISABLE_ALLGATHER_DEFAULT)

        self.gradient_clipping = get_scalar_param(pd, c.GRADIENT_CLIPPING,
                                                  c.GRADIENT_CLIPPING_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(
            pd, c.SPARSE_GRADIENTS, c.SPARSE_GRADIENTS_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, c.PRESCALE_GRADIENTS,
                                                   c.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            pd, c.GRADIENT_PREDIVIDE_FACTOR, c.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig(pd)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        # gradient-reduction wire (runtime/comm/bucketing.py)
        self.comm_config = DeepSpeedCommConfig(pd, self.zero_config,
                                               world_size=self.world_size)

        # async input pipeline (runtime/dataloader.py PrefetchLoader +
        # engine._DeviceFeed) — default ON
        self.data_pipeline_config = DeepSpeedDataPipelineConfig(pd)

        # chaos-ready runtime: fault injection + retry + watchdog
        # (runtime/resilience.py)
        self.faults_config = DeepSpeedFaultsConfig(pd)

        # the self-tuning runtime (runtime/autotune/): fingerprinted
        # config search + the online retune loop
        self.autotune_config = DeepSpeedAutotuneConfig(pd)

        # inference-side knobs (deepspeed_tpu.serving): KV cache storage
        # dtype + self-speculative decoding — the autotuner's "serve"
        # scope searches this block
        self.serving_config = DeepSpeedServingConfig(pd)

        # Pallas kernel registry selection (deepspeed_tpu.kernels) —
        # the autotuner's "kernel" scope searches this block
        self.kernels_config = DeepSpeedKernelsConfig(pd)

        # pipeline: use_p2p_channels forces the multi-host channel
        # executor even single-process (the driver's virtual-multichip
        # dryrun runs the real cross-process code path this way)
        self.pipe_use_p2p_channels = bool(
            (pd.get("pipeline") or {}).get("use_p2p_channels", False))
        # debug_schedule selects the per-event interpreted schedule walk
        # (the parity oracle / bring-up executor) instead of the default
        # precompiled flat program (runtime/pipe/compiler.py)
        self.pipe_debug_schedule = bool(
            (pd.get("pipeline") or {}).get("debug_schedule", False))

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(pd)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(pd)

        # precision
        self.fp16_enabled = get_fp16_enabled(pd)
        self.precision = get_precision(pd)
        fp16_dict = pd.get(c.FP16, {})
        self.loss_scale = get_scalar_param(fp16_dict, c.FP16_LOSS_SCALE,
                                           c.FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = get_scalar_param(
            fp16_dict, c.FP16_INITIAL_SCALE_POWER,
            c.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = get_scalar_param(
            fp16_dict, c.FP16_LOSS_SCALE_WINDOW, c.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = get_scalar_param(fp16_dict, c.FP16_HYSTERESIS,
                                           c.FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = get_scalar_param(fp16_dict, c.FP16_MIN_LOSS_SCALE,
                                               c.FP16_MIN_LOSS_SCALE_DEFAULT)
        self.amp_enabled = get_scalar_param(pd.get(c.AMP, {}), c.AMP_ENABLED,
                                            c.AMP_ENABLED_DEFAULT)
        self.amp_params = {k: v for k, v in pd.get(c.AMP, {}).items()
                           if k != c.AMP_ENABLED}

        # optimizer / scheduler
        opt_dict = pd.get(c.OPTIMIZER, None)
        self.optimizer_name = (opt_dict.get(c.TYPE).lower()
                               if opt_dict and opt_dict.get(c.TYPE) else None)
        self.optimizer_params = (opt_dict.get(c.OPTIMIZER_PARAMS, {})
                                 if opt_dict else None)
        self.optimizer_legacy_fusion = (get_scalar_param(
            opt_dict, c.LEGACY_FUSION, c.LEGACY_FUSION_DEFAULT)
            if opt_dict else c.LEGACY_FUSION_DEFAULT)
        self.zero_allow_untested_optimizer = get_scalar_param(
            pd, c.ZERO_ALLOW_UNTESTED_OPTIMIZER,
            c.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        sched_dict = pd.get(c.SCHEDULER, None)
        self.scheduler_name = sched_dict.get(c.TYPE) if sched_dict else None
        self.scheduler_params = (sched_dict.get(c.SCHEDULER_PARAMS, {})
                                 if sched_dict else None)

        # observability
        self.wall_clock_breakdown = get_scalar_param(
            pd, c.WALL_CLOCK_BREAKDOWN, c.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(pd, c.MEMORY_BREAKDOWN,
                                                 c.MEMORY_BREAKDOWN_DEFAULT)
        # structured run telemetry (monitor/): JSONL event stream,
        # profiler capture window, heartbeats — TensorBoard is one sink
        self.monitor_config = DeepSpeedMonitorConfig(pd)
        tb = pd.get(c.TENSORBOARD, {})
        self.tensorboard_enabled = get_scalar_param(tb, c.TENSORBOARD_ENABLED,
                                                    c.TENSORBOARD_ENABLED_DEFAULT)
        self.tensorboard_output_path = get_scalar_param(
            tb, c.TENSORBOARD_OUTPUT_PATH, c.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.tensorboard_job_name = get_scalar_param(
            tb, c.TENSORBOARD_JOB_NAME, c.TENSORBOARD_JOB_NAME_DEFAULT)

        # progressive layer drop
        pld = pd.get(c.PROGRESSIVE_LAYER_DROP, {})
        self.pld_enabled = get_scalar_param(pld, c.PLD_ENABLED, c.PLD_ENABLED_DEFAULT)
        self.pld_params = ({c.PLD_THETA: get_scalar_param(pld, c.PLD_THETA,
                                                          c.PLD_THETA_DEFAULT),
                            c.PLD_GAMMA: get_scalar_param(pld, c.PLD_GAMMA,
                                                          c.PLD_GAMMA_DEFAULT)}
                           if self.pld_enabled else False)

        ckpt = pd.get(c.CHECKPOINT, {})
        self.checkpoint_tag_validation_mode = str(get_scalar_param(
            ckpt, c.CHECKPOINT_TAG_VALIDATION,
            c.CHECKPOINT_TAG_VALIDATION_DEFAULT)).lower()
        self.checkpoint_tag_validation_enabled = \
            self.checkpoint_tag_validation_mode != "ignore"
        self.checkpoint_tag_validation_fail = \
            self.checkpoint_tag_validation_mode == "fail"
        # TPU addition: overlap checkpoint serialization with training
        # (serialize+write+commit land on background threads; the commit
        # marker and 'latest' update last — runtime/checkpointing.py)
        self.checkpoint_async_save = bool(get_scalar_param(
            ckpt, c.CHECKPOINT_ASYNC_SAVE, c.CHECKPOINT_ASYNC_SAVE_DEFAULT))
        self.checkpoint_commit_timeout_ms = int(get_scalar_param(
            ckpt, c.CHECKPOINT_COMMIT_TIMEOUT_MS,
            c.CHECKPOINT_COMMIT_TIMEOUT_MS_DEFAULT))
        if self.checkpoint_commit_timeout_ms <= 0:
            raise ValueError(
                f"checkpoint.{c.CHECKPOINT_COMMIT_TIMEOUT_MS} must be a "
                f"positive millisecond count, got "
                f"{self.checkpoint_commit_timeout_ms}")
        # SIGTERM = save-if-possible (elasticity/supervisor.py): a set
        # preempt_save_dir arms the engine's signal handler — emergency
        # checkpoint at the next step boundary, then a clean exit
        preempt = get_scalar_param(ckpt, c.CHECKPOINT_PREEMPT_SAVE_DIR,
                                   c.CHECKPOINT_PREEMPT_SAVE_DIR_DEFAULT)
        if preempt is not None and not isinstance(preempt, str):
            raise ValueError(
                f"checkpoint.{c.CHECKPOINT_PREEMPT_SAVE_DIR} must be a "
                f"directory path string or null, got {preempt!r}")
        self.checkpoint_preempt_save_dir = preempt

        self.sparse_attention = pd.get(c.SPARSE_ATTENTION, None)
        self.vocabulary_size = get_scalar_param(pd, c.VOCABULARY_SIZE,
                                                c.VOCABULARY_SIZE_DEFAULT)

        # TPU additions
        self.mesh_shape = pd.get(c.MESH, c.MESH_DEFAULT)

    # -- batch triple (reference config.py:681-752) -----------------------

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = self.world_size

        if all(x is not None for x in (train_batch, micro_batch, grad_acc)):
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * dp
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // dp
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * dp
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = self.world_size
        if not (train_batch > 0 and micro_batch > 0 and grad_acc > 0):
            raise DeepSpeedConfigError(
                f"batch sizes must be positive: train_batch_size={train_batch}, "
                f"micro_batch={micro_batch}, grad_acc={grad_acc}")
        if train_batch != micro_batch * grad_acc * dp:
            raise DeepSpeedConfigError(
                f"Check batch related parameters: train_batch_size={train_batch} "
                f"is not equal to micro_batch_per_gpu({micro_batch}) * "
                f"gradient_acc_steps({grad_acc}) * world_size({dp})")

    # -- sanity (reference config.py _do_sanity_check) --------------------

    def _do_sanity_check(self):
        if self.optimizer_name is not None and self.zero_enabled:
            if (self.optimizer_name not in c.DEEPSPEED_OPTIMIZERS and
                    not self.zero_allow_untested_optimizer):
                logger.warning(
                    f"optimizer '{self.optimizer_name}' is untested with ZeRO; "
                    f"set '{c.ZERO_ALLOW_UNTESTED_OPTIMIZER}': true to silence")
        if self.zero_config.stage == 2 and not self.fp16_enabled:
            # reference requires fp16 for ZeRO>0; bf16/fp32 work fine on TPU,
            # keep a log line for parity awareness only
            logger.debug("ZeRO-2 without reduced precision (allowed on TPU)")

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        for k in sorted(self.__dict__):
            if not k.startswith("_"):
                logger.info(f"  {k} = {self.__dict__[k]}")
