"""DeepSpeedEngine — the core training engine.

Reference: deepspeed/runtime/engine.py:102 (DeepSpeedEngine(Module) with
forward :959 / backward :1040 / step :1201, optimizer selection :647,
checkpoint I/O :1491-1890). The public API is kept — forward/backward/step,
gradient-accumulation boundaries, loss scaling, save/load_checkpoint — but
the execution model is TPU-native:

* One jitted `_micro_step` computes loss+grads for a micro batch and folds
  them into a (possibly ZeRO-sharded) fp32 accumulator. Data parallelism is
  implicit by default: the batch is sharded over the `data` mesh axis and
  the loss is a global mean, so XLA inserts the gradient psum — right on
  ICI where the per-leaf psums overlap the backward. With
  `"comm": {"gradient_reduction": "bucketed"}` the same step instead
  computes LOCAL grads under shard_map and reduces them through the
  static BucketPlan (runtime/comm/bucketing.py): one fused collective
  per dtype bucket — the reference's `reduce_bucket_size` machinery
  (engine.py:1323-1396, zero/stage2.py:614-745), measured 2x+ faster on
  serialization-bound fabrics (BENCH.md grad-wire round).
* One jitted `_apply_step` unscales, checks overflow, clips, runs the fused
  optimizer, applies ZeRO sharding constraints, and updates the loss-scale
  state — the skip-on-overflow decision is a branchless select inside the
  same program (contrast reference fp16/loss_scaler + stage2.step).
* ZeRO stages are sharding plans (runtime/zero/partition.py), not optimizer
  wrappers: stage 1 shards optimizer state, stage 2 shards the gradient
  accumulator (psum becomes reduce-scatter), stage 3 shards parameters.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import comm
from ..comm.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                         MeshInfo)
from ..monitor.counters import COUNTERS
from ..ops.adam import DeepSpeedCPUAdam, FusedAdam
from ..ops.lamb import FusedLamb
from ..utils.logging import log_dist, logger
from . import checkpointing as ckpt_io
from . import constants as const
from .config import DeepSpeedConfig
from .dataloader import (DeepSpeedDataLoader, PrefetchLoader,
                         RepeatingLoader, timed_next)
from . import resilience
from .fp16.loss_scaler import create_loss_scaler
from .fp16.onebit import OnebitAdam, OnebitLamb
from .lr_schedules import SCHEDULERS
from .module import TrainModule
from .comm.bucketing import BucketPlan
from .pipe.p2p import batch_shardable
from .progressive_layer_drop import ProgressiveLayerDrop
from .utils import ThroughputTimer, has_overflow
from ..utils.timer import SynchronizedWallClockTimer
from .zero.partition import ZeroShardingPlan

DTYPES = {"float32": jnp.float32, "float16": jnp.float16,
          "bfloat16": jnp.bfloat16}

# deferred steps_per_print log entries kept in flight before the oldest
# is force-settled (each holds one device scalar; tiny either way)
_STEP_LOG_RING = 4


class _DeviceFeed:
    """Device-side double buffering for the input pipeline.

    Owns a host iterator and keeps AT MOST ONE batch placed on device
    ahead of the consumer: `next()` returns the current step's batch
    (fetch+place synchronously only on the first call or when lookahead
    is off); `schedule()` — called right after a step program is
    dispatched — pulls batch N+1 from the host iterator (an instant
    queue pop when PrefetchLoader runs underneath) and enqueues its
    `device_put` toward the NamedSharding target, so the H2D transfer
    runs while step N's program computes.

    Donation-safe by construction: batch arguments are never in the step
    programs' donate_argnums and every place() builds fresh device
    arrays, so rotating to the next buffer cannot alias storage a
    running program still reads.

    `lookahead` engages only for the engine-owned training iterator:
    prefetching ahead of a USER-supplied iterator would consume batches
    the caller may still expect to own.
    """

    _EMPTY = object()

    def __init__(self, source, fetch, place, scan: bool,
                 lookahead: bool = True):
        self.source = source          # identity key (the host iterator)
        self.scan = scan              # payload unit: stacked global batch?
        self._fetch = fetch
        self._place = place
        self._lookahead = lookahead
        self._pending = self._EMPTY
        self._exhausted = False

    @property
    def has_pending(self) -> bool:
        return self._pending is not self._EMPTY

    def next(self):
        if self._pending is not self._EMPTY:
            batch = self._pending
            self._pending = self._EMPTY
            return batch
        if self._exhausted:
            raise StopIteration
        return self._place(self._fetch())

    def schedule(self) -> None:
        """Fetch + device-place the NEXT batch; call right after the
        step dispatch returns (the program runs while this transfers)."""
        if not self._lookahead or self._exhausted or \
                self._pending is not self._EMPTY:
            return
        try:
            host = self._fetch()
        except StopIteration:
            self._exhausted = True
            return
        self._pending = self._place(host)


class DeepSpeedEngine:
    def __init__(self, args=None, model: Optional[TrainModule] = None,
                 optimizer=None, model_parameters=None, training_data=None,
                 lr_scheduler=None, mpu=None, dist_init_required=None,
                 collate_fn=None, config_params=None, dont_change_device=False):
        if model is None:
            raise ValueError("deepspeed_tpu.initialize requires a model")
        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.mpu = mpu
        self.collate_fn = collate_fn
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self._skipped_steps = 0
        self.loaded_checkpoint_tag = None

        if dist_init_required is None or dist_init_required:
            comm.init_distributed()

        config = config_params
        if config is None and args is not None:
            config = getattr(args, "deepspeed_config", None)
        if config is None:
            raise ValueError(
                "DeepSpeed requires --deepspeed_config or a config dict")

        # elastic handoff BEFORE the mesh: the supervisor's
        # DSTPU_SURVIVING_WORLD drives the dp width the mesh is built
        # at, and a garbled handoff must fail here, loudly, not train
        # at the wrong world size (elasticity/elastic_env.py validates)
        self._elastic = self._read_elastic_env()

        # mesh first (config's dp world size derives from it)
        self.mesh_info = self._build_mesh(config, mpu)
        self._config = DeepSpeedConfig(
            config, world_size=self.mesh_info.get_data_parallel_world_size())
        self.dp_world_size = self.mesh_info.get_data_parallel_world_size()
        self.mp_world_size = self.mesh_info.get_model_parallel_world_size()

        # MoE token movement: install the validated comm.moe selection
        # process-globally BEFORE params are placed (the sharding plan's
        # expert-spec translation and the layer's dispatch engine both
        # read it) — moe/dispatch.py
        from ..moe import dispatch as _moe_dispatch

        _moe_dispatch.set_wire_config(self._config.comm_config.moe)
        if self._config.comm_config.moe != _moe_dispatch.MoEWireConfig():
            log_dist(self._config.comm_config.moe.describe(), ranks=[0])

        # Pallas kernel registry: install the validated "kernels" block
        # the same way (selection is read at trace time, so this must
        # precede the first compiled program) — kernels/registry.py
        from ..kernels import registry as _kernel_registry

        _kernel_registry.set_kernel_config(self._config.kernels_config.config)
        if self._config.kernels_config.config != _kernel_registry.KernelConfig():
            log_dist(self._config.kernels_config.config.describe(), ranks=[0])

        self.compute_dtype = DTYPES[self._config.precision]
        self.loss_scaler = create_loss_scaler(self._config)

        if self._config.sparse_gradients_enabled:
            # documented divergence from reference engine.py:1397-1449
            # (CSR allreduce of embedding grads): in-jit DP reduction is a
            # fused XLA psum riding ICI, where a row-sparse wire format
            # (dynamic row counts -> retrace/padding) costs more than the
            # dense collective it replaces. The config key is accepted for
            # parity; CSRTensor serves host-side/out-of-jit exchange.
            log_dist("sparse_gradients: accepted for API parity; in-jit "
                     "DP reduction stays dense (XLA psum over ICI)",
                     ranks=[0])

        # parameters: user-supplied pytree wins, else model.init
        key = jax.random.PRNGKey(int(os.environ.get("DSTPU_SEED", 42)))
        self._rng_key, init_key = jax.random.split(key)

        # ZeRO-Infinity: stage 3 + offload_param streams params from host
        # — the full tree is NEVER materialized on device (larger-than-HBM
        # models; reference zero/stage3.py + swap_tensor paging)
        self._infinity = self._configure_infinity(init_key)
        if self._infinity is not None:
            if model_parameters is not None:
                # user-supplied weights become the host masters
                self._infinity.load_masters_tree(model_parameters)
            self._finish_infinity_init(lr_scheduler, training_data)
            return

        if model_parameters is not None:
            params = model_parameters
        else:
            params = model.init(init_key)
        params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, dtype=jnp.float32), params)  # fp32 master

        # ZeRO sharding plan + placement
        self.zero_plan = ZeroShardingPlan(
            self._config.zero_optimization_stage, self.mesh_info, params,
            param_specs=getattr(model, "param_specs", None))
        self._params = jax.device_put(params, self.zero_plan.param_shardings())
        log_dist(self.zero_plan.describe(), ranks=[0])

        # optimizer
        self.optimizer = self._configure_optimizer()
        self._offload = self._configure_offload(params)
        if self._offload is not None:
            # optimizer state lives on host (RAM or NVMe); device keeps
            # compute-dtype working weights only
            self._params = jax.device_put(
                jax.tree_util.tree_map(
                    lambda p: p.astype(self.compute_dtype)
                    if jnp.issubdtype(p.dtype, jnp.floating) else p, params),
                self.zero_plan.param_shardings())
            self._opt_state = None
        else:
            opt_state = self.optimizer.init(self._params)
            self._opt_state = jax.device_put(
                opt_state, self.zero_plan.opt_state_shardings(opt_state))
        self._scaler_state = self.loss_scaler.jit_state()
        self._grad_acc = None  # lazily built zeros, sharded per grad_spec
        self._cached = None    # (loss, grads) from forward awaiting backward

        # lr scheduler
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)

        # progressive layer drop
        self.progressive_layer_drop = None
        if self._config.pld_enabled:
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self._config.pld_params[const.PLD_THETA],
                gamma=self._config.pld_params[const.PLD_GAMMA])

        # data
        self.training_dataloader = (self.deepspeed_io(training_data)
                                    if training_data is not None else None)

        self._init_hook_state()

        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.steps_per_print() or 50)
        self.bucket_plan = self._build_bucket_plan()
        self._qwz_gather = self._build_qwz_gather()
        self._overlap_mode = self._resolve_overlap()
        self._build_overlap()
        self._step_fns = self._build_step_fns()
        self._last_lr = self._current_lr()

        # observability (reference engine.py:177-181, 966-1019, 1058-1068)
        self.timers = SynchronizedWallClockTimer()
        self._wall_clock_breakdown = bool(self._config.wall_clock_breakdown)
        self.monitor = None
        if self._config.tensorboard_enabled and comm.get_rank() == 0:
            from ..utils.tensorboard import TensorBoardMonitor
            self.monitor = TensorBoardMonitor(
                self._config.tensorboard_output_path,
                self._config.tensorboard_job_name)
        self._flops_profiled = False
        self._last_loss = None
        self._pending_overflow = None
        self._pending_full = None
        self._device_feed = None        # owned-iterator double buffer
        self._user_device_feed = None   # latest user-iterator feed
        self._step_log_ring = deque()   # deferred steps_per_print scalars
        self.run_monitor = self._init_run_monitor()
        self._watchdog = self._init_resilience()
        self._register_exchange_watchdog()
        self._init_preemption()
        self._autotune_batch = None     # last sharded batch (probe replay)
        self._autotuner = self._init_autotune()

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------

    def _init_hook_state(self):
        """Layer-output hooks + gradient stashing (EleutherAI fork
        additions, reference engine.py:222-254 and :139-140,1156-1161)."""
        self.layer_outputs = {}
        self.layers_to_hook = []
        self.layer_name_pattern = "transformerlayer"
        self.hooks = []  # API parity; JAX has no hook handles
        self._capture_layers = None
        self._store_gradients = False
        self.store_gradients_cpu = False
        self.stored_gradients = None
        self.training = True  # torch Module-parity default (train()/eval())

    def _configure_infinity(self, init_key):
        zc = self._config.zero_config
        if not (self._config.zero_optimization_stage >= 3
                and zc.offload_param is not None
                and hasattr(self.module, "stream_init")):
            return None
        from .zero.infinity import InfinityRuntime

        hparams = dict(self._config.optimizer_params or {})
        adam_w = bool(hparams.pop(const.ADAM_W_MODE, True))
        # offload_param nvme -> masters page through the aio engine
        # (reference partitioned_param_swapper.py:223-277); any nvme path
        # also pages the Adam moments (offload_optimizer nvme covers the
        # moments-only configuration)
        on_nvme = zc.offload_param.device == "nvme"
        opt = zc.offload_optimizer
        opt_nvme = opt is not None and opt.device == "nvme"
        nvme = (zc.offload_param.nvme_path if on_nvme
                else opt.nvme_path if opt_nvme else None)
        return InfinityRuntime(self.module, init_key, hparams,
                               adam_w_mode=adam_w,
                               compute_dtype=self.compute_dtype,
                               nvme_path=nvme,
                               params_on_nvme=on_nvme)

    def _finish_infinity_init(self, lr_scheduler, training_data=None):
        """Minimal engine state for the streamed path (no device param
        tree, no jitted step fns, no zero plan)."""
        self._params = None
        self._opt_state = None
        self._offload = None
        self.zero_plan = None
        self._qwz_gather = None
        self._grad_acc = None
        self._cached = None
        self._overlap_mode = None
        self._overlap_exchange = None
        self._qwz_overlap = None
        self._overlap_pending = []
        cc = getattr(self._config, "comm_config", None)
        mode = getattr(cc, "overlap", "none") if cc is not None else "none"
        if mode != "none":
            # satellite contract: a requested overlap NEVER silently
            # no-ops — Infinity streams per-block grads host-side and
            # owns its own pipelining ("on" warns, "auto" informs,
            # matching _resolve_overlap)
            msg = ("comm.overlap requested but ZeRO-Infinity streams "
                   "parameters and gradients host-side; the serial "
                   "streamed path stays in charge")
            if mode == "on":
                logger.warning(msg)
            else:
                log_dist(msg, ranks=[0])
        self.optimizer = self._configure_optimizer()  # lr container only
        self._scaler_state = self.loss_scaler.jit_state()
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)
        self.progressive_layer_drop = None
        self.training_dataloader = (self.deepspeed_io(training_data)
                                    if training_data is not None else None)
        self._init_hook_state()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.steps_per_print() or 50)
        self.bucket_plan = None  # grads stream host-side, never bucketed
        self._step_fns = {}
        self._last_lr = self._current_lr()
        self.timers = SynchronizedWallClockTimer()
        self._wall_clock_breakdown = bool(self._config.wall_clock_breakdown)
        self.monitor = None
        self._flops_profiled = True
        self._last_loss = None
        self._pending_overflow = None
        self._pending_full = None
        self._device_feed = None
        self._user_device_feed = None
        self._step_log_ring = deque()
        self.run_monitor = self._init_run_monitor()
        self._watchdog = self._init_resilience()
        self._init_demotion_state()
        self._init_preemption()
        self._autotune_batch = None
        self._autotuner = None  # live probing needs the device step paths
        if getattr(self._config, "autotune_config", None) is not None and \
                self._config.autotune_config.enabled:
            log_dist("autotune requested but ZeRO-Infinity streams the "
                     "step host-side — the live autotuner does not attach "
                     "(tune Infinity runs through tools/autotune_bench.py"
                     "'s engine-factory search)", ranks=[0])

    def _init_demotion_state(self):
        """Coordinated-demotion state: set when the exchange flags
        itself broken/demote-requested; consumed at a step boundary
        (_finish_demotion) once every rank agrees on the step.  Returns
        the comm config (None when the config has no comm block — every
        overlap knob then falls back to its constants.py default)."""
        self._demote_reason = None
        self._demotion_target = None
        cc = getattr(self._config, "comm_config", None)
        self._overlap_timeout_s = (
            cc.overlap_timeout_ms if cc is not None
            else const.COMM_OVERLAP_TIMEOUT_MS_DEFAULT) / 1000.0
        return cc

    def _read_elastic_env(self):
        """Consume + validate the supervisor's elastic relaunch handoff
        (DSTPU_SURVIVING_WORLD / DSTPU_DEAD_RANKS / DSTPU_INCARNATION —
        elasticity/elastic_env.py).  Non-numeric or inconsistent values
        raise at init by contract; a legitimate handoff is LOGGED even
        before the shrink path engages, and the incarnation id is
        pinned so every coordination-service KV key this process posts
        is namespaced away from the dead generation's."""
        from ..elasticity.elastic_env import read_elastic_env

        env = read_elastic_env()
        # pin unconditionally: a prior engine in this process may have
        # cached a HIGHER incarnation — booting under a cleared env must
        # return the KV namespace to unprefixed keys, not inherit it
        from .comm.hostwire import set_incarnation

        set_incarnation(env.incarnation)
        if env.active:
            log_dist(
                env.describe()
                + (f"; KV keys scoped to incarnation {env.incarnation}"
                   if env.incarnation > 0 else "")
                + ("; the mesh will be rebuilt at the surviving world "
                   "and state resumes through resharding-on-restore"
                   if env.surviving_world is not None else ""),
                ranks=[0])
        return env

    def _elastic_devices(self, mesh_dict):
        """Device slice for a DSTPU_SURVIVING_WORLD boot, or None when
        the mesh should resolve naturally.  The supervisor counts the
        surviving world in PROCESS units (its dead ranks are process
        ranks), so:

        * relaunch matches (`process_count == surviving_world`): the
          survivors' real devices ARE the new world — no override; the
          mesh resolves over them naturally, so multi-device hosts keep
          every local chip (dp = devices/other, not the process count).
        * single-process simulation (`process_count == 1 <
          surviving_world`): the chaos dry-run shape — the surviving
          world is read as the dp DEVICE width and the mesh is built
          over the leading device slice.  Every non-data axis must be
          explicit (a -1 "take the rest" axis has no defined size once
          data is pinned).
        * anything else is a launcher/supervisor disagreement on the
          world size — refusing loudly beats guessing a mesh."""
        sw = self._elastic.surviving_world
        if sw is None:
            return None
        procs = jax.process_count()
        if procs == sw:
            log_dist(
                f"elastic restart: running on the {sw} surviving "
                f"process(es) with {jax.device_count()} device(s) — the "
                f"mesh resolves over the survivors' devices", ranks=[0])
            return None
        if procs != 1:
            raise ValueError(
                f"elastic restart: this relaunch has {procs} processes "
                f"but DSTPU_SURVIVING_WORLD={sw} — the launcher and the "
                f"supervisor disagree on the surviving world; refusing "
                f"to guess a mesh")
        other = 1
        for axis in ("model", "pipe", "seq"):
            size = int(mesh_dict.get(axis, 1) or 1)
            if size == -1:
                raise ValueError(
                    f"elastic restart: mesh.{axis}=-1 cannot be resolved "
                    f"under DSTPU_SURVIVING_WORLD={sw} — give the "
                    f"{axis} axis an explicit size")
            other *= max(1, size)
        return comm.elastic_device_slice(sw * other)

    def _build_mesh(self, config, mpu) -> MeshInfo:
        if isinstance(config, str):
            # file-path configs must drive the mesh/hierarchy exactly
            # like dict configs; a bad path surfaces as DeepSpeedConfig's
            # error right after, so fall back quietly here
            try:
                with open(config) as f:
                    config = json.load(f)
            except Exception:
                config = {}
        mesh_dict = {}
        if isinstance(config, dict):
            mesh_dict = dict(config.get(const.MESH) or {})
        if mpu is not None and not mesh_dict:
            mesh_dict = {"model": mpu.get_model_parallel_world_size()}
        devices = self._elastic_devices(mesh_dict)
        if devices is not None:
            # single-process simulation path only: a matching true
            # relaunch returned None above and resolves naturally
            sw = self._elastic.surviving_world
            if mesh_dict.get("data") not in (None, -1, sw):
                log_dist(
                    f"elastic restart: mesh.data={mesh_dict['data']} "
                    f"overridden by DSTPU_SURVIVING_WORLD={sw} — the "
                    f"supervisor's survivor count wins", ranks=[0])
            mesh_dict["data"] = sw
        return comm.make_mesh(
            data=mesh_dict.get("data", -1),
            model=mesh_dict.get("model", 1),
            pipe=mesh_dict.get("pipe", 1),
            seq=mesh_dict.get("seq", 1),
            data_outer=self._resolve_hierarchy(
                config, mesh_dict,
                device_count=len(devices) if devices is not None
                else None),
            devices=devices)

    def _resolve_hierarchy(self, config, mesh_dict,
                           device_count=None) -> int:
        """Outer factor for a hierarchical data axis, resolved BEFORE
        full config parsing (the mesh must exist first).  1 == flat.
        Only the bucketed gradient wire consumes the factored axis, so
        the hierarchy engages only when that wire is requested and the
        mesh is pure-DP; anything else logs the reason and stays flat.
        An explicit factor that doesn't divide dp raises a ValueError
        naming the axis sizes (config.check_hierarchy_divides) instead
        of tracing into a shape error later — EXCEPT on an elastic
        shrink restart, where a factor sized for the full world may
        legitimately stop dividing the surviving dp: there it is
        re-derived from the surviving topology (auto) with a log,
        because failing the relaunch over a stale perf knob would turn
        one dead host into a dead job."""
        from .config import check_hierarchy_divides, parse_comm_hierarchy

        comm_dict = (config.get(const.COMM) or {}) \
            if isinstance(config, dict) else {}
        hierarchy = parse_comm_hierarchy(comm_dict.get(const.COMM_HIERARCHY))
        if hierarchy == "none":
            return 1
        # RESOLVED axis sizes (the same resolver make_mesh uses): the
        # factor is validated against the real dp, and the pure-DP gate
        # sees what -1 ("take the rest") axes actually resolve to — raw
        # dict values would let e.g. model=-1 slip past the blocker
        from ..comm.mesh import (DATA_AXIS as _DA, MODEL_AXIS as _MA,
                                 PIPE_AXIS as _PA, SEQ_AXIS as _SA,
                                 _resolve_sizes)

        data = mesh_dict.get("data", -1)
        sizes = _resolve_sizes(device_count if device_count is not None
                               else jax.device_count(), {
            _DA: -1 if data is None else data,
            _MA: mesh_dict.get("model", 1),
            _PA: mesh_dict.get("pipe", 1),
            _SA: mesh_dict.get("seq", 1)})
        dp = sizes[_DA]
        if isinstance(hierarchy, int):
            if self._elastic.surviving_world is not None and \
                    dp % int(hierarchy) != 0:
                log_dist(
                    f"elastic restart: comm.hierarchy outer={hierarchy} "
                    f"no longer divides the surviving dp={dp} — "
                    f"re-deriving the factor from the surviving "
                    f"topology (auto)", ranks=[0])
                hierarchy = "auto"
            else:
                # an explicit non-dividing factor is a config error even
                # when another blocker keeps the mesh flat: raising here
                # (before any "falling back" log) matches the comm-config
                # validator instead of contradicting it
                check_hierarchy_divides(hierarchy, dp)
        blockers = []
        # TWO consumers ride the factored axis: the bucketed gradient
        # wire and the explicit MoE expert a2a (comm.moe — inner
        # placement keeps the expert exchange on data_inner, and the
        # two-hop lowering compresses the outer hop independently)
        moe_dict = comm_dict.get(const.COMM_MOE) or {}
        moe_wire_requested = isinstance(moe_dict, dict) and any(
            moe_dict.get(k) is not None
            for k in ("a2a_wire_dtype", "a2a_wire_dtype_inner",
                      "a2a_wire_dtype_outer"))
        if str(comm_dict.get(const.COMM_GRADIENT_REDUCTION,
                             const.COMM_GRADIENT_REDUCTION_DEFAULT)
               ).lower() != "bucketed" and not moe_wire_requested:
            blockers.append("comm.gradient_reduction is not 'bucketed' "
                            "and no comm.moe a2a wire is requested "
                            "(only those wires ride the factored axis)")
        for ax in (_MA, _PA, _SA):
            if sizes[ax] > 1:
                blockers.append(f"{ax} axis > 1 (hierarchy needs a "
                                "pure-DP mesh)")
        # the AUTHORITATIVE zero-config parse (stage defaults, legacy
        # bool, cpu_offload/offload_optimizer normalization) — never a
        # re-derivation from the raw dict that could drift from the
        # runtime's own gates; a malformed section is left for
        # DeepSpeedConfig to raise the real error on
        from .zero.config import DeepSpeedZeroConfig

        try:
            zcfg = DeepSpeedZeroConfig(config if isinstance(config, dict)
                                       else {})
        except Exception:
            zcfg = None
        if zcfg is not None and zcfg.stage >= 3:
            blockers.append("ZeRO-3 (param sharding keeps the flat axis)")
        if zcfg is not None and (zcfg.cpu_offload
                                 or zcfg.offload_optimizer is not None):
            # same condition _configure_offload engages on: the step
            # runs host-side, the bucketed wire never engages, and a
            # factored mesh would only buy hpZ's extra partition memory
            # with zero slow-fabric savings
            blockers.append("ZeRO-Offload (the step runs host-side)")
        if blockers:
            log_dist("comm.hierarchy requested but unavailable — keeping "
                     "the flat data axis: " + "; ".join(blockers),
                     ranks=[0])
            return 1
        if hierarchy == "auto":
            outer = comm.derive_data_outer(dp)
            if outer == 1:
                log_dist("comm.hierarchy auto: topology offers no "
                         "two-level factorization (single process, or "
                         "inner groups of 1) — keeping the flat data "
                         "axis", ranks=[0])
            return outer
        if dp // int(hierarchy) == 1:
            log_dist(f"comm.hierarchy outer={hierarchy} leaves inner "
                     "groups of 1 — keeping the flat data axis",
                     ranks=[0])
            return 1
        return int(hierarchy)

    def _configure_optimizer(self):
        """reference engine.py:647-757 optimizer selection."""
        if self.client_optimizer is not None:
            log_dist("using client optimizer", ranks=[0])
            return self.client_optimizer
        name = self._config.optimizer_name
        params = dict(self._config.optimizer_params or {})
        if name is None:
            log_dist("no optimizer configured; defaulting to FusedAdam",
                     ranks=[0])
            return FusedAdam()
        if name in (const.ADAM_OPTIMIZER, "adamw"):
            # both "Adam" and "AdamW" default to decoupled decay, matching
            # reference FusedAdam(adam_w_mode=True); "adam_w_mode": false in
            # params selects classic L2
            adam_w = params.pop(const.ADAM_W_MODE, True)
            if self._config.zero_config.cpu_offload:
                return DeepSpeedCPUAdam(adam_w_mode=adam_w, **params)
            return FusedAdam(adam_w_mode=adam_w, **params)
        if name == const.LAMB_OPTIMIZER:
            return FusedLamb(**params)
        if name == const.ONEBIT_ADAM_OPTIMIZER:
            return OnebitAdam(**params)
        if name == const.ONEBIT_LAMB_OPTIMIZER:
            return OnebitLamb(**params)
        if name.startswith("optax:"):
            # any optax optimizer by name — the torch.optim passthrough
            # analogue (reference engine.py:702-757); gated under ZeRO by
            # zero_allow_untested_optimizer (reference :655-664)
            if self._config.zero_enabled and \
                    not self._config.zero_allow_untested_optimizer:
                raise ValueError(
                    f"{name!r} is untested with ZeRO; set "
                    "zero_allow_untested_optimizer to proceed")
            import optax

            from .optax_adapter import OptaxOptimizer

            fn_name = name.split(":", 1)[1]
            fn = getattr(optax, fn_name, None)
            if fn is None:
                raise ValueError(f"optax has no optimizer {fn_name!r}")
            lr = params.pop("lr", params.pop("learning_rate", 1e-3))
            wrapped = optax.inject_hyperparams(fn)(learning_rate=lr,
                                                   **params)
            return OptaxOptimizer(wrapped, lr=lr)
        raise ValueError(f"unknown optimizer {name!r}; supported: "
                         f"{const.DEEPSPEED_OPTIMIZERS} or 'optax:<name>'")

    def _configure_offload(self, params):
        """ZeRO-Offload: host-RAM or NVMe optimizer state + native CPU-Adam
        (reference stage2.py:1450-1461 / swap_tensor; SURVEY.md §2.4)."""
        zc = self._config.zero_config
        if not (zc.cpu_offload or zc.offload_optimizer is not None):
            return None
        from .zero.offload import CPUOffloadRuntime

        nvme = None
        if zc.offload_optimizer is not None and \
                zc.offload_optimizer.device == "nvme":
            nvme = zc.offload_optimizer.nvme_path
        hparams = dict(self._config.optimizer_params or {})
        adam_w = bool(hparams.pop(const.ADAM_W_MODE, True))
        return CPUOffloadRuntime(
            params, hparams, adam_w_mode=adam_w, nvme_path=nvme,
            param_dtype=self.compute_dtype,
            param_shardings=self.zero_plan.param_shardings())

    def _configure_lr_scheduler(self, client_scheduler):
        sched = client_scheduler
        if sched is None:
            name = self._config.scheduler_name
            if name is None:
                return None
            if name not in SCHEDULERS:
                raise ValueError(f"unknown scheduler {name!r}")
            sched = SCHEDULERS[name](self.optimizer,
                                     **(self._config.scheduler_params or {}))
            log_dist(f"using scheduler {name}", ranks=[0])
        warn_hook = getattr(self.optimizer, "warn_if_rescale_inexact", None)
        if warn_hook is not None:
            warn_hook()
        return sched

    # ------------------------------------------------------------------
    # structured run telemetry (monitor/)
    # ------------------------------------------------------------------

    def _init_run_monitor(self):
        """Per-rank JSONL event stream + profiler capture window +
        multi-host heartbeats (monitor/monitor.py).  The TensorBoard
        monitor (if configured) becomes one sink beside the stream."""
        mc = getattr(self._config, "monitor_config", None)
        self._tracer = None
        self._trace_on = False
        if mc is None or not mc.enabled:
            return None
        from ..monitor import RunMonitor

        extra = {
            "train_batch_size": self.train_batch_size(),
            "micro_batch_size": self.train_micro_batch_size_per_gpu(),
            "gradient_accumulation_steps":
                self.gradient_accumulation_steps(),
            "precision": self._config.precision,
            "zero_stage": self._config.zero_optimization_stage,
            "model": type(self.module).__name__,
        }
        rm = RunMonitor(mc, tensorboard=self.monitor,
                        manifest_extra=extra)
        # span tracing (monitor/tracing.py): the engine caches the
        # recorder and a per-step sampling gate, resampled at every
        # optimizer boundary so a whole global batch traces (or not)
        # as a unit
        self._tracer = rm.tracer
        if rm.tracer is not None:
            self._trace_on = rm.tracer.sampled(self.global_steps + 1)
        return rm

    def _dispatch_tracer(self):
        """The gate every training trace site goes through: the
        recorder only when tracing is enabled AND the in-flight step is
        sampled.  One attribute read on the untraced path; no site ever
        synchronizes a device value, so traced and untraced runs stay
        bitwise identical."""
        tr = getattr(self, "_tracer", None)
        return tr if (tr is not None and self._trace_on) else None

    def _timed_next(self, data_iter):
        return timed_next(data_iter, tracer=self._dispatch_tracer(),
                          step=self.global_steps + 1)

    def _init_resilience(self):
        """Install the chaos-runtime pieces from the "faults" config
        block (runtime/resilience.py): the process-global fault plan
        (cleared when this engine has no rules, so stale injection from
        a previous engine can never leak into a new run), the transient
        retry policy, and — when enabled — the StepWatchdog armed
        beside the run monitor (its snapshots land in the monitor run
        dir, where the elasticity supervisor's HeartbeatWatcher polls
        for the escalation file)."""
        fc = getattr(self._config, "faults_config", None)
        if fc is None:
            return None
        plan = fc.plan if fc.enabled else None
        if plan is not None:
            plan.rank = comm.get_rank()
        resilience.install_fault_plan(plan)
        resilience.install_retry_policy(fc.retry_policy)
        if not fc.watchdog_enabled:
            return None
        run_dir = (self.run_monitor.run_dir
                   if self.run_monitor is not None else None)
        snap_dir = fc.watchdog_snapshot_dir or run_dir or \
            os.path.join(os.getcwd(), "dstpu_watchdog")
        wd = resilience.StepWatchdog(
            fc.watchdog_deadline_s, snap_dir,
            escalate_dir=run_dir or snap_dir,
            poll_s=fc.watchdog_poll_s, rank=comm.get_rank(),
            first_beat_mult=fc.watchdog_first_beat_mult)
        tr = getattr(self, "_tracer", None)
        if tr is not None:
            # flight recorder: a trip snapshot ships the last N trace
            # events, so a wedged step carries its own timeline
            wd.set_flight_recorder(tr.last_events)
        return wd

    def _init_preemption(self):
        """Honor the supervisor's "SIGTERM = save-if-possible" contract
        (elasticity/supervisor.py sends SIGTERM first, SIGKILL after
        --grace): with `checkpoint.preempt_save_dir` configured, a
        SIGTERM sets a flag the step boundary consumes — emergency
        checkpoint into that directory, committed through the two-phase
        barrier, then a clean exit so the relaunch resumes from the
        preemption point instead of the last periodic save."""
        self._preempt_requested = False
        self._prev_sigterm = None
        self._preempt_save_dir = getattr(
            self._config, "checkpoint_preempt_save_dir", None)
        if not self._preempt_save_dir:
            return
        import signal

        def handler(signum, frame):
            # async-signal context: flag + log only — the save itself
            # runs on the training thread at the next step boundary,
            # where the engine state is committed and consistent
            self._preempt_requested = True
            logger.warning(
                "SIGTERM received: emergency checkpoint will be saved "
                f"to {self._preempt_save_dir} at the next step boundary, "
                "then this process exits cleanly")

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, handler)
            log_dist(
                "preemption safety armed: SIGTERM checkpoints to "
                f"{self._preempt_save_dir} at the next step boundary",
                ranks=[0])
        except ValueError:
            # signal handlers install only on the main thread
            self._prev_sigterm = None
            logger.warning(
                "checkpoint.preempt_save_dir is set but this engine was "
                "constructed off the main thread, where signal handlers "
                "cannot install — SIGTERM preemption checkpointing is "
                "DISABLED; call engine.request_preemption_checkpoint() "
                "from your own handler instead")

    def _uninstall_preemption_handler(self):
        if getattr(self, "_prev_sigterm", None) is None:
            return
        import signal

        try:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
        except ValueError:
            pass
        self._prev_sigterm = None

    def request_preemption_checkpoint(self):
        """Programmatic twin of the SIGTERM handler: the next step
        boundary saves the emergency checkpoint and exits cleanly.
        For schedulers that deliver preemption out of band (k8s grace
        hooks, custom signal multiplexers)."""
        self._preempt_requested = True

    @property
    def preemption_requested(self) -> bool:
        return bool(getattr(self, "_preempt_requested", False))

    def _maybe_preempt_checkpoint(self):
        """Step-boundary tail of the SIGTERM contract: save, commit,
        exit.  Runs on the training thread with the engine at a clean
        post-step state — the saved tag resumes bitwise."""
        if not getattr(self, "_preempt_requested", False):
            return
        self._preempt_requested = False
        save_dir = getattr(self, "_preempt_save_dir", None)
        if not save_dir:
            logger.warning(
                "preemption checkpoint requested but no "
                "checkpoint.preempt_save_dir is configured — continuing "
                "WITHOUT saving (the relaunch resumes from the last "
                "periodic checkpoint)")
            return
        tag = f"preempt_step{self.global_steps}"
        logger.warning(
            f"preemption: saving emergency checkpoint {tag!r} to "
            f"{save_dir} (step {self.global_steps})")
        self.save_checkpoint(save_dir, tag=tag)
        # an async save must COMMIT before the process may exit — the
        # flush blocks on the background writers and the two-phase
        # commit barrier, so an interrupted flush can never leave a
        # half-written resume point (uncommitted tags are skipped)
        ckpt_io.flush_pending()
        logger.warning(
            f"preemption: checkpoint {tag!r} committed; exiting cleanly "
            "for the supervisor/scheduler to relaunch")
        self.finalize_monitoring()
        raise SystemExit(0)

    def _maybe_monitor_flops(self, fn, *args, per_step_mult=1.0):
        """Resolve flops-per-step ONCE via the flops profiler's cost
        analysis (AOT lowering against the jit cache); the monitor then
        derives achieved TFLOPs from it every step.  Any failure turns
        the feature off rather than retrying per step."""
        rm = self.run_monitor
        if rm is None or rm.flops_per_step is not None \
                or not rm.config.flops:
            return
        try:
            from ..profiling.flops_profiler.profiler import analyze_fn

            stats = analyze_fn(fn, *args)
            rm.flops_per_step = float(stats["flops"]) * per_step_mult
            rm.emit("flops", {"flops_per_step": rm.flops_per_step,
                              "per_step_mult": per_step_mult})
        except Exception as e:
            rm.config.flops = False
            logger.warning(f"monitor: flops analysis disabled: {e}")

    def _monitor_scalar(self, x):
        """Device scalar -> python float for a step event.  With
        sync_timing false the user opted out of per-step syncs (the
        deferred-overflow design exists to avoid exactly that stall), so
        a device value still in flight is SKIPPED (is_ready check)
        rather than blocked on — the event omits it."""
        if x is None:
            return None
        ready = getattr(x, "is_ready", None)
        if ready is not None and not self.run_monitor.sync_timing:
            try:
                if not ready():
                    return None
            except Exception:
                return None
        try:
            return float(x)
        except (TypeError, ValueError):
            return None

    def _emit_run_event(self, grad_norm=None, overflow=None, **extra):
        """One schema-versioned step event on this rank (called from
        every step-bookkeeping path once counters are settled)."""
        rm = self.run_monitor
        if rm is None:
            return
        metrics = {
            "loss": self._monitor_scalar(self._last_loss),
            "lr": self._current_lr(),
            "loss_scale": self._monitor_scalar(
                self._scaler_state["cur_scale"]),
            "skipped_steps": self._skipped_steps,
            "samples_per_sec": round(
                self.tput_timer.avg_samples_per_sec(), 2),
        }
        ov = self._monitor_scalar(overflow)
        if ov is not None:
            metrics["overflow"] = bool(ov)
        gn = self._monitor_scalar(grad_norm)
        if gn is not None:
            metrics["grad_norm"] = gn
        metrics.update(extra)
        rm.step_end(self.global_steps, **metrics)

    def _init_autotune(self):
        """Attach the self-tuning runtime (runtime/autotune/) when the
        "autotune" config block enables it: `autotune_search()` probes
        the legal comm-config space through live StepBuilder rebuilds
        (winner-cached by (model shape, mesh, fabric) fingerprint), and
        with `autotune.online.enabled` the step() boundary watches for
        sustained regression and live-retunes a bounded neighborhood."""
        ac = getattr(self._config, "autotune_config", None)
        if ac is None or not ac.enabled:
            return None
        # decline at INIT on engines live probing cannot serve (the
        # EngineProber constructor would raise) — the Infinity-path
        # contract: a requested autotune never crashes training at an
        # unpredictable step, it declines loudly up front
        blockers = []
        if self._offload is not None:
            blockers.append("ZeRO-Offload (the step runs host-side)")
        if self._qwz_overlap is not None or self._qwz_gather is not None:
            blockers.append("the qwZ stage-3 gather (prep is outside the "
                            "live-probe surface)")
        if self.mesh_info.axis_size(PIPE_AXIS) > 1:
            blockers.append("pipe-parallel stages")
        if blockers:
            log_dist("autotune requested but the live tuner does not "
                     "attach: " + "; ".join(blockers) + " — tune this "
                     "config through tools/autotune_bench.py's "
                     "engine-factory search", ranks=[0])
            return None
        from .autotune import AutotuneRuntime

        runtime = AutotuneRuntime(self, ac)
        log_dist(
            "autotune armed: probe_steps="
            f"{ac.probe_steps} wire_dtypes={list(ac.wire_dtypes)} "
            f"online={'on' if ac.online_enabled else 'off'}"
            + (f" cache={ac.cache_path}" if ac.cache_path else ""),
            ranks=[0])
        return runtime

    def autotune_search(self, batch=None, candidates=None, force=False,
                        cache_path=None):
        """Run the fingerprinted config search NOW (a step boundary —
        no pending micro gradients) and apply the winner (unless
        `autotune.apply_winner` is false).  `batch` seeds the probe
        batch when no forward has run yet; `force` skips the winner
        cache.  Returns the outcome dict ({"winner", "cached",
        "probes", "trace", ...}).  Needs the "autotune" config block
        enabled."""
        if self._autotuner is None:
            raise RuntimeError(
                "autotune_search needs {'autotune': {'enabled': true}} in "
                "the config (and a device step path — stage < 3, no "
                "offload/Infinity)")
        return self._autotuner.search(batch=batch, candidates=candidates,
                                      force=force, cache_path=cache_path)

    def finalize_monitoring(self):
        """Flush the event stream and write end-of-run summaries.  Under
        multi-host the summary merge is collective — call on every rank
        (or skip entirely; per-step events are already durable).  Also
        settles any deferred step-log lines, stops the input pipeline's
        background threads, and blocks on any async checkpoint writes
        still in flight — shutdown never abandons an uncommitted tag."""
        self._drain_step_log(force=True)
        self.close_data_pipeline()
        self.close_overlap()
        self._uninstall_preemption_handler()
        ckpt_io.flush_pending()
        if getattr(self, "_watchdog", None) is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self.run_monitor is not None:
            self.run_monitor.close()
        if self.monitor is not None:
            self.monitor.flush()

    def close_data_pipeline(self):
        """Stop the engine-owned PrefetchLoader's background threads and
        drop the device-side double buffer.  Idempotent; engine GC tears
        the threads down too (the prefetch iterator carries a finalizer)
        — this is the deterministic hook."""
        self._device_feed = None
        self._user_device_feed = None
        it = getattr(self, "_train_iter", None)
        if it is not None:
            # _train_iter is the RepeatingLoader; .loader is the
            # (possibly Prefetch-wrapped) base loader
            loader = getattr(it, "loader", None)
            if hasattr(loader, "close"):
                loader.close()
            del self._train_iter

    # ------------------------------------------------------------------
    # jitted step programs
    # ------------------------------------------------------------------

    def _build_bucket_plan(self):
        """Static bucketed-wire plan (runtime/comm/bucketing.py) for the
        dense DP path, or None when XLA's implicit psum stays in charge.
        Computed ONCE here — the jitted steps consume precomputed leaf
        offsets, never a per-step tree walk."""
        cc = getattr(self._config, "comm_config", None)
        if cc is None or cc.gradient_reduction != "bucketed":
            return None
        dp = self.mesh_info.axis_size(DATA_AXIS)
        blockers = []
        if dp <= 1:
            blockers.append("dp==1 (nothing to reduce)")
        for ax in (MODEL_AXIS, PIPE_AXIS, SEQ_AXIS):
            if self.mesh_info.axis_size(ax) > 1:
                blockers.append(f"{ax} axis > 1 (mixed-axis meshes stay on "
                                "the implicit wire)")
        if self._offload is not None:
            blockers.append("ZeRO-Offload (the step runs host-side)")
        if self._config.zero_optimization_stage >= 3:
            blockers.append("ZeRO-3 (gathering the full param tree at the "
                            "shard_map boundary would defeat param sharding)")
        if getattr(self.optimizer, "handles_dp_reduction", False) and \
                self._use_onebit_comm():
            # only when the compressed hot path actually engages — a
            # 1-bit optimizer falling back to dense DP reduction (gas>1,
            # ZeRO, offload) benefits from bucketing like plain Adam
            blockers.append("1-bit optimizer owns the compressed wire")
        if blockers:
            log_dist("bucketed gradient wire requested but unavailable — "
                     "falling back to implicit XLA reduction: "
                     + "; ".join(blockers), ranks=[0])
            return None
        scatter = (self._config.zero_optimization_stage >= 2
                   and bool(self._config.zero_config.reduce_scatter))
        from .comm.bucketing import GATHER_WIRES
        if scatter and cc.wire_dtype in GATHER_WIRES \
                and not self.mesh_info.hierarchical:
            log_dist(f"{cc.wire_dtype} wire is gather-structured; ZeRO>=2 "
                     "bucket reduction stays allreduce-lowered", ranks=[0])
        levels = None
        if self.mesh_info.hierarchical:
            from .comm.bucketing import WireLevel
            from ..comm.mesh import DATA_INNER_AXIS, DATA_OUTER_AXIS

            levels = (
                WireLevel(DATA_INNER_AXIS, self.mesh_info.data_inner_size,
                          cc.wire_dtype_inner),
                WireLevel(DATA_OUTER_AXIS, self.mesh_info.data_outer_size,
                          cc.wire_dtype_outer),
            )
        plan = BucketPlan(self._params, dp_size=dp,
                          bucket_elems=cc.reduce_bucket_size,
                          wire=cc.wire_dtype, scatter=scatter,
                          levels=levels,
                          quant_block=cc.quant_block_size)
        log_dist(plan.describe(), ranks=[0])
        return plan

    def _build_qwz_gather(self):
        """qwZ (ZeRO++): blockwise-quantized stage-3 parameter
        all-gather (zero/partition.QuantizedWeightGather), or None when
        not requested / not applicable.  The master weights stay full
        precision; only the compute-side gather is quantized."""
        qw = getattr(self._config.zero_config, "quantized_weights", None)
        if not qw:
            return None
        blockers = []
        if self._config.zero_optimization_stage < 3:
            blockers.append("ZeRO stage < 3 (parameters are replicated — "
                            "there is no gather to quantize)")
        if self.mesh_info.axis_size(DATA_AXIS) <= 1:
            blockers.append("dp==1 (nothing to gather)")
        for ax in (MODEL_AXIS, PIPE_AXIS, SEQ_AXIS):
            if self.mesh_info.axis_size(ax) > 1:
                # on legacy jax the shard_map axis_names shim runs FULL
                # manual, where the gather's data-only specs would
                # silently replicate TP-sharded leaves to full width —
                # a memory hazard, not a fallback; pure-DP only
                blockers.append(f"{ax} axis > 1 (mixed-axis meshes keep "
                                "the full-width gather)")
        if self._offload is not None:
            blockers.append("ZeRO-Offload (the step runs host-side)")
        if blockers:
            log_dist("zero_optimization.quantized_weights requested but "
                     "unavailable — parameters gather at full width: "
                     + "; ".join(blockers), ranks=[0])
            return None
        from .zero.partition import QuantizedWeightGather

        gather = QuantizedWeightGather(
            self.zero_plan, self._params, wire=qw,
            block=self._config.comm_config.quant_block_size)
        if not gather.active:
            log_dist("zero_optimization.quantized_weights: no stage-3 "
                     "leaf is data-sharded (all below min_size_to_shard) "
                     "— parameters gather at full width", ranks=[0])
            return None
        log_dist(gather.describe(), ranks=[0])
        return gather

    def _build_step_fns(self):
        """All jitted step programs come out of the schedule-driven
        StepBuilder (runtime/step_builder.py): ONE set of prep/grad/
        reduce/apply stage closures composed per the resolved
        StepSchedule — fused, scan, split, onebit, or the overlapped
        grads/exchange/combine pipeline.  Per-dispatch wire/qwZ counter
        accounting rides the emitted programs (CountedFn), so the byte
        math lives in the builder, once."""
        from .step_builder import StepBuilder

        fns = StepBuilder(self).build()
        if self._overlap_mode == "wire" and "grads" not in fns:
            # the schedule downgraded (e.g. layer-output capture forced
            # the implicit wire) — say so instead of silently serializing
            log_dist("comm.overlap: this step build cannot ride the "
                     "overlapped wire (no bucketed plan in effect); "
                     "running the serial schedule", ranks=[0])
        return fns

    def _resolve_overlap(self):
        """Resolve the `comm.overlap` knob against what this engine can
        actually serve: "wire" (host-exchanged bucketed gradient
        reduction, stage < 3), "qwz" (host-exchanged + prefetched
        stage-3 quantized parameter gather), or None with a LOGGED
        fallback — a requested overlap must never silently no-op."""
        cc = getattr(self._config, "comm_config", None)
        mode = getattr(cc, "overlap", "none") if cc is not None else "none"
        if mode == "none":
            return None
        blockers = []
        if getattr(self.optimizer, "handles_dp_reduction", False) and                 self._use_onebit_comm():
            blockers.append("the 1-bit optimizer owns the compressed "
                            "wire (error feedback cannot split across "
                            "an exchange boundary)")
        if self._offload is not None:
            blockers.append("ZeRO-Offload (the step runs host-side)")
        if self.mesh_info.axis_size(PIPE_AXIS) > 1:
            blockers.append("pipe-parallel stages (the pipeline "
                            "schedule owns inter-stage overlap)")
        if not blockers:
            if self.bucket_plan is not None:
                return "wire"
            if self._qwz_gather is not None:
                return "qwz"
            blockers.append(
                "no overlappable wire is configured (needs "
                "comm.gradient_reduction=bucketed at stage<3, or "
                "zero_optimization.quantized_weights at stage 3)")
        msg = ("comm.overlap=" + str(mode) + " requested but the serial "
               "path stays in charge: " + "; ".join(blockers))
        if mode == "on":
            logger.warning(msg)
        else:
            log_dist(msg, ranks=[0])
        return None

    def _build_overlap(self):
        """Construct the host exchange + (mode "qwz") the prefetchable
        encode/decode programs for the resolved overlap mode."""
        # the exchange survives step-fn rebuilds (retuned bucket plans,
        # hook/stash flips): its rendezvous keys are write-once and the
        # peer sockets are good for the engine's lifetime
        exchange = getattr(self, "_overlap_exchange", None)
        self._overlap_exchange = exchange
        self._qwz_overlap = None
        self._overlap_pending = []
        self._qwz_prefetch = None
        self._qwz_cparams_cache = None
        cc = self._init_demotion_state()
        if self._overlap_mode is None:
            return
        from .comm.overlap import make_exchange

        dp = self.mesh_info.axis_size(DATA_AXIS)
        if exchange is None:
            # same None fallback as _init_demotion_state: a config
            # without a comm block still builds a working exchange
            keepalive_ms = (
                cc.overlap_keepalive_ms if cc is not None
                else const.COMM_OVERLAP_KEEPALIVE_MS_DEFAULT)
            attempts = (
                cc.overlap_reconnect_attempts if cc is not None
                else const.COMM_OVERLAP_RECONNECT_ATTEMPTS_DEFAULT)
            window_ms = (
                cc.overlap_reconnect_window_ms if cc is not None
                else const.COMM_OVERLAP_RECONNECT_WINDOW_MS_DEFAULT)
            self._overlap_exchange = make_exchange(
                dp,
                keepalive_s=keepalive_ms / 1000.0,
                reconnect_attempts=attempts,
                reconnect_window_s=window_ms / 1000.0)
            self._register_exchange_watchdog()
        self._overlap_matrix_sharding = NamedSharding(
            self.mesh_info.mesh, PartitionSpec())
        if self._overlap_mode == "wire":
            _, self._overlap_payload_nbytes = \
                self.bucket_plan.overlap_layout
            log_dist("comm.overlap: bucketed gradient wire rides the "
                     "host exchange — reduction of micro-step N "
                     "overlaps micro-step N+1's compute "
                     f"({self._overlap_payload_nbytes} B/rank/micro)",
                     ranks=[0])
        else:
            from .step_builder import StepBuilder

            gather = self._qwz_gather
            compute_dtype = self.compute_dtype

            def cast_fn(tree):
                return jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype) if jnp.issubdtype(
                        x.dtype, jnp.floating) else x, tree)

            encode, decode = gather.build_overlap(cast_fn)
            builder = StepBuilder(self)
            self._qwz_overlap = (
                builder._counted(encode, qwz=gather, qwz_events=1),
                builder._counted(decode))
            _, self._overlap_payload_nbytes = gather.overlap_layout()
            log_dist("comm.overlap: qwZ stage-3 parameter gather rides "
                     "the host exchange, prefetched behind the previous "
                     "step's apply "
                     f"({self._overlap_payload_nbytes} B/rank/step)",
                     ranks=[0])

    def _overlap_submit(self, payload):
        """Hand one encoded wire payload (a rank-stacked device array)
        to the host exchange.  The worker thread materializes the local
        shards (blocking on the producing program THERE, never here)
        and moves the bytes while the device runs whatever was
        dispatched next."""
        total = self._overlap_payload_nbytes
        blocks = []
        for shard in payload.addressable_shards:
            rank = int(shard.index[0].start or 0) // total
            blocks.append((rank, (lambda d: lambda: d)(shard.data)))
        return self._overlap_exchange.submit(blocks)

    def _drain_overlap(self):
        """Settle every in-flight gradient exchange: sync the device to
        the last grads program (everything after that host-blocked wait
        is EXPOSED wire time — the number overlap exists to shrink,
        recorded as `grad_wire.exposed_ms` in the ckpt.stall_ms
        µs-in-bytes convention), then fold each micro's combined
        gradients into the accumulator in micro order — bit-identical
        to the serial wire's per-micro reduction order."""
        pending = self._overlap_pending
        self._check_overlap_health()
        if not pending:
            return
        if "combine" not in self._step_fns:
            raise RuntimeError(
                "overlap: in-flight gradient exchanges but the current "
                "step build has no combine program — the step programs "
                "were rebuilt mid-accumulation (register_forward_hook / "
                "store_gradients between forward and step?)")
        if self._grad_acc is None:
            self._grad_acc = self._zero_grad_acc()
        if self._last_loss is not None:
            jax.block_until_ready(self._last_loss)
        exposed_us = 0
        while pending:
            ticket = pending[0]
            before = ticket.wait_us
            mat = ticket.wait(self._overlap_timeout_s)
            exposed_us += ticket.wait_us - before
            mdev = jax.device_put(mat, self._overlap_matrix_sharding)
            # combine dispatches are async: the NEXT ticket's wire wait
            # overlaps this combine's device execution.  The ticket is
            # popped only once COMBINED: a wait() that raises leaves it
            # (and everything after it) pending, so a retried step()
            # resumes exactly where the drain stopped instead of
            # folding earlier tickets' gradients twice.
            self._grad_acc = self._step_fns["combine"](self._grad_acc,
                                                       mdev)
            pending.pop(0)
            self._retire_ticket(ticket)
        COUNTERS.add("grad_wire.exposed_ms", int(exposed_us), calls=1)
        tr = self._dispatch_tracer()
        if tr is not None:
            tr.add_complete("wire_exposed", "wire",
                            dur_us=int(exposed_us),
                            step=self.global_steps + 1)
        self._check_overlap_health()

    def _retire_ticket(self, ticket):
        retire = getattr(self._overlap_exchange, "retire", None)
        if retire is not None:
            retire(ticket)

    def _check_overlap_health(self):
        """Record a demotion request surfaced by the exchange (reconnect
        budget exhausted, a peer's DEMOTE broadcast, or an injected
        send-side fault with nothing lost).  The request is CONSUMED at
        the next step boundary by _finish_demotion — mid-accumulation
        the exchange keeps serving (its KV fallback transport stays
        bitwise), so nothing here can change training math."""
        ex = self._overlap_exchange
        if ex is None or self._demote_reason is not None:
            return
        # while the exchange is unhealthy, probe the KV demote-pending
        # flag too — a peer whose conn to us died may already be in KV
        # mode, and its DEMOTE frame never reached us
        poll = getattr(ex, "poll_peer_demotion", None)
        if poll is not None:
            poll()
        if getattr(ex, "demote_requested", False):
            broken = getattr(ex, "broken", None)
            self._demote_reason = (
                f"{type(broken).__name__}: {broken}" if broken is not None
                else "a peer requested demotion")
            logger.warning(
                "comm.overlap: the host exchange requested coordinated "
                f"demotion ({self._demote_reason}); the serial in-program "
                "wire takes over at the next agreed step boundary")

    def _predispatch_demotion(self):
        """Consume a pending coordinated demotion BEFORE dispatching the
        next step's programs.  A peer that flagged demotion parks in the
        demotion barrier at its own step boundary and never joins this
        step's in-program collectives — a rank that dispatches first
        blocks inside a psum until the barrier timeout (observed on the
        2-proc TCP campaign: one rank waiting in agree_demotion_step,
        the other stuck in its forward program).  The pre-forward point
        of a fresh accumulation window IS a step boundary, so finishing
        the demotion here is the same clean state step() uses;
        mid-accumulation the boundary in step() still owns it."""
        if self._demote_reason is None:
            return
        if self._overlap_pending or \
                self.micro_steps % self.gradient_accumulation_steps() != 0:
            return
        self._finish_demotion()

    def _finish_demotion(self):
        """Coordinated demotion endgame, run at a step boundary (after
        the apply): agree with every rank on the demotion step through
        the exchange's KV barrier (max of the boundaries reached — a
        rank behind the max keeps training over the KV fallback until
        it gets there), then tear the exchange down and rebuild the
        step programs through StepBuilder on the serial in-program
        wire.  Losses stay bitwise: the overlapped and serial wires are
        reduction-math-identical (pinned since PR 9), and every
        in-flight exchange was drained before this runs."""
        if self._demote_reason is None:
            return
        ex = self._overlap_exchange
        if ex is None:
            self._demote_reason = None
            return
        if self._demotion_target is None or \
                self.global_steps >= self._demotion_target:
            # re-enter the (non-parking) agreement every boundary until
            # it settles: None = some rank has not voted yet, a higher
            # value = keep training to the agreed step on the degraded
            # transport, then the arrival barrier at the target returns
            # the final step every rank demotes at together
            timeout_ms = max(1, int(self._overlap_timeout_s * 1000))
            agreed = ex.agree_demotion_step(
                self.global_steps, timeout_ms=timeout_ms)
            if agreed is None:
                return
            if agreed != self._demotion_target:
                self._demotion_target = agreed
                if agreed > self.global_steps:
                    log_dist(
                        "comm.overlap demotion: ranks agreed on step "
                        f"{agreed}; this rank (at step "
                        f"{self.global_steps}) continues on the KV "
                        "fallback transport until then", ranks=[0])
        if self.global_steps < self._demotion_target:
            return
        reason = self._demote_reason
        COUNTERS.add("exchange.demotions")
        logger.warning(
            f"comm.overlap DEMOTED at step {self.global_steps}: {reason} "
            "— the host exchange is torn down and the step programs are "
            "rebuilt on the serial in-program wire (losses stay bitwise; "
            "the overlap win is forfeited until the next engine build)")
        self.close_overlap()
        self._overlap_exchange = None
        self._overlap_mode = None
        self._qwz_overlap = None
        self._qwz_prefetch = None
        self._qwz_cparams_cache = None
        self._overlap_pending = []
        self._demote_reason = None
        self._demotion_target = None
        self._demoted_reason = reason  # step_builder's schedule log
        self._step_fns = self._build_step_fns()

    def _register_exchange_watchdog(self):
        """Name the exchange's service threads in the StepWatchdog's
        stall snapshot: a hung exchange then reads as 'overlap_exchange'
        with its receiver/sender liveness, not an anonymous stall."""
        wd = getattr(self, "_watchdog", None)
        ex = getattr(self, "_overlap_exchange", None)
        if wd is not None and ex is not None and hasattr(ex, "threads"):
            wd.register_threads("overlap_exchange", ex.threads)

    def _qwz_kick_prefetch(self):
        """Dispatch the NEXT step's quantized parameter gather right
        behind the apply that produced the params: the encode program
        queues after the apply on the device, and the host exchange
        then runs behind the step's host-side tail (bookkeeping, input
        pipeline) and the next forward's dispatch."""
        if self._qwz_overlap is None:
            return
        if self._demote_reason is not None:
            # demotion pending: don't feed the dying exchange new work —
            # the serial gather takes over after the rebuild (bitwise)
            self._qwz_cparams_cache = None
            self._qwz_prefetch = None
            return
        encode, _decode = self._qwz_overlap
        self._qwz_cparams_cache = None
        self._qwz_prefetch = (self._params,
                              self._overlap_submit(encode(self._params)))

    def _step_cparams(self):
        """The (possibly prefetched) gathered compute params for this
        step.  A prefetch that landed before the forward asked for it
        is a `qwz.prefetch_hits` event (bytes = µs of head start, the
        µs-in-bytes convention); a stale prefetch (params replaced out
        of band, e.g. load_checkpoint) is discarded and the gather runs
        on demand."""
        if self._qwz_overlap is None:
            return None
        self._check_overlap_health()
        cache = self._qwz_cparams_cache
        if cache is not None and cache[0] is self._params:
            return cache[1]
        encode, decode = self._qwz_overlap
        pre = self._qwz_prefetch
        self._qwz_prefetch = None
        prefetched = pre is not None and pre[0] is self._params
        if prefetched:
            ticket = pre[1]
        else:
            if pre is not None:
                # stale (params swapped out of band): unregister it so
                # the transport does not hold every rank's payload for
                # an exchange nobody will consume
                self._retire_ticket(pre[1])
            ticket = self._overlap_submit(encode(self._params))
        import time as _time

        # only a PREFETCHED ticket can score a hit: an on-demand
        # submit can also be ready by now (the worker posts local
        # blocks before the network send), but that is a race artifact,
        # not a head start
        if prefetched and ticket.ready and ticket.done_at is not None:
            head_us = int((_time.perf_counter() - ticket.done_at) * 1e6)
            COUNTERS.add("qwz.prefetch_hits", max(0, head_us), calls=1)
        mat = ticket.wait(self._overlap_timeout_s)
        self._retire_ticket(ticket)
        self._check_overlap_health()
        mdev = jax.device_put(mat, self._overlap_matrix_sharding)
        cparams = decode(self._params, mdev)
        self._qwz_cparams_cache = (self._params, cparams)
        return cparams

    def close_overlap(self):
        """Tear the overlap exchange down (sockets + worker threads).
        Idempotent; finalize_monitoring calls it."""
        ex = getattr(self, "_overlap_exchange", None)
        if ex is not None:
            ex.close()
            # the watchdog's group closure would otherwise keep the
            # closed exchange (and its payload buffers) alive forever
            wd = getattr(self, "_watchdog", None)
            if wd is not None:
                wd.unregister_threads("overlap_exchange")

    def _use_onebit_comm(self) -> bool:
        """True when the optimizer's own (compressed) DP reduction runs in
        the training hot path. Mirrors the reference constraint set: 1-bit
        optimizers are incompatible with ZeRO stages and grad accumulation
        fans through the dense accumulator, so the compressed wire path
        needs gas==1, stage 0, no offload, dp > 1."""
        opt = self.optimizer
        if not getattr(opt, "handles_dp_reduction", False):
            return False
        ok = (self.gradient_accumulation_steps() == 1
              and self._offload is None
              and self._config.zero_optimization_stage == 0
              and self.mesh_info.axis_size(DATA_AXIS) > 1
              and not self.mesh_info.hierarchical)
        if not ok:
            log_dist(
                "1-bit optimizer falling back to dense DP reduction "
                "(compressed comm needs gas==1, ZeRO stage 0, no offload, "
                "dp>1, a FLAT data axis — reference onebit/adam.py has the "
                "same constraints; the compressed wire addresses one named "
                "axis)",
                ranks=[0])
        return ok

    def _build_onebit_step(self, cast):
        """Fused step with the optimizer-owned compressed reduction over
        the `data` axis INSIDE shard_map: gradients stay local per shard,
        only the optimizer's (sign-compressed after freeze_step) momentum
        crosses the wire — the reference NcclBackend wire pattern
        (comm/nccl.py:47-186) on XLA collectives."""
        model = self.module
        compute_dtype = self.compute_dtype
        opt = self.optimizer
        scaler = self.loss_scaler
        pld_enabled = self.progressive_layer_drop is not None
        mesh = self.mesh_info.mesh
        dp = self.mesh_info.axis_size(DATA_AXIS)
        if float(self._config.gradient_clipping or 0.0) > 0.0:
            logger.warning("gradient clipping is not applied on the 1-bit "
                           "compressed path (local grads are never "
                           "globally reduced; reference parity)")

        if not getattr(self, "_onebit_hot", False):
            # per-rank error-feedback buffers: [dp, *param] sharded over
            # data (skip when rebuilding step fns — already expanded)
            self._opt_state = dict(self._opt_state)
            for key in ("worker_error", "server_error"):
                expanded = jax.tree_util.tree_map(
                    lambda e: jnp.zeros((dp,) + tuple(e.shape), jnp.float32),
                    self._opt_state[key])
                self._opt_state[key] = jax.device_put(
                    expanded, jax.tree_util.tree_map(
                        lambda _: NamedSharding(
                            mesh, PartitionSpec(DATA_AXIS)), expanded))

        self._onebit_hot = True
        err_spec = PartitionSpec(DATA_AXIS)
        state_specs = {k: (err_spec if k in ("worker_error", "server_error")
                           else PartitionSpec())
                       for k in self._opt_state}

        def run(params, opt_state, scaler_state, batch, rng, lr, pld_theta):
            loss_scale = scaler_state["cur_scale"]
            cparams = cast(params, compute_dtype)

            def scaled_loss_fn(p):
                kwargs = {}
                if pld_enabled:
                    kwargs = {"progressive_layer_drop": True,
                              "pld_theta": pld_theta}
                out = model.loss(p, batch, rng=rng, train=True, **kwargs)
                loss = out[0] if isinstance(out, tuple) else out
                return loss.astype(jnp.float32) * loss_scale, loss

            # LOCAL gradients: the loss is the mean over this shard's rows
            # only — no implicit psum; the optimizer does the reduction
            grads, loss = jax.grad(scaled_loss_fn, has_aux=True)(cparams)
            grads = cast(grads, jnp.float32)
            overflow = jax.lax.pmax(
                has_overflow(grads).astype(jnp.int32), DATA_AXIS) > 0
            grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)

            local_state = dict(opt_state)
            for key in ("worker_error", "server_error"):
                local_state[key] = jax.tree_util.tree_map(
                    lambda e: e[0], opt_state[key])
            new_params, new_opt = opt.update(grads, local_state, params,
                                            lr=lr, comm_axis=DATA_AXIS)
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
            new_params = sel(new_params, params)
            new_opt = sel(new_opt, local_state)
            new_opt = dict(new_opt)
            for key in ("worker_error", "server_error"):
                new_opt[key] = jax.tree_util.tree_map(
                    lambda e: e[None], new_opt[key])
            new_scaler = scaler.jit_update(scaler_state, overflow)
            loss_mean = jax.lax.pmean(loss, DATA_AXIS)
            # layer capture / grad stashing are not offered on this path
            # (local grads never exist globally-reduced); empty extras
            return (new_params, new_opt, new_scaler, loss_mean, overflow,
                    jnp.zeros((), jnp.float32), {})

        smapped = jax.shard_map(
            run, mesh=mesh,
            in_specs=(PartitionSpec(), state_specs, PartitionSpec(),
                      PartitionSpec(DATA_AXIS), PartitionSpec(),
                      PartitionSpec(), PartitionSpec()),
            out_specs=(PartitionSpec(), state_specs, PartitionSpec(),
                       PartitionSpec(), PartitionSpec(), PartitionSpec(),
                       PartitionSpec()),
            axis_names={DATA_AXIS}, check_vma=False)
        return jax.jit(smapped, donate_argnums=(0, 1))

    def _zero_grad_acc(self):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), self._params)
        return jax.device_put(zeros, self.zero_plan.grad_shardings())

    def _shard_batch(self, batch):
        """Place the global batch sharded over the data axis (dim 0)."""
        mesh = self.mesh_info.mesh
        replicated = [0]  # bytes of indivisible leaves in THIS batch

        def put(x):
            x = jnp.asarray(x)
            spec = [None] * x.ndim
            if batch_shardable(x.shape, max(1, self.dp_world_size)):
                spec[0] = self.mesh_info.data_spec
            elif x.ndim:
                # replicating costs dp x memory/compute — count the
                # batch (input.replicated_batches, rendered by the run
                # report) and tell the user once
                replicated[0] += int(x.nbytes)
                if not getattr(self, "_warned_replicated_batch", False):
                    self._warned_replicated_batch = True
                    logger.warning(
                        f"batch dim 0 ({x.shape[0]}) not divisible by data "
                        f"shards ({self.dp_world_size}); replicating batch "
                        f"over the data axis")
            target = NamedSharding(mesh, PartitionSpec(*spec))
            if isinstance(x, jax.Array) and \
                    x.sharding.is_equivalent_to(target, x.ndim):
                return x  # already placed — skip a per-step dispatch
            COUNTERS.add("input.h2d_bytes", int(x.nbytes))
            return jax.device_put(x, target)

        placed = jax.tree_util.tree_map(put, batch)
        if replicated[0]:
            # ONE event per batch (calls counts batches, bytes their
            # replicated payload) — per-leaf counting would inflate with
            # the batch pytree's arity
            COUNTERS.add("input.replicated_batches", replicated[0])
        return placed

    def _next_rng(self):
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    def _current_lr(self):
        """Current lr from param_groups, or None for optimizers without the
        torch-style attribute (their update() then uses its own default —
        never silently train at lr=0)."""
        groups = getattr(self.optimizer, "param_groups", None)
        if groups and "lr" in groups[0]:
            return float(groups[0]["lr"])
        return None

    # ------------------------------------------------------------------
    # public training API (reference engine.py:959,1040,1201)
    # ------------------------------------------------------------------

    def forward(self, batch, rng=None):
        """Compute loss AND gradients for a micro batch (fused fwd+bwd —
        separate passes would recompute the forward under autodiff).
        Returns the (unscaled) loss; gradients are cached for backward().

        gas==1 fast path: the whole step (fwd+bwd+optimizer+scaler) runs as
        one fused program here; step() then only does host bookkeeping."""
        if self._overlap_exchange is not None:
            self._check_overlap_health()
            self._predispatch_demotion()
        rm = self.run_monitor
        if rm is not None and self.is_gradient_accumulation_boundary():
            rm.step_start(self.global_steps)
        sp = rm.span("forward") if rm is not None else None
        if self._infinity is not None:
            loss = self._infinity_forward(batch)
        elif "grads" in self._step_fns:
            loss = self._overlap_forward(batch, rng)
        elif "full" in self._step_fns:
            loss = self._fused_forward(batch, rng)
        else:
            loss = self._micro_forward(batch, rng)
        if sp is not None:
            sp.close(sync=loss if rm.sync_timing else None)
        return loss

    def _micro_forward(self, batch, rng):
        """Split-path micro step: fused fwd+bwd into the gradient
        accumulator; apply runs at the boundary in step()."""
        if self._grad_acc is None:
            self._grad_acc = self._zero_grad_acc()
        if self.is_gradient_accumulation_boundary():
            self.tput_timer.start()  # times one full global batch
        batch = self._shard_batch(batch)
        self._autotune_batch = batch  # probe replay (never donated)
        rng = rng if rng is not None else self._next_rng()
        theta = jnp.asarray(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop else 1.0, jnp.float32)
        profiling = self._maybe_profile_flops(batch, rng, theta)
        # split path: flops/step ~= micro flops x gas (the apply program
        # is optimizer-bound, negligible FLOPs next to fwd+bwd)
        p0 = self._step_cparams() if self._qwz_overlap is not None \
            else self._params
        self._maybe_monitor_flops(
            self._step_fns["micro"].fn, p0, self._grad_acc, batch,
            rng, self._scaler_state["cur_scale"], theta,
            per_step_mult=float(self.gradient_accumulation_steps()))
        if self._wall_clock_breakdown:
            self.timers("forward").start()
        p0 = self._step_cparams() if self._qwz_overlap is not None \
            else self._params
        loss, self._grad_acc, extras = self._step_fns["micro"](
            p0, self._grad_acc, batch, rng,
            self._scaler_state["cur_scale"], theta)
        self._consume_extras(extras)
        if self._wall_clock_breakdown:
            # one fused fwd+bwd program: this IS forward+backward time
            self.timers("forward").stop(sync=loss)
        if profiling is not None:
            profiling.stop_profile(params=self._params, sync=loss)
            profiling.stats.update(self._flops_stats)
            profiling.print_model_profile(
                profile_step=self.global_steps,
                top_modules=self._config.flops_profiler_config.top_modules,
                detailed=self._config.flops_profiler_config.detailed)
        self._cached = loss
        self._last_loss = loss
        return loss

    def _overlap_forward(self, batch, rng):
        """Overlapped-wire micro step: the grads program emits this
        rank's encoded wire payload, which the host exchange moves
        while the device runs whatever is dispatched next (the next
        micro's grads program, the boundary combines); the reduction is
        deferred to step()'s drain.  Losses and the final params are
        bitwise the serial wire's — the combine program mirrors its
        reduction math expression for expression."""
        if self.is_gradient_accumulation_boundary():
            self.tput_timer.start()  # times one full global batch
        self._check_overlap_health()
        batch = self._shard_batch(batch)
        self._autotune_batch = batch  # probe replay (never donated)
        rng = rng if rng is not None else self._next_rng()
        theta = jnp.asarray(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop else 1.0, jnp.float32)
        profiling = self._maybe_profile_flops(batch, rng, theta)
        self._maybe_monitor_flops(
            self._step_fns["grads"].fn, self._params, batch, rng,
            self._scaler_state["cur_scale"], theta,
            per_step_mult=float(self.gradient_accumulation_steps()))
        if self._wall_clock_breakdown:
            self.timers("forward").start()
        loss, payload = self._step_fns["grads"](
            self._params, batch, rng, self._scaler_state["cur_scale"],
            theta)
        self._overlap_pending.append(self._overlap_submit(payload))
        if self._wall_clock_breakdown:
            # one fused fwd+bwd program: this IS forward+backward time
            self.timers("forward").stop(sync=loss)
        if profiling is not None:
            profiling.stop_profile(params=self._params, sync=loss)
            profiling.stats.update(self._flops_stats)
            profiling.print_model_profile(
                profile_step=self.global_steps,
                top_modules=self._config.flops_profiler_config.top_modules,
                detailed=self._config.flops_profiler_config.detailed)
        self._cached = loss
        self._last_loss = loss
        return loss

    def _infinity_forward(self, batch):
        """Streamed micro step; the host master update runs at the
        accumulation boundary over the summed fp32 grads (gas > 1 costs
        no extra device memory — the sink lives on the host). step()
        bookkeeps via _pending_full at the boundary.
        Multi-host: `batch` is this process's LOCAL shard of the global
        batch (the dataloader already strides per process); grads/loss are
        averaged across processes inside the runtime."""
        gas = self.gradient_accumulation_steps()
        boundary_micro = (self.micro_steps % gas) == gas - 1
        if self.micro_steps % gas == 0:
            self._resolve_pending_overflow()  # settle the PREVIOUS step
            self.tput_timer.start()
        loss = self._infinity.micro_step(batch)
        if boundary_micro:
            overflow = self._infinity.apply_accumulated(
                lr=self._current_lr(),
                clip=float(self._config.gradient_clipping or 0.0))
            self._pending_full = (self._scaler_state, bool(overflow),
                                  jnp.zeros((), jnp.float32))
        self._cached = loss
        self._last_loss = loss
        return loss

    def _fused_forward(self, batch, rng):
        """gas==1: run the single fused step program and commit the new
        state immediately (the update is branchless-correct in-device, so
        committing at the boundary's forward is semantically the same step
        the split path applies in step()); step() finishes the host-side
        bookkeeping. The previous step's deferred overflow flag is settled
        FIRST so the scheduler lr read below is the rolled-back one."""
        self._resolve_pending_overflow()
        self.tput_timer.start()
        batch = self._shard_batch(batch)
        self._autotune_batch = batch  # probe replay (never donated)
        rng = rng if rng is not None else self._next_rng()
        theta = jnp.asarray(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop else 1.0, jnp.float32)
        cur_lr = self._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        profiling = self._maybe_profile_flops(batch, rng, theta, lr=lr)
        args = (self._params, self._opt_state, self._scaler_state,
                batch, rng, lr, theta)
        if self._qwz_overlap is not None:
            args = args + (self._step_cparams(),)
        self._maybe_monitor_flops(self._step_fns["full"].fn, *args)
        if self._wall_clock_breakdown:
            self.timers("forward").start()
        (self._params, self._opt_state, new_scaler, loss,
         overflow, grad_norm, extras) = self._step_fns["full"](*args)
        self._qwz_kick_prefetch()
        self._consume_extras(extras)
        if self._wall_clock_breakdown:
            # the fused program IS forward+backward+step
            self.timers("forward").stop(sync=loss)
        if profiling is not None:
            profiling.stop_profile(params=self._params, sync=loss)
            profiling.stats.update(self._flops_stats)
            profiling.print_model_profile(
                profile_step=self.global_steps,
                top_modules=self._config.flops_profiler_config.top_modules,
                detailed=self._config.flops_profiler_config.detailed)
        self._pending_full = (new_scaler, overflow, grad_norm)
        self._cached = loss
        self._last_loss = loss
        return loss

    def _maybe_profile_flops(self, batch, rng, theta, lr=None):
        """FLOPS profiler hook (reference engine.py:966-1019): at
        profile_step, statically analyze the jitted micro-step and time
        this invocation."""
        cfg = self._config.flops_profiler_config
        if not cfg.enabled or self._flops_profiled or \
                self.global_steps != cfg.profile_step:
            return None
        from ..profiling.flops_profiler.profiler import (FlopsProfiler,
                                                         analyze_fn)
        self._flops_profiled = True
        if "grads" in self._step_fns:
            self._flops_stats = analyze_fn(
                self._step_fns["grads"].fn, self._params, batch, rng,
                self._scaler_state["cur_scale"], theta)
        elif "full" in self._step_fns:
            args = (self._params, self._opt_state, self._scaler_state,
                    batch, rng, lr, theta)
            if self._qwz_overlap is not None:
                args = args + (self._step_cparams(),)
            self._flops_stats = analyze_fn(self._step_fns["full"].fn,
                                           *args)
        else:
            if self._grad_acc is None:
                self._grad_acc = self._zero_grad_acc()
            p0 = self._step_cparams() if self._qwz_overlap is not None \
                else self._params
            self._flops_stats = analyze_fn(
                self._step_fns["micro"].fn, p0, self._grad_acc, batch,
                rng, self._scaler_state["cur_scale"], theta)
        prof = FlopsProfiler()
        prof.start_profile()
        return prof

    def backward(self, loss=None, allreduce_gradients=True):
        """Gradients were produced in forward(); this advances the
        micro-step bookkeeping (API parity with reference backward :1040)."""
        if self._cached is None:
            raise RuntimeError("backward() called before forward()")
        self.micro_steps += 1
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size
        self._cached = None
        return loss

    # ------------------------------------------------------------------
    # layer-output hooks + gradient stashing (EleutherAI fork additions)
    # ------------------------------------------------------------------

    def register_forward_hook(self, layers_to_hook,
                              layer_name_pattern="transformerlayer"):
        """Capture per-layer block outputs into engine.layer_outputs
        (reference engine.py:227-254). JAX has no module hooks: the model
        instead threads the requested outputs out of the jitted step as
        explicit aux (model.loss(..., capture_layers=...)), so capture
        costs one extra HBM write per hooked layer and nothing else.

        layers_to_hook: "all" or a list of layer indices ([] disables).
        layer_name_pattern is accepted for API parity; layer selection here
        is by index (the model's blocks are a list, not named submodules)."""
        self.layer_name_pattern = layer_name_pattern
        self.layers_to_hook = layers_to_hook
        self.layer_outputs = {}
        if layers_to_hook == "all":
            cap = "all"
        elif layers_to_hook:
            cap = tuple(int(i) for i in layers_to_hook)
            n_layers = getattr(getattr(self.module, "config", None),
                               "num_layers", None)
            if n_layers is not None:
                bad = [i for i in cap if not 0 <= i < n_layers]
                if bad:
                    raise ValueError(
                        f"layers_to_hook {bad} out of range for a "
                        f"{n_layers}-layer model")
        else:
            cap = None
        if cap is not None:
            if self._infinity is not None:
                raise NotImplementedError(
                    "layer-output hooks are unavailable under ZeRO-Infinity "
                    "streaming (block outputs are consumed as they stream)")
            if getattr(self, "_onebit_hot", False):
                raise NotImplementedError(
                    "layer-output hooks are unavailable on the 1-bit "
                    "compressed step path")
            if not self._model_supports_capture():
                raise TypeError(
                    f"{type(self.module).__name__}.loss does not accept "
                    "capture_layers; implement it to use forward hooks")
        if cap != self._capture_layers:
            self._capture_layers = cap
            self._step_fns = self._build_step_fns()

    def _model_supports_capture(self) -> bool:
        import inspect

        loss_fn = getattr(self.module, "loss", None)
        if loss_fn is None:
            return False
        try:
            sig = inspect.signature(loss_fn)
        except (TypeError, ValueError):
            return False
        return "capture_layers" in sig.parameters

    @property
    def store_gradients(self) -> bool:
        """When True, each optimizer step stashes the post-clip, unscaled,
        DP-averaged gradient pytree in engine.stored_gradients (reference
        engine.py:139-140,1156-1161; set store_gradients_cpu for a host
        numpy copy). On an overflow (skipped) step the stash is zeros —
        never inf/nan. Flipping this retraces the step program."""
        return self._store_gradients

    @store_gradients.setter
    def store_gradients(self, value):
        value = bool(value)
        if value == self._store_gradients:
            return
        if value and getattr(self, "_onebit_hot", False):
            raise NotImplementedError(
                "gradient stashing is unavailable on the 1-bit compressed "
                "step path (gradients are never globally reduced)")
        if value and self._infinity is not None:
            raise NotImplementedError(
                "gradient stashing is unavailable under ZeRO-Infinity "
                "streaming (per-block grads are consumed as they stream)")
        self._store_gradients = value
        if not value:
            self.stored_gradients = None
        if self._step_fns:
            self._step_fns = self._build_step_fns()

    def _consume_extras(self, extras):
        """Host-side sink for optional step outputs (layer captures, grad
        stash)."""
        caps = extras.get("layer_outputs")
        if caps:
            self.layer_outputs = dict(caps)
        grads = extras.get("grads")
        if grads is not None:
            if self.store_gradients_cpu:
                grads = jax.device_get(grads)
            self.stored_gradients = grads

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps % self.gradient_accumulation_steps()) == 0

    def step(self):
        """Weight update at accumulation boundaries (reference :1201)."""
        if self.micro_steps == 0 or not self.is_gradient_accumulation_boundary():
            return
        # chaos runtime: every optimizer-step boundary (all four step
        # paths funnel through here) advances the fault plan's step
        # schedule, fires the `engine.step` injection site, and beats
        # the hang watchdog
        resilience.step_boundary(self.global_steps)
        if self._watchdog is not None:
            self._watchdog.beat(self.global_steps)
            tr = self._dispatch_tracer()
            if tr is not None:
                tr.instant("watchdog_beat", "watchdog",
                           step=self.global_steps)
        if self._offload is not None:
            out = self._offload_step()
        elif getattr(self, "_pending_full", None) is not None:
            out = self._fused_step_bookkeeping()
        else:
            out = self._boundary_step()
        # boundary tail: the engine is at a clean post-step state here —
        # the only point where a coordinated demotion may rebuild the
        # step programs and where a SIGTERM'd run can checkpoint + exit
        self._finish_demotion()
        if self._autotuner is not None:
            # the online retune loop observes (and may rebuild) ONLY at
            # this clean boundary, like the demotion above
            self._autotuner.on_step_boundary()
        self._maybe_preempt_checkpoint()
        tr = getattr(self, "_tracer", None)
        if tr is not None:
            # resample the trace gate for the next global batch
            self._trace_on = tr.sampled(self.global_steps + 1)
        return out

    def _boundary_step(self):
        """The split/overlap boundary body: drain, apply, bookkeeping."""
        if self._wall_clock_breakdown:
            self.timers("step").start()
        rsp = (self.run_monitor.span("step")
               if self.run_monitor is not None else None)
        self._drain_overlap()
        self._resolve_pending_overflow()
        cur_lr = self._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        (self._params, self._opt_state, self._scaler_state, self._grad_acc,
         overflow, grad_norm, extras) = self._step_fns["apply"](
            self._params, self._opt_state, self._scaler_state,
            self._grad_acc, lr)
        self._qwz_kick_prefetch()
        self._consume_extras(extras)
        self.global_steps += 1
        # DEFERRED overflow handling: bool(overflow) here would sync every
        # step, serializing Python dispatch against device compute (the
        # weight update itself is already branchless-correct in-device).
        # Step the scheduler optimistically; _resolve_pending_overflow
        # rolls it back on the rare overflow step, reading the flag next
        # boundary when the device has long finished.
        self._pending_overflow = overflow
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if rsp is not None:
            rsp.close(sync=grad_norm if self.run_monitor.sync_timing
                      else None)
        if self._wall_clock_breakdown:
            self.timers("step").stop(sync=grad_norm)
            self._log_timers()
        if self.monitor is not None or (
                self.run_monitor is not None
                and self.run_monitor.sync_timing):
            # Monitoring already syncs (float(loss)), so settle the deferred
            # overflow first — else the emitted lr scalar is one scheduler
            # step ahead on an overflowed step. Without a monitor the
            # deferral stands; direct scheduler reads between steps may be
            # one iteration ahead until the next step()/skipped_steps access.
            self._resolve_pending_overflow()
        self._emit_monitor_scalars()
        self.tput_timer.stop(report_speed=False)
        self._queue_step_log()
        self._emit_run_event(grad_norm=grad_norm, overflow=overflow)

    def _queue_step_log(self):
        """steps_per_print logging WITHOUT a device sync: the loss-scale
        scalar is usually still in flight right after the step dispatch,
        so `float()`-ing it here would serialize the Python thread
        against device compute every print window.  Instead the device
        scalar rides a small FIFO ring and the line prints on a later
        step once its buffer is ready — the same deferred settlement
        _resolve_pending_overflow applies to the overflow flag."""
        if self.steps_per_print() and \
                self.global_steps % self.steps_per_print() == 0:
            self._step_log_ring.append(
                (self.global_steps, self._current_lr(),
                 self.tput_timer.avg_samples_per_sec(),
                 self._scaler_state["cur_scale"]))
        self._drain_step_log()

    def _drain_step_log(self, force: bool = False):
        """Emit queued step lines whose scalars have settled (in order);
        `force` (finalize/teardown) and a full ring settle regardless —
        the ring bounds staleness, it never drops a line."""
        ring = self._step_log_ring
        while ring:
            step, lr, sps, scale = ring[0]
            if not force and len(ring) <= _STEP_LOG_RING:
                ready_fn = getattr(scale, "is_ready", None)
                if ready_fn is not None:
                    try:
                        ready = ready_fn()
                    except Exception:
                        ready = True  # no async view: float() below is safe
                    if not ready:
                        return
            ring.popleft()
            lr_str = f"{lr:.3e}" if lr is not None else "optimizer-default"
            log_dist(
                f"step={step}, lr={lr_str}, "
                f"loss_scale={float(scale)}, "
                f"samples/sec={sps:.1f}", ranks=[0])

    def _fused_step_bookkeeping(self):
        """Host-side tail of the fused (gas==1) step: the device update was
        already committed in _fused_forward; advance counters, scheduler,
        PLD and monitoring exactly as the split path does."""
        new_scaler, overflow, _grad_norm = self._pending_full
        self._pending_full = None
        self._scaler_state = new_scaler
        self.global_steps += 1
        self._pending_overflow = overflow
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()  # optimistic; rolled back on overflow
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self._wall_clock_breakdown:
            self._log_timers()
        if self.monitor is not None or (
                self.run_monitor is not None
                and self.run_monitor.sync_timing):
            self._resolve_pending_overflow()
        self._emit_monitor_scalars()
        self.tput_timer.stop(report_speed=False)
        self._queue_step_log()
        self._emit_run_event(grad_norm=_grad_norm, overflow=overflow)

    def _resolve_pending_overflow(self):
        """Apply the host-side bookkeeping for the PREVIOUS step's overflow
        flag (deferred to avoid a per-step device sync). The in-device
        update already skipped the weights and halved the loss scale; here
        we fix the counters and roll the optimistic scheduler step back."""
        pending = getattr(self, "_pending_overflow", None)
        if pending is None:
            return
        self._pending_overflow = None
        if bool(pending):
            self._skipped_steps += 1
            if self.lr_scheduler is not None:
                it = getattr(self.lr_scheduler, "last_batch_iteration", None)
                if it is not None:  # step(-1) is valid (init state)
                    self.lr_scheduler.step(it - 1)  # undo optimistic step
            log_dist(f"overflow: skipped step, new loss scale "
                     f"{float(self._scaler_state['cur_scale'])}", ranks=[0])

    def _log_timers(self):
        """Windowed wall-clock breakdown (reference engine.py:1239-1284):
        per-step means over the steps_per_print window."""
        window = self.steps_per_print() or 1
        if self.global_steps % window == 0:
            self.timers.log(["forward", "step"], normalizer=window,
                            memory_breakdown=self._config.memory_breakdown)

    def _emit_monitor_scalars(self):
        """TensorBoard scalars (reference engine.py:1223-1237)."""
        if self.monitor is None:
            return
        if self._last_loss is not None:
            self.monitor.add_scalar("Train/Samples/train_loss",
                                    float(self._last_loss),
                                    self.global_samples)
        cur = self._current_lr()
        if cur is not None:
            self.monitor.add_scalar("Train/Samples/lr", cur,
                                    self.global_samples)
        self.monitor.add_scalar("Train/Samples/loss_scale",
                                float(self._scaler_state["cur_scale"]),
                                self.global_samples)

    def _offload_step(self):
        """Host-side step: grads D2H -> native CPU-Adam on fp32 masters ->
        updated weights H2D. Loss-scale bookkeeping mirrors the device path."""
        if self._wall_clock_breakdown:
            self.timers("step").start()
        denom = float(self._scaler_state["cur_scale"]) * \
            self.gradient_accumulation_steps()
        if self._config.prescale_gradients:
            denom /= float(self._config.gradient_predivide_factor or 1.0)
        grad_leaves = jax.tree_util.tree_leaves(self._grad_acc)
        new_params, overflow, _norm = self._offload.step(
            grad_leaves, denom, self._current_lr(),
            clip=float(self._config.gradient_clipping or 0.0))
        if self._store_gradients:
            # host path: stash pre-clip unscaled grads (clipping happens
            # inside the native step; documented divergence from the
            # device path's post-clip stash); zeroed on overflow like the
            # device paths — the step was skipped
            treedef = jax.tree_util.tree_structure(self._grad_acc)
            self.stored_gradients = jax.tree_util.tree_unflatten(
                treedef,
                [np.zeros(np.shape(g), np.float32) if overflow
                 else np.asarray(g, np.float32) / denom
                 for g in grad_leaves])
        self._scaler_state = self.loss_scaler.jit_update(
            self._scaler_state, jnp.asarray(overflow))
        self.global_steps += 1
        if overflow:
            self._skipped_steps += 1
            log_dist(f"offload step overflow: skipping, new loss scale "
                     f"{float(self._scaler_state['cur_scale'])}", ranks=[0])
        else:
            self._params = new_params
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        self._grad_acc = None
        if self._wall_clock_breakdown:
            self.timers("step").stop()  # host step: already synchronous
            self._log_timers()
        self._emit_monitor_scalars()
        self.tput_timer.stop(report_speed=False)
        self._emit_run_event(overflow=overflow)

    def _wrap_prefetch(self, loader):
        """Wrap the engine-owned loader in PrefetchLoader when the
        data_pipeline config asks for host-side background collate."""
        dp = self._config.data_pipeline_config
        if not dp.host_prefetch:
            return loader
        return PrefetchLoader(loader, prefetch_depth=dp.prefetch_depth,
                              num_workers=dp.num_workers)

    def _data_feed(self, data_iter, scan: bool) -> Optional[_DeviceFeed]:
        """The (cached) device double-buffer bound to `data_iter`, or
        None when device prefetch is off / the path streams host-side
        (ZeRO-Infinity consumes host batches directly).

        Two cache slots: the engine-OWNED iterator's feed (the only one
        with lookahead, i.e. the only one that can hold a prefetched
        batch) and the latest USER iterator's feed.  Keeping them apart
        means a train_batch(user_iter) call can never evict an owned
        feed whose pending batch was already consumed from the training
        stream — that batch survives for the next train_batch()."""
        dp = self._config.data_pipeline_config
        if not dp.device_feed or self._infinity is not None:
            return None
        owned = data_iter is getattr(self, "_train_iter", None)
        feed = self._device_feed if owned else self._user_device_feed
        if feed is not None and feed.source is data_iter:
            if feed.scan == scan:
                return feed
            if feed.has_pending:
                # a prefetched batch is already placed for the OTHER
                # path's payload shape; silently re-slicing it would be
                # easy to get subtly wrong — fail loud instead
                raise RuntimeError(
                    "data_pipeline: the train_batch step path changed "
                    "mid-accumulation with a prefetched batch in flight "
                    "(manual forward() calls interleaved with "
                    "train_batch?); call train_batch only at "
                    "accumulation boundaries or disable "
                    "data_pipeline.device_prefetch")
        if scan:
            gas = self.gradient_accumulation_steps()

            def _stack(*leaves):
                # host batches stack as numpy (one H2D for the whole
                # global batch at place time); leaves already on device
                # stack as jnp — np.asarray on them would be a blocking
                # D2H round-trip the non-feed path never pays
                if any(isinstance(l, jax.Array) for l in leaves):
                    return jnp.stack([jnp.asarray(l) for l in leaves])
                return np.stack([np.asarray(l) for l in leaves])

            def fetch():
                micro = [self._timed_next(data_iter) for _ in range(gas)]
                try:
                    stacked = jax.tree_util.tree_map(_stack, *micro)
                except (ValueError, TypeError):
                    # heterogeneous micro batches can't stack: hand the
                    # raw list back for the per-micro fallback
                    return ("raw", micro)
                return ("stacked", stacked)

            def place(tagged):
                tag, payload = tagged
                if tag == "stacked":
                    payload = self._shard_batch_stacked(payload)
                return (tag, payload)
        else:
            def fetch():
                return self._timed_next(data_iter)

            place = self._shard_batch
        feed = _DeviceFeed(data_iter, fetch, place, scan=scan,
                           lookahead=owned)
        if owned:
            self._device_feed = feed
        else:
            self._user_device_feed = feed
        return feed

    def train_batch(self, data_iter=None):
        """Convenience: run a full global batch (gas micro steps + update).
        Returns the mean loss (reference PipelineEngine.train_batch parity
        at the engine level).

        With gas > 1 on the standard device path this compiles the WHOLE
        global batch (scan over micro steps + optimizer) into one program
        — a single host dispatch per global batch.

        Input pipeline (config "data_pipeline", default ON): the
        engine-owned iterator runs fetch+collate on background threads
        (PrefetchLoader) and the next batch's H2D transfer is dispatched
        while the current step's program runs (_DeviceFeed), so the host
        gap between step dispatches collapses to a queue pop."""
        if data_iter is None:
            if self.training_dataloader is None:
                raise ValueError("train_batch needs data_iter or training_data")
            if not hasattr(self, "_train_iter"):
                self._train_iter = iter(RepeatingLoader(
                    self._wrap_prefetch(self.training_dataloader)))
            data_iter = self._train_iter
        use_scan = ("full_scan" in self._step_fns and self.micro_steps %
                    self.gradient_accumulation_steps() == 0)
        feed = self._data_feed(data_iter, scan=use_scan)
        if use_scan:
            loss = self._scan_train_batch(data_iter, feed)
            self._advance_sample_cursor(data_iter)
            return loss
        losses = []
        for _ in range(self.gradient_accumulation_steps()):
            batch = (feed.next() if feed is not None
                     else self._timed_next(data_iter))
            losses.append(self.forward(batch))
            self.backward()
            if feed is not None:
                feed.schedule()  # H2D of micro N+1 rides under micro N
        self.step()
        self._advance_sample_cursor(data_iter)
        return jnp.mean(jnp.stack(losses))

    def _advance_sample_cursor(self, data_iter):
        """Advance the engine-owned loader's consumed-side sample
        cursor by the gas batches this train_batch trained on.  Only
        the OWNED iterator advances it: batches a user iterator serves
        are outside the exactly-once contract, and prefetch lookahead
        never counts (produced != consumed)."""
        if data_iter is not getattr(self, "_train_iter", None):
            return
        rec = getattr(self.training_dataloader, "record_consumed", None)
        if rec is not None:
            rec(self.gradient_accumulation_steps())

    def _scan_train_batch(self, data_iter, feed=None):
        if self._overlap_exchange is not None:
            self._check_overlap_health()
            self._predispatch_demotion()
        gas = self.gradient_accumulation_steps()
        if feed is not None:
            tag, payload = feed.next()
            if tag == "raw":
                # heterogeneous micro batches can't stack: fall back
                for batch in payload:
                    self.forward(batch)
                    self.backward()
                self.step()
                return self._last_loss
            stacked = payload  # already device-placed by the feed
        else:
            micro_batches = [self._timed_next(data_iter)
                             for _ in range(gas)]
            try:
                stacked = jax.tree_util.tree_map(
                    lambda *leaves: jnp.stack(
                        [jnp.asarray(l) for l in leaves]), *micro_batches)
            except (ValueError, TypeError):
                # heterogeneous micro batches can't stack: fall back
                for batch in micro_batches:
                    self.forward(batch)
                    self.backward()
                self.step()
                return self._last_loss
        self._resolve_pending_overflow()
        rm = self.run_monitor
        if rm is not None:
            rm.step_start(self.global_steps)
        self.tput_timer.start()
        stacked = self._shard_batch_stacked(stacked)
        if self._autotuner is not None:
            # probe replay stash: one micro slice (the prober re-stacks
            # to whatever gas the probed composition needs).  Unlike
            # the other forward paths' zero-cost reference stash, this
            # slice is a per-leaf device dispatch — autotuned runs only.
            self._autotune_batch = jax.tree_util.tree_map(
                lambda x: x[0], stacked)
        # ONE split dispatch for the whole global batch (a python loop of
        # _next_rng() costs gas separate jax.random.split dispatches):
        # key state folds forward once, per-micro keys peel off the rest
        keys = jax.random.split(self._rng_key, gas + 1)
        self._rng_key, rngs = keys[0], keys[1:]
        theta = jnp.asarray(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop else 1.0, jnp.float32)
        cur_lr = self._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        args = (self._params, self._opt_state, self._scaler_state,
                stacked, rngs, lr, theta)
        if self._qwz_overlap is not None:
            # the gather rides the host exchange ONCE per global batch,
            # prefetched behind the previous step's apply
            args = args + (self._step_cparams(),)
        self._maybe_monitor_flops(self._step_fns["full_scan"].fn, *args)
        sp = rm.span("forward") if rm is not None else None
        (self._params, self._opt_state, new_scaler, loss, overflow,
         grad_norm, extras) = self._step_fns["full_scan"](*args)
        self._qwz_kick_prefetch()
        if feed is not None:
            # the scan program is in flight: collate + H2D of the NEXT
            # global batch overlap it (before any sync-closing span)
            feed.schedule()
        if sp is not None:
            sp.close(sync=loss if rm.sync_timing else None)
        self._consume_extras(extras)
        self.micro_steps += gas
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size * gas
        self._pending_full = (new_scaler, overflow, grad_norm)
        self._last_loss = loss
        self._cached = None
        self.step()  # host bookkeeping via _fused_step_bookkeeping
        return loss

    def _shard_batch_stacked(self, stacked):
        """Place a [gas, B, ...] stacked batch: data axis on dim 1."""
        mesh = self.mesh_info.mesh

        def put(x):
            x = jnp.asarray(x)
            spec = [None] * x.ndim
            if x.ndim > 1 and x.shape[1] % max(1, self.dp_world_size) == 0:
                spec[1] = self.mesh_info.data_spec
            target = NamedSharding(mesh, PartitionSpec(*spec))
            if isinstance(x, jax.Array) and \
                    x.sharding.is_equivalent_to(target, x.ndim):
                return x
            COUNTERS.add("input.h2d_bytes", int(x.nbytes))
            return jax.device_put(x, target)

        return jax.tree_util.tree_map(put, stacked)

    def eval_batch(self, batch, rng=None):
        """Loss without gradient/bookkeeping side effects (jitted + cached)."""
        if self._infinity is not None:
            return self._infinity.eval_loss(batch)
        if not hasattr(self, "_eval_fn"):
            model = self.module
            dtype = self.compute_dtype

            def eval_fn(params, batch, rng):
                cparams = jax.tree_util.tree_map(
                    lambda x: x.astype(dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
                out = model.loss(cparams, batch, rng=rng, train=False)
                return out[0] if isinstance(out, tuple) else out

            self._eval_fn = jax.jit(eval_fn)
        batch = self._shard_batch(batch)
        rng = rng if rng is not None else self._next_rng()
        return self._eval_fn(self._params, batch, rng)

    # ------------------------------------------------------------------
    # accessors (reference engine.py:300-536)
    # ------------------------------------------------------------------

    @property
    def params(self):
        if self._infinity is not None:
            return self._infinity.masters_tree()  # host fp32 masters
        return self._params

    def get_batch_info(self):
        """(train_batch_size, micro_batch_size, gradient_accumulation_steps)
        — reference engine.py:256-268."""
        return (self._config.train_batch_size,
                self._config.train_micro_batch_size_per_gpu,
                self._config.gradient_accumulation_steps)

    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def steps_per_print(self):
        return self._config.steps_per_print

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def precision(self):
        return self._config.precision

    # -- config accessor surface (reference engine.py:300-536) ---------

    def train(self, mode: bool = True):
        """torch Module-parity mode toggle. Train/eval behaviour here is
        selected per-call (model.loss(train=...)), so this only records
        intent for API compatibility."""
        self.training = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def zero_grad(self):
        """API parity (reference engine.py:1103): gradient zeroing happens
        inside the jitted apply step (the accumulator is returned zeroed),
        so there is nothing to do between steps."""
        self._grad_acc = None

    def allreduce_gradients(self, bucket_size=None, hierarchy=None):
        """reference engine.py:1023-1038.  DP gradient reduction runs
        INSIDE the jitted step here — through the BucketPlan's fused
        collectives when `comm.gradient_reduction=="bucketed"`, else
        XLA's implicit psum — so by the time this can be called the
        gradients are already reduced and there is no separate pass to
        run.  What the call CAN do:

        * `bucket_size` (elements, the reference's meaning) retunes the
          BucketPlan and recompiles the step programs when the bucketed
          wire is active — the reference's dynamic-bucket knob.
        * `hierarchy` (an outer factor, or {"outer": n}) is VALIDATED
          against the dp size with a shape-level ValueError naming the
          axis sizes — never traced into an opaque reshape error.  The
          factorization itself is fixed at initialize() (it is the mesh
          layout every array placement derives from), so a valid factor
          that differs from the current mesh raises too, pointing at the
          config knob.
        * On paths where globally-reduced gradients never exist (the
          1-bit compressed wire, ZeRO-Infinity streaming) it raises
          instead of silently lying about having reduced anything."""
        if self._infinity is not None or getattr(self, "_onebit_hot", False):
            raise RuntimeError(
                "allreduce_gradients: globally-reduced gradients never "
                "materialize on this path (ZeRO-Infinity streams per-block "
                "grads; the 1-bit optimizer owns the compressed wire) — "
                "there is nothing to reduce")
        if hierarchy is not None:
            from .config import check_hierarchy_divides, parse_comm_hierarchy

            parsed = parse_comm_hierarchy(hierarchy)
            dp = self.mesh_info.axis_size(DATA_AXIS)
            current = self.mesh_info.data_outer_size
            if isinstance(parsed, int):
                check_hierarchy_divides(parsed, dp)
            if parsed == "auto":
                parsed = comm.derive_data_outer(dp)
                parsed = "none" if parsed == 1 else parsed
            wanted = 1 if parsed == "none" else int(parsed)
            if wanted != current and not (
                    wanted > 1 and dp // wanted == 1 and current == 1):
                raise ValueError(
                    f"allreduce_gradients: the data-axis factorization is "
                    f"the mesh layout and is fixed at initialize() — "
                    f"currently data_outer={current} x data_inner="
                    f"{dp // max(1, current)}; set comm.hierarchy in the "
                    f"config to train with data_outer={wanted}")
        if bucket_size is not None and self.bucket_plan is not None and \
                int(bucket_size) != self.bucket_plan.bucket_elems:
            self._config.comm_config.reduce_bucket_size = int(bucket_size)
            # settle in-flight overlapped exchanges against the CURRENT
            # plan's combine before it is replaced — a mid-accumulation
            # retune must not drop already-dispatched micro gradients
            self._drain_overlap()
            self.bucket_plan = self._build_bucket_plan()
            self._build_overlap()  # payload layout follows the plan
            self._step_fns = self._build_step_fns()
            log_dist("allreduce_gradients: rebucketed -> "
                     + self.bucket_plan.describe(), ranks=[0])
        elif not getattr(self, "_warned_allreduce_noop", False):
            self._warned_allreduce_noop = True
            log_dist("allreduce_gradients: reduction already runs in-jit ("
                     + (self.bucket_plan.describe() if self.bucket_plan
                        else "implicit XLA psum at the loss-mean boundary")
                     + "); nothing to do", ranks=[0])

    def get_mom(self):
        """First-moment decay (beta1) per param group (reference :525)."""
        groups = getattr(self.optimizer, "param_groups", None) or []
        out = []
        for g in groups:
            if "betas" in g:
                out.append(g["betas"][0])
            else:
                out.append(g.get("momentum", 0.0))
        return out

    def get_pld_theta(self):
        if self.progressive_layer_drop is not None:
            return self.progressive_layer_drop.get_theta()
        return None

    def pld_enabled(self):
        return self._config.pld_enabled

    def pld_params(self):
        return self._config.pld_params

    def pld_theta(self):
        return (self._config.pld_params or {}).get(const.PLD_THETA, 1.0)

    def pld_gamma(self):
        return (self._config.pld_params or {}).get(const.PLD_GAMMA, 0.001)

    def get_summary_writer(self):
        return getattr(self.monitor, "writer", None)

    def dump_state(self):
        return self._config.dump_state

    def dynamic_loss_scale(self):
        return self._config.loss_scale == 0

    def initial_dynamic_scale(self):
        return 2 ** self._config.initial_scale_power

    def dynamic_loss_scale_args(self):
        return {"init_scale": 2 ** self._config.initial_scale_power,
                "scale_window": self._config.loss_scale_window,
                "delayed_shift": self._config.hysteresis,
                "min_scale": self._config.min_loss_scale}

    def amp_enabled(self):
        return self._config.amp_enabled

    def amp_params(self):
        return self._config.amp_params

    def elasticity_enabled(self):
        return self._config.elasticity_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def allreduce_always_fp32(self):
        """reference fp32_allreduce option.  The implicit wire always
        accumulates in fp32 (grads are cast before the psum); the
        bucketed wire reports its configured dtype — bf16/split wires
        trade accumulation width for bytes (comm_tuning.md).  Active
        layer-output capture forces the step programs back onto the
        implicit fp32 wire (_build_step_fns), so report THAT."""
        if self.bucket_plan is not None and self._capture_layers is None:
            return self.bucket_plan.exact_fp32
        return True

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def optimizer_name(self):
        return self._config.optimizer_name

    def optimizer_params(self):
        return self._config.optimizer_params

    def optimizer_legacy_fusion(self):
        return self._config.optimizer_legacy_fusion

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def wall_clock_breakdown(self):
        return self._wall_clock_breakdown

    def tensorboard_enabled(self):
        return self._config.tensorboard_enabled

    def tensorboard_output_path(self):
        return self._config.tensorboard_output_path

    def tensorboard_job_name(self):
        return self._config.tensorboard_job_name

    def checkpoint_tag_validation_enabled(self):
        return self._config.checkpoint_tag_validation_enabled

    def checkpoint_tag_validation_fail(self):
        return self._config.checkpoint_tag_validation_fail

    def flops_profiler_enabled(self):
        return self._config.flops_profiler_config.enabled

    def flops_profiler_profile_step(self):
        return self._config.flops_profiler_config.profile_step

    def flops_profiler_module_depth(self):
        return self._config.flops_profiler_config.module_depth

    def flops_profiler_top_modules(self):
        return self._config.flops_profiler_config.top_modules

    def flops_profiler_detailed(self):
        return self._config.flops_profiler_config.detailed

    def zero_optimization(self):
        return self._config.zero_enabled

    def zero_allow_untested_optimizer(self):
        return self._config.zero_allow_untested_optimizer

    def zero_reduce_scatter(self):
        return self._config.zero_config.reduce_scatter

    def zero_overlap_comm(self):
        return self._config.zero_config.overlap_comm

    def zero_cpu_offload(self):
        return self._config.zero_config.cpu_offload

    def zero_offload_optimizer(self):
        return self._config.zero_config.offload_optimizer

    def zero_offload_param(self):
        return self._config.zero_config.offload_param

    def zero_sub_group_size(self):
        return self._config.zero_config.sub_group_size

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_reduce_bucket_size(self):
        return self._config.zero_config.reduce_bucket_size

    def zero_allgather_bucket_size(self):
        return self._config.zero_config.allgather_bucket_size

    def zero_allgather_partitions(self):
        return self._config.zero_config.allgather_partitions

    def zero_contiguous_gradients(self):
        return self._config.zero_config.contiguous_gradients

    def zero_elastic_checkpoint(self):
        return self._config.zero_config.elastic_checkpoint

    def zero_load_from_fp32_weights(self):
        return self._config.zero_config.load_from_fp32_weights

    def zero_max_live_parameters(self):
        return self._config.zero_config.max_live_parameters

    def zero_max_reuse_distance(self):
        return self._config.zero_config.max_reuse_distance

    def zero_prefetch_bucket_size(self):
        return self._config.zero_config.prefetch_bucket_size

    def zero_param_persistence_threshold(self):
        return self._config.zero_config.param_persistence_threshold

    def zero_gather_fp16_weights_on_model_save(self):
        return self._config.zero_config.gather_fp16_weights_on_model_save

    def zero_optimization_partition_gradients(self):
        return self.zero_optimization_stage() >= 2

    def zero_optimization_partition_weights(self):
        return self.zero_optimization_stage() >= 3

    def module_state_dict(self):
        """Module weights as a host pytree (reference engine.py:1443)."""
        return jax.tree_util.tree_map(np.asarray, self.params)

    def load_module_state_dict(self, state_dict, strict=True):
        """Replace module weights from a host pytree (reference :1456).
        strict: require the same tree structure.

        Under CPU-offload/Infinity the fp32 masters are re-seeded from the
        given weights — if those came from module_state_dict() (compute
        dtype under offload), master precision is truncated to it. Use
        save_checkpoint/load_checkpoint to move state losslessly."""
        if strict:
            expect = jax.tree_util.tree_structure(self.params)
            got = jax.tree_util.tree_structure(state_dict)
            if expect != got:
                raise ValueError(
                    f"state_dict tree mismatch: {got} != {expect}")
        self._install_module_weights(state_dict)

    def _install_module_weights(self, host_tree):
        """Weight install shared by load_checkpoint and
        load_module_state_dict. Infinity: host masters only (the streamed
        tree must never fully materialize on device). Offload: reseed the
        fp32 masters and keep compute-dtype working weights on device.
        Otherwise: device fp32 tree under the ZeRO plan's shardings."""
        if self._infinity is not None:
            self._infinity.load_masters_tree(host_tree)
            return
        params = jax.tree_util.tree_map(jnp.asarray, host_tree)
        if self._offload is not None:
            self._offload.masters = [
                np.asarray(l, np.float32).ravel().copy()
                for l in jax.tree_util.tree_leaves(host_tree)]
            params = jax.tree_util.tree_map(
                lambda p: p.astype(self.compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        self._params = jax.device_put(params,
                                      self.zero_plan.param_shardings())

    @property
    def skipped_steps(self):
        """Resolves the deferred overflow flag first, so callers see
        settled counters (the deferral is a dispatch optimization, not an
        API change)."""
        self._resolve_pending_overflow()
        return self._skipped_steps

    @property
    def loss_scale(self):
        return float(self._scaler_state["cur_scale"])

    def get_lr(self):
        return [g["lr"] for g in getattr(self.optimizer, "param_groups",
                                         [{"lr": 0.0}])]

    def deepspeed_io(self, dataset, batch_size=None, route=None,
                     data_sampler=None, collate_fn=None, num_local_io_workers=None):
        """reference engine.py:882 — build the distributed dataloader.

        Single-controller JAX consumes the GLOBAL micro batch
        (micro_per_gpu * dp_world) per forward, and EVERY process
        assembles the SAME global batch: `device_put(host_value,
        global_sharding)` treats each process's value as the global
        array (the same-value-everywhere contract, _compat.py), so a
        process-strided per-shard slice here would hand it W different
        "globals" and silently train on a torn mix of them — found by
        the elastic campaign's cross-width loss-parity pin.  Each
        process transfers only its addressable shard of the batch it
        assembled, so device bytes stay 1/dp; the host-side read
        amplification is the single-controller trade.  (Per-process
        strided loading remains available to direct
        DeepSpeedDataLoader users via the data_parallel_* arguments.)"""
        global_micro = (batch_size if batch_size is not None else
                        self.train_micro_batch_size_per_gpu() *
                        self.dp_world_size)
        return DeepSpeedDataLoader(
            dataset, batch_size=global_micro, shuffle=True,
            collate_fn=collate_fn or self.collate_fn,
            data_parallel_world_size=1, data_parallel_rank=0)

    def save_fp16_model(self, save_dir, save_filename="mp_rank_00_model_states.msgpack"):
        """Weights-only export in the compute dtype (reference
        engine.py:1882 save_fp16_model): no optimizer/scheduler state,
        loadable as a plain pytree."""
        from flax import serialization

        tree = self.module_state_dict_fp16()
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, save_filename)
        if jax.process_index() == 0:
            with open(path, "wb") as f:
                f.write(serialization.msgpack_serialize(tree))
        log_dist(f"saved {self.precision()} model weights to {path}",
                 ranks=[0])
        return path

    def module_state_dict_fp16(self):
        """Consolidated compute-dtype weights (reference
        _zero3_consolidated_fp16_state_dict, engine.py:1820-1881): for
        ZeRO-3 the per-leaf host fetch performs the all-gather the
        reference hand-rolls with partition hooks; non-addressable
        (multi-host) shards gather via process_allgather first."""
        params = self.params  # infinity: host masters; else device tree
        dtype = self.compute_dtype

        def to_host(p):
            if isinstance(p, jax.Array) and not p.is_fully_addressable:
                from jax.experimental import multihost_utils

                p = multihost_utils.process_allgather(p, tiled=True)
            floating = jnp.issubdtype(
                getattr(p, "dtype", np.float32), jnp.floating)
            arr = np.asarray(p)
            return arr.astype(dtype) if floating else arr

        return jax.tree_util.tree_map(to_host, params)

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:1491-1890)
    # ------------------------------------------------------------------

    def _client_state(self, client_state: Dict[str, Any]):
        state = dict(client_state or {})
        state.update({
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            "micro_steps": self.micro_steps,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
        })
        return state

    def _async_ckpt_snapshot(self, tree):
        """Device-copy every jax.Array leaf and kick the D2H transfers;
        host leaves pass through (the checkpoint layer snapshots
        in-place-mutating numpy masters itself).  All leaves ride ONE
        jitted copy program — per-leaf jnp.copy costs a dispatch each
        (~15 ms of blocked training for an MLP-sized tree on the CPU
        box), the fused program costs one.  jit never aliases these
        outputs to their inputs (jnp.copy defeats the input-passthrough
        sharing), so the copies survive later steps donating the
        original buffers."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        idx = [i for i, l in enumerate(leaves) if isinstance(l, jax.Array)]
        if idx:
            if not hasattr(self, "_ckpt_copy_fn"):
                self._ckpt_copy_fn = jax.jit(
                    lambda xs: [jnp.copy(x) for x in xs])
            copies = self._ckpt_copy_fn([leaves[i] for i in idx])
            for i, c in zip(idx, copies):
                leaves[i] = c
        snapped = jax.tree_util.tree_unflatten(treedef, leaves)
        ckpt_io.prefetch_to_host(snapped)
        return snapped

    def _checkpoint_meta(self):
        """Saving-run topology recorded in the commit marker — what a
        restoring run needs to reshard ZeRO-1/2 partitions (incl. hpZ
        secondary shards) onto its own (dp, hierarchy) layout."""
        meta = {
            "world_size": jax.process_count(),
            "mp_world_size": self.mp_world_size,
            "dp_world_size": self.dp_world_size,
            "zero_stage": self.zero_optimization_stage(),
            "data_outer": 1,
            "data_inner": self.dp_world_size,
            "hierarchical": False,
            "global_steps": self.global_steps,
        }
        if self.zero_plan is not None:
            meta.update(self.zero_plan.partition_layout())
        cursor_fn = getattr(self.training_dataloader, "sample_cursor",
                            None)
        if cursor_fn is not None:
            # global sample cursor (epoch, position, shuffle seed): a
            # restoring run — at ANY dp width — resumes the engine-owned
            # loader exactly one batch past the last trained one, so
            # across a shrink->grow cycle every sample is consumed
            # exactly once (runtime/dataloader.py load_sample_cursor)
            meta["sample_cursor"] = cursor_fn()
        return meta

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        self._resolve_pending_overflow()  # counters must be settled
        if tag is None:
            tag = f"global_step{self.global_steps}"
        self._checkpoint_tag_validation(tag)
        inf_sd = None
        if self._infinity is not None:
            if self._infinity.pager is not None:
                # NVMe-paged masters: stream group files directly from the
                # pages — never materialize the full fp32 set in host RAM
                module_np, inf_sd = self._infinity.save_streamed(
                    os.path.join(save_dir, str(tag)))
            else:
                module_np = self._infinity.masters_tree()
        elif self._offload is not None:
            # host fp32 masters are the source of truth under offload
            module_np = jax.tree_util.tree_unflatten(
                self._offload.treedef,
                [m.reshape(s) for m, s in zip(self._offload.masters,
                                              self._offload.shapes)])
        else:
            # device tree passes through as-is: the checkpoint writer
            # serializes sharded leaves per-shard (no host gather)
            module_np = self._params
        model_state = {
            "module": module_np,
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None else None),
            "loss_scaler": {
                k: np.asarray(v) for k, v in self._scaler_state.items()},
            "rng_key": np.asarray(self._rng_key),
            **self._client_state(client_state),
        }
        opt_to_save = self._opt_state
        if opt_to_save is not None and hasattr(self.optimizer,
                                               "serialize_state"):
            # optimizers with msgpack-hostile state (optax namedtuples)
            # flatten themselves; deserialize_state rebuilds on load
            opt_to_save = self.optimizer.serialize_state(opt_to_save)
        if getattr(self, "_onebit_hot", False) and opt_to_save is not None:
            # per-rank error-feedback buffers ([dp, *param] fp32 x2) are
            # re-zeroed on load anyway — don't write 2x dp x model-size of
            # dead weight into every checkpoint
            opt_to_save = {k: v for k, v in opt_to_save.items()
                           if k not in ("worker_error", "server_error")}
        optim_state = {
            "optimizer_state": (
                inf_sd if inf_sd is not None
                else self._infinity.state_dict() if self._infinity is not None
                else self._offload.state_dict() if self._offload is not None
                else opt_to_save),
            "offload": (self._offload is not None
                        or self._infinity is not None),
            # json round-trip: msgpack rejects tuples (betas); lists restore fine
            "optimizer_hparams": (json.loads(json.dumps(
                self.optimizer.state_dict()))
                if hasattr(self.optimizer, "state_dict") else None),
            "zero_stage": self.zero_optimization_stage(),
        }
        async_save = bool(getattr(self._config, "checkpoint_async_save",
                                  False))
        if async_save:
            # non-blocking device snapshot right after the step dispatch:
            # jnp.copy enqueues an identity program per leaf (it runs the
            # moment the in-flight step finishes — the training thread
            # never waits), and copy_to_host_async starts the D2H behind
            # it.  Donation-safe by construction: the copies are fresh
            # arrays that never enter any step program's donate_argnums,
            # so the background writer can np.asarray them long after
            # later steps have donated the ORIGINAL param/opt buffers
            # away (same discipline as _DeviceFeed's fresh per-place
            # arrays).
            model_state, optim_state = self._async_ckpt_snapshot(
                (model_state, optim_state))
        snap = COUNTERS.snapshot()
        t0_save = time.perf_counter()
        ckpt_io.save_checkpoint_state(
            save_dir, tag, model_state, optim_state, save_latest=save_latest,
            async_save=async_save, meta=self._checkpoint_meta(),
            commit_timeout_ms=getattr(self._config,
                                      "checkpoint_commit_timeout_ms",
                                      ckpt_io.COMMIT_TIMEOUT_MS),
            device_leaves_are_snapshots=async_save)
        tr = self._dispatch_tracer()
        if tr is not None:
            tr.add_complete(
                "ckpt_stall", "ckpt",
                dur_us=int((time.perf_counter() - t0_save) * 1e6),
                tag=str(tag), step=self.global_steps)
        if self.run_monitor is not None:
            delta = COUNTERS.delta_since(snap)
            self.run_monitor.emit("ckpt", {
                "tag": str(tag),
                "async": async_save,
                "stall_ms": round(delta.get("ckpt.stall_ms", {})
                                  .get("bytes", 0) / 1000.0, 3),
                "pending": ckpt_io.pending_count(),
                "step": self.global_steps,
            })
        return True

    def _log_checkpoint_reshard(self, load_dir, ckpt_dir):
        """Announce a topology transition recorded in the commit marker
        (saved (dp, hierarchy, stage) != restoring) — the actual
        re-partition is the device_put under this run's own sharding
        plan below; this makes it legible instead of silent.  An
        elastic world-size transition additionally bumps the
        `elastic.shrinks`/`elastic.regrows` counters (rendered in the
        run report's Resilience section, excluded from the comm byte
        table like `fault.*`).  Returns the marker so callers (sample-
        cursor restore) don't pay the read twice."""
        from .zero.partition import describe_reshard

        marker = ckpt_io.read_tag_meta(load_dir, os.path.basename(ckpt_dir))
        saved = (marker or {}).get("meta")
        msg = describe_reshard(saved, self._checkpoint_meta(),
                               reason=(self._elastic.reason
                                       if self._elastic.active else None))
        if msg:
            log_dist(msg, ranks=[0])
        try:
            saved_dp = int((saved or {}).get("dp_world_size"))
        except (TypeError, ValueError):
            saved_dp = None
        if saved_dp is not None:
            cur_dp = self.mesh_info.get_data_parallel_world_size()
            if cur_dp < saved_dp:
                COUNTERS.add("elastic.shrinks")
            elif cur_dp > saved_dp:
                COUNTERS.add("elastic.regrows")
        return marker

    def _restore_sample_cursor(self, marker):
        """Apply the commit marker's global sample cursor to the
        engine-owned loader (shard-aware: the loader converts the
        position to ITS width), and drop any iterator/prefetch/device-
        feed state built before the restore — those batches came from
        the pre-restore cursor and would double-serve samples."""
        loader = self.training_dataloader
        restore = getattr(loader, "load_sample_cursor", None)
        cursor = ((marker or {}).get("meta") or {}).get("sample_cursor")
        if cursor is None or restore is None:
            return
        restore(cursor)
        # drop iterator/prefetch/device-feed state built on the stale
        # cursor (one teardown path: prefetch threads, both feeds,
        # the owned iterator)
        self.close_data_pipeline()
        log_dist(
            f"sample cursor restored: epoch {loader._consumed_epoch}, "
            f"batch {loader._consumed_position} of {len(loader)} — the "
            f"exactly-once stream resumes shard-aware at "
            f"dp={self.dp_world_size}", ranks=[0])

    def _checkpoint_tag_validation(self, tag):
        """All ranks must agree on the tag (reference :1671-1686). In
        single-controller JAX ranks share the tag by construction; validate
        printable-ness only."""
        if self._config.checkpoint_tag_validation_enabled:
            if any(ch in str(tag) for ch in "\n\t "):
                msg = f"checkpoint tag {tag!r} contains whitespace"
                if self._config.checkpoint_tag_validation_fail:
                    raise ValueError(msg)
                logger.warning(msg)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True):
        # a paged Infinity engine walks stream-group files RAM-bounded;
        # everyone else materializes markers here (resolve_streamed)
        paged = (self._infinity is not None
                 and self._infinity.pager is not None)
        try:
            ckpt_dir, model_state, optim_state = ckpt_io.load_checkpoint_state(
                load_dir, tag, resolve_streams=not paged)
        except FileNotFoundError as e:
            # nothing to resume from — warn and train fresh.  A tag that
            # EXISTS but is uncommitted/incomplete raises
            # CheckpointIntegrityError instead, which propagates: silently
            # restarting from scratch over a damaged checkpoint would
            # throw the run away.
            logger.warning(f"load_checkpoint: {e}")
            return None, {}
        marker = self._log_checkpoint_reshard(load_dir, ckpt_dir)
        self._restore_sample_cursor(marker)

        if self._infinity is not None:
            if paged and ckpt_io.has_stream_markers(model_state["module"]):
                # an incomplete group-file set raises
                # CheckpointIntegrityError from load_streamed's pre-flight
                # (nothing was mutated) and propagates — the tag exists,
                # so "warn and train fresh" would be the wrong outcome
                self._infinity.load_streamed(
                    ckpt_dir,
                    optim_state["optimizer_state"]
                    if (load_optimizer_states
                        and optim_state is not None
                        and optim_state.get("offload")) else None)
            else:
                # non-paged engines got markers resolved by
                # load_checkpoint_state (resolve_streams=True above)
                self._infinity.load_masters_tree(model_state["module"])
                if load_optimizer_states and optim_state is not None and \
                        optim_state.get("offload"):
                    self._infinity.load_state_dict(
                        optim_state["optimizer_state"])
            if model_state.get("loss_scaler") is not None:
                self._scaler_state = {
                    k: jnp.asarray(v)
                    for k, v in model_state["loss_scaler"].items()}
            if load_lr_scheduler_states and self.lr_scheduler is not None \
                    and model_state.get("lr_scheduler") is not None:
                self.lr_scheduler.load_state_dict(model_state["lr_scheduler"])
            if model_state.get("rng_key") is not None:
                self._rng_key = jnp.asarray(model_state["rng_key"])
            self.global_steps = int(model_state.get("global_steps", 0))
            self.global_samples = int(model_state.get("global_samples", 0))
            self._skipped_steps = int(model_state.get("skipped_steps", 0))
            self.micro_steps = int(model_state.get("micro_steps", 0))
            self.loaded_checkpoint_tag = os.path.basename(ckpt_dir)
            client_state = {k: v for k, v in model_state.items()
                            if k not in ("module", "lr_scheduler",
                                         "loss_scaler")}
            return ckpt_dir, client_state

        self._install_module_weights(model_state["module"])
        if load_optimizer_states and optim_state is not None and \
                self._offload is not None and optim_state.get("offload"):
            self._offload.load_state_dict(optim_state["optimizer_state"])
        elif load_optimizer_states and optim_state is not None and \
                self._offload is None:
            restored = optim_state["optimizer_state"]
            if hasattr(self.optimizer, "deserialize_state"):
                restored = self.optimizer.deserialize_state(
                    restored, self._params)
            if getattr(self, "_onebit_hot", False):
                # per-rank error-feedback buffers are world-size-shaped;
                # on any resume they restart at zero for the CURRENT dp
                # (reference re-inits them on topology change too) — a
                # transient, convergence-benign reset
                restored = {k: v for k, v in restored.items()
                            if k not in ("worker_error", "server_error")}
                keep = {k: self._opt_state[k]
                        for k in ("worker_error", "server_error")}
                zeroed = jax.tree_util.tree_map(jnp.zeros_like, keep)
                opt = jax.tree_util.tree_map(jnp.asarray, restored)
                self._opt_state = {
                    **jax.device_put(
                        opt, self.zero_plan.opt_state_shardings(opt)),
                    **zeroed}
            else:
                opt = jax.tree_util.tree_map(jnp.asarray, restored)
                self._opt_state = jax.device_put(
                    opt, self.zero_plan.opt_state_shardings(opt))
            hparams = optim_state.get("optimizer_hparams")
            if hparams is not None and hasattr(self.optimizer,
                                               "load_state_dict"):
                # restores runtime lr/beta mutations (e.g. manual decay)
                self.optimizer.load_state_dict(
                    jax.tree_util.tree_map(
                        lambda x: x.item() if hasattr(x, "item") and
                        getattr(x, "ndim", 1) == 0 else x, hparams))
        if model_state.get("loss_scaler") is not None:
            self._scaler_state = {
                k: jnp.asarray(v) for k, v in model_state["loss_scaler"].items()}
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                model_state.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(model_state["lr_scheduler"])
            # re-apply the restored schedule position to param_groups so the
            # first post-resume step uses the right lr
            it = getattr(self.lr_scheduler, "last_batch_iteration", None)
            if it is not None and it >= 0:
                self.lr_scheduler.step(it)
        if model_state.get("rng_key") is not None:
            self._rng_key = jnp.asarray(model_state["rng_key"])
        self.global_steps = int(model_state.get("global_steps", 0))
        self.global_samples = int(model_state.get("global_samples", 0))
        self._skipped_steps = int(model_state.get("skipped_steps", 0))
        self.micro_steps = int(model_state.get("micro_steps", 0))
        self._grad_acc = None
        self.loaded_checkpoint_tag = os.path.basename(ckpt_dir)

        client_state = {k: v for k, v in model_state.items()
                        if k not in ("module", "lr_scheduler", "loss_scaler")}
        return ckpt_dir, client_state
