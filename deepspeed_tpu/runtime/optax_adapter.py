"""Adapter exposing any optax GradientTransformation as an engine
optimizer.

Reference analogue: the engine's torch.optim passthrough — reference
_configure_basic_optimizer falls back to any torch optimizer class
(engine.py:702-757) and `zero_allow_untested_optimizer` gates ZeRO over
it. Here the whole JAX optimizer ecosystem plugs in the same way:

    import optax
    opt = OptaxOptimizer(optax.adafactor(learning_rate=1e-3))
    engine, *_ = ds.initialize(model=model, optimizer=opt, config=cfg)

The adapter satisfies the engine's functional protocol
(init / update(grads, state, params, lr)) and the torch-style
param_groups surface the LR schedulers mutate. A schedule-driven lr is
threaded by injecting it through optax's standard `learning_rate`
hyperparameter when the transformation was built with
optax.inject_hyperparams, else by scaling the update (exact for any
transform whose final step is scale_by_learning_rate, i.e. all stock
optax optimizers)."""

from __future__ import annotations

import warnings
from typing import Any, Optional

import jax
import jax.numpy as jnp


class OptaxOptimizer:
    name = "OptaxOptimizer"

    def __init__(self, transform, lr: Optional[float] = None):
        """transform: an optax.GradientTransformation (or the result of
        optax.inject_hyperparams(...) for exact lr injection). lr: the
        nominal learning rate exposed to schedulers via param_groups;
        defaults to 1.0, meaning scheduler values multiply the
        transform's own internal rate."""
        self.transform = transform
        self.param_groups = [dict(lr=1.0 if lr is None else float(lr))]
        # schedulers may overwrite param_groups lr in their ctor (e.g.
        # LRRangeTest), so whether the user left lr defaulted must be
        # recorded now for warn_if_rescale_inexact
        self._lr_was_default = lr is None
        self._warned_rescale = False

    @property
    def lr(self):
        return self.param_groups[0]["lr"]

    def init(self, params):
        return {"optax": self.transform.init(params),
                "_base_lr": jnp.asarray(self.lr, jnp.float32)}

    def _inject_lr(self, opt_state, lr):
        """If the state carries inject_hyperparams' hyperparams dict with
        a learning_rate entry, set it (exact); returns (state, handled)."""
        hp = getattr(opt_state, "hyperparams", None)
        if isinstance(hp, dict) and "learning_rate" in hp:
            new_hp = dict(hp)
            new_hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
            return opt_state._replace(hyperparams=new_hp), True
        return opt_state, False

    def update(self, grads, state, params, lr=None, **_):
        opt_state = state["optax"]
        base_lr = state["_base_lr"]
        handled = False
        if lr is not None:
            opt_state, handled = self._inject_lr(opt_state, lr)
        updates, new_opt = self.transform.update(grads, opt_state, params)
        if lr is not None and not handled:
            # stock optax optimizers end in scale_by_learning_rate, so a
            # multiplicative rescale by (lr / base_lr) is exact
            ratio = jnp.asarray(lr, jnp.float32) / jnp.maximum(
                base_lr, jnp.asarray(1e-30, jnp.float32))
            updates = jax.tree_util.tree_map(
                lambda u: (u.astype(jnp.float32) * ratio).astype(u.dtype),
                updates)
        new_params = jax.tree_util.tree_map(
            lambda p, u: (p.astype(jnp.float32) +
                          u.astype(jnp.float32)).astype(p.dtype),
            params, updates)
        return new_params, {"optax": new_opt, "_base_lr": base_lr}

    def warn_if_rescale_inexact(self) -> None:
        """Engine hook, called once when an lr scheduler is attached. The
        scheduler's lr reaches update() as a traced array, so the footgun
        (scheduler emits absolute lrs while base_lr defaulted to 1.0 and
        the transform has its own rate baked in — effective lr becomes the
        PRODUCT) can only be diagnosed here, before tracing."""
        if self._warned_rescale:
            return
        try:  # cheap probe: does init expose inject_hyperparams' dict?
            state = self.transform.init({"_p": jnp.zeros((1,), jnp.float32)})
        except Exception:
            # structure-sensitive transform (multi_transform, masked, ...):
            # can't tell from a dummy tree whether injection works — stay
            # silent rather than false-alarm (best-effort diagnostic only)
            return
        _, handled = self._inject_lr(state, self.lr)
        if handled:
            return  # exact lr injection available; no rescale fallback
        if self._lr_was_default:
            warnings.warn(
                "OptaxOptimizer: an lr scheduler is attached but the "
                "transform was not built with optax.inject_hyperparams, so "
                "scheduler values are applied by multiplicative rescale "
                "against base_lr=1.0. If the transform has its own learning "
                "rate baked in, the scheduler value MULTIPLIES it (e.g. "
                "1e-3 x 1e-3 = 1e-6 effective). Pass lr=<the transform's "
                "rate> to OptaxOptimizer, or build it with "
                "optax.inject_hyperparams for exact injection. The rescale "
                "is only exact for transforms ending in "
                "scale_by_learning_rate.", stacklevel=2)
            self._warned_rescale = True

    # torch-parity niceties used by checkpoint/save paths
    def state_dict(self) -> Any:
        return {"param_groups": self.param_groups}

    def load_state_dict(self, sd) -> None:
        if sd and "param_groups" in sd:
            self.param_groups = [dict(g) for g in sd["param_groups"]]

    # checkpoint protocol: optax states contain arbitrary namedtuples the
    # msgpack writer can't encode; flatten to a leaf list and rebuild the
    # structure from a fresh init at load (engine save/load hooks these)
    def serialize_state(self, state):
        return {"__optax_leaves__": list(jax.tree_util.tree_leaves(state))}

    def deserialize_state(self, payload, params):
        if not (isinstance(payload, dict) and "__optax_leaves__" in payload):
            return payload  # old/plain format
        # eval_shape: the treedef without allocating a throwaway state
        template = jax.eval_shape(self.init, params)
        treedef = jax.tree_util.tree_structure(template)
        leaves = [jnp.asarray(l) for l in payload["__optax_leaves__"]]
        return jax.tree_util.tree_unflatten(treedef, leaves)
