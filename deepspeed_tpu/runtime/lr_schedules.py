"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Reference: deepspeed/runtime/lr_schedules.py:301,408,677,761. Pure-Python
step-based schedulers; they mutate `optimizer.param_groups[i]["lr"]` exactly
like the reference so user loops port unchanged. The engine reads the
current lr per step and feeds it into the jitted update as a traced scalar
(no recompilation per lr change).
"""

import math

from ..utils.logging import logger

# config/CLI key names (reference lr_schedules.py:15-53)
LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


def add_tuning_arguments(parser):
    """CLI args for LR schedules (reference lr_schedules.py:54)."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    return parser


def _get_optimizer(optimizer):
    if hasattr(optimizer, "param_groups"):
        return optimizer
    if hasattr(optimizer, "optimizer") and hasattr(optimizer.optimizer,
                                                   "param_groups"):
        return optimizer.optimizer
    raise TypeError(
        f"{type(optimizer).__name__} has no param_groups; not an optimizer")


def _format_param(optimizer, value, name):
    if isinstance(value, (list, tuple)):
        if len(value) != len(optimizer.param_groups):
            raise ValueError(
                f"expected {len(optimizer.param_groups)} values for {name}, "
                f"got {len(value)}")
        return list(value)
    return [value] * len(optimizer.param_groups)


class _LRSchedulerBase:
    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, \
            "need to call step() first"
        return self._last_lr

    def _update_optimizer(self, group_lrs):
        for param_group, lr in zip(self.optimizer.param_groups, group_lrs):
            param_group["lr"] = lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._update_optimizer(self.get_lr())
        self._last_lr = [g["lr"] for g in self.optimizer.param_groups]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_LRSchedulerBase):
    """LR range test: lr = min_lr * (1 + step_rate * interval) (reference :301)."""

    def __init__(self, optimizer, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        self.optimizer = _get_optimizer(optimizer)
        self.min_lr = _format_param(self.optimizer, lr_range_test_min_lr,
                                    "lr_range_test_min_lr")
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration
        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lr)

    def _interval(self):
        x = float(self.last_batch_iteration + 1) / self.step_size
        return math.floor(x) if self.staircase else x

    def get_lr(self):
        inc = 1 + self.step_rate * self._interval()
        return [lr * inc for lr in self.min_lr]


class OneCycle(_LRSchedulerBase):
    """1Cycle LR (+inverse momentum cycle) with post-cycle decay (reference :408)."""

    def __init__(self, optimizer, cycle_min_lr, cycle_max_lr, decay_lr_rate=0.0,
                 cycle_first_step_size=2000, cycle_second_step_size=None,
                 cycle_first_stair_count=0, cycle_second_stair_count=None,
                 decay_step_size=0, cycle_momentum=True, cycle_min_mom=0.8,
                 cycle_max_mom=0.9, decay_mom_rate=0.0,
                 last_batch_iteration=-1):
        self.optimizer = _get_optimizer(optimizer)
        first = float(cycle_first_step_size)
        second = float(cycle_second_step_size
                       if cycle_second_step_size is not None else first)
        self.total_size = first + second
        self.step_ratio = first / self.total_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = (cycle_first_stair_count
                                   if cycle_second_stair_count is None
                                   else cycle_second_stair_count)
        self.decay_step_size = decay_step_size

        self.min_lrs = _format_param(self.optimizer, cycle_min_lr, "cycle_min_lr")
        self.max_lrs = _format_param(self.optimizer, cycle_max_lr, "cycle_max_lr")
        self.decay_lr_rate = decay_lr_rate
        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lrs)

        self.cycle_momentum = cycle_momentum
        if cycle_momentum:
            if not all("betas" in g for g in self.optimizer.param_groups):
                logger.warning("cycle_momentum disabled: optimizer has no betas")
                self.cycle_momentum = False
            else:
                self.decay_mom_rate = decay_mom_rate
                n_groups = len(self.optimizer.param_groups)
                self.min_moms = [(cycle_min_mom, 0.99)] * n_groups
                self.max_moms = [(cycle_max_mom, 0.99)] * n_groups
                if last_batch_iteration == -1:
                    for mom, group in zip(self.min_moms,
                                          self.optimizer.param_groups):
                        group["betas"] = mom
        self.last_batch_iteration = last_batch_iteration

    def _scale_factor(self):
        batch_iteration = self.last_batch_iteration + 1
        cycle = math.floor(1 + batch_iteration / self.total_size)
        x = 1.0 + batch_iteration / self.total_size - cycle
        if x <= self.step_ratio:
            return x / self.step_ratio
        return (x - 1) / (self.step_ratio - 1)

    def _get_cycle_lr(self):
        scale = self._scale_factor()
        return [min_lr + (max_lr - min_lr) * scale
                for min_lr, max_lr in zip(self.min_lrs, self.max_lrs)]

    def _get_decay_lr(self, decay_batch_iteration):
        factor = 1 + self.decay_lr_rate * (decay_batch_iteration /
                                           self.decay_step_size)
        return [min_lr / factor for min_lr in self.min_lrs]

    def get_lr(self):
        if (self.last_batch_iteration + 1) < self.total_size or \
                not self.decay_step_size:
            return self._get_cycle_lr()
        return self._get_decay_lr(self.last_batch_iteration - self.total_size + 1)

    def _get_cycle_mom(self):
        scale = self._scale_factor()
        moms = []
        for base_betas, max_betas in zip(self.min_moms, self.max_moms):
            height = (max_betas[0] - base_betas[0]) * scale
            moms.append((max_betas[0] - height, base_betas[1]))
        return moms

    def _get_decay_mom(self, decay_batch_iteration):
        factor = 1 + self.decay_mom_rate * (decay_batch_iteration /
                                            self.decay_step_size)
        return [(beta0 * factor, beta1) for beta0, beta1 in self.max_moms]

    def get_mom(self):
        if not self.cycle_momentum:
            return None
        if (self.last_batch_iteration + 1) < self.total_size or \
                not self.decay_step_size:
            return self._get_cycle_mom()
        return self._get_decay_mom(self.last_batch_iteration -
                                   self.total_size + 1)

    def step(self, batch_iteration=None):
        if batch_iteration is None:
            batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = batch_iteration
        self._update_optimizer(self.get_lr())
        self._last_lr = [g["lr"] for g in self.optimizer.param_groups]
        if self.cycle_momentum:
            for param_group, mom in zip(self.optimizer.param_groups,
                                        self.get_mom()):
                param_group["betas"] = mom


class WarmupLR(_LRSchedulerBase):
    """Log-warmup from min_lr to max_lr over warmup_num_steps, then flat
    (reference :677)."""

    def __init__(self, optimizer, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, last_batch_iteration=-1):
        self.optimizer = _get_optimizer(optimizer)
        self.min_lrs = _format_param(self.optimizer, warmup_min_lr, "min_lr")
        self.max_lrs = _format_param(self.optimizer, warmup_max_lr, "max_lr")
        self.delta_lrs = [b - s for b, s in zip(self.max_lrs, self.min_lrs)]
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        self.last_batch_iteration = last_batch_iteration

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(
                self.last_batch_iteration + 1)
        return 1.0

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler "
                           "before it has started")
            return [0.0]
        gamma = self._get_gamma()
        return [min_lr + (delta * gamma)
                for min_lr, delta in zip(self.min_lrs, self.delta_lrs)]


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 over total_num_steps (reference :761)."""

    def __init__(self, optimizer, total_num_steps, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning(
                f"total_num_steps {total_num_steps} is less than "
                f"warmup_num_steps {warmup_num_steps}")

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(
                self.last_batch_iteration + 1)
        return max(0.0,
                   float(self.total_num_steps - self.last_batch_iteration) /
                   float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


SCHEDULERS = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_scheduler_class(name):
    if name not in SCHEDULERS:
        raise ValueError(
            f"unknown scheduler {name!r}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULERS[name]
