"""Config parsing helpers (reference: deepspeed/runtime/config_utils.py)."""

import json
from collections import Counter


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json object_pairs_hook that rejects duplicate keys (reference
    config_utils.py dict_raise_error_on_duplicate_keys)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = Counter([pair[0] for pair in ordered_pairs])
        keys = [key for key, value in counter.items() if value > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder(json.JSONEncoder):
    """Emit large/small floats in scientific notation for readable dumps
    (reference config_utils.py ScientificNotationEncoder)."""

    def iterencode(self, o, _one_shot=False):
        return super().iterencode(self._transform(o), _one_shot=_one_shot)

    def _transform(self, o):
        if isinstance(o, float) and (abs(o) >= 1e3 or (0 < abs(o) < 1e-3)):
            return _SciFloat(o)
        if isinstance(o, dict):
            return {k: self._transform(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [self._transform(v) for v in o]
        return o


class _SciFloat(float):
    def __repr__(self):
        return f"{float(self):e}"


class DeepSpeedConfigObject:
    """repr-able config holder (reference config_utils.py)."""

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4,
                          cls=ScientificNotationEncoder, default=repr)
