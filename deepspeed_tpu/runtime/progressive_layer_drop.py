"""Progressive Layer Drop curriculum
(reference: deepspeed/runtime/progressive_layer_drop.py:5).

theta(t) = (1 - theta_base) * exp(-gamma * t) + theta_base — the keep
probability handed to the model each step (engine injects it as a traced
scalar into the jitted step; the model applies it with a Bernoulli mask
inside lax-friendly code).
"""

import numpy as np

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step: int):
        self.current_theta = ((1.0 - self.theta) *
                              np.exp(-self.gamma * global_step) + self.theta)
