"""Batch-size ramp scheduler (EleutherAI addition;
reference: deepspeed/runtime/bs_schedules.py:5).

Ramps the batch size in `num_intervals` linear stairs from
ceil(final * min_batch_size_multiplier) to final over warmup_num_steps.
Note for TPU: changing batch size retriggers XLA compilation per stair —
num_intervals distinct shapes are compiled, which is bounded and cached.
"""

import math

import numpy as np


class BatchSizeScheduler:
    def __init__(self, final_batch_size, min_batch_size_multiplier: float = 0.01,
                 warmup_num_steps: int = 1000, num_intervals: int = 4,
                 last_batch_iteration: int = -1, deepspeed=None):
        self.warmup_num_steps = warmup_num_steps
        self.last_batch_iteration = last_batch_iteration
        self.final_batch_size = final_batch_size
        self.num_intervals = num_intervals
        self.min_batch_size_multiplier = min_batch_size_multiplier
        self.schedule = self._build_schedule()
        self.current_batch_size = None
        self.deepspeed = deepspeed

    def _build_schedule(self):
        start = math.ceil(self.min_batch_size_multiplier * self.final_batch_size)
        batch_sizes = np.linspace(start, self.final_batch_size,
                                  num=self.num_intervals, dtype=int)
        steps = np.linspace(0, self.warmup_num_steps, num=self.num_intervals,
                            dtype=int)
        schedule = {}
        prev = None
        for step, bs in zip(steps, batch_sizes):
            if int(bs) != prev:
                schedule[int(step)] = int(bs)
            prev = int(bs)
        return schedule

    def get_current_batch_size(self):
        keys = sorted(self.schedule.keys(), reverse=True)
        for k in keys:
            if self.last_batch_iteration >= k:
                return self.schedule[k]
        return self.schedule[keys[-1]]

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self.current_batch_size = self.get_current_batch_size()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
