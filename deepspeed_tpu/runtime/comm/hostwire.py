"""Host-TCP compressed collectives — the second comm substrate.

Reference: deepspeed/runtime/comm/mpi.py (MpiBackend) — the SAME
error-compensated 1-bit algorithm as the NCCL backend, carried by a
second, device-fabric-independent transport. The TPU analogue: XLA
collectives over ICI/DCN are the primary substrate
(runtime/comm/compressed.py); this module carries the identical
algorithm over the jax.distributed coordination service's key-value
store — plain TCP between processes, nothing on the device fabric.

Two things only a host wire can do here:

* a TRUE 1-bit wire format: np.packbits ships 1 bit/element + one fp32
  scale. XLA has no packed-int1 type, so the in-jit sign path travels at
  full width (measured negative result, BENCH.md "1-bit Adam measured");
  the reference needed CuPy bit-packing for exactly this
  (deepspeed/runtime/compression/cupy.py) — packbits is its host-side
  twin.
* transport independence: gradients can be reduced even when the device
  fabric is owned by a different collective (e.g. during pipeline
  channel transfers), mirroring how the reference's MPI backend rides
  beside NCCL.

Intended for SMALL, compression-friendly payloads (1-bit/int8 optimizer
wires). The coordinator relays bytes (upload ~1 full payload + 1 owned
chunk per step per rank), so this is a fallback/secondary fabric, not a
bandwidth contender — same positioning as the reference's MPI path.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..resilience import (fault_point, is_transient_not_timeout,
                          retry_transient)

DEFAULT_TIMEOUT_MS = 120_000

# -- incarnation scoping (elastic shrink-to-survivors restarts) -------------
# The coordination-service KV is write-once per key and a dead rank's
# keys are never cleaned (nobody can know what it posted mid-flight).
# An elastic restart that reuses the SAME coordination service (the
# supervisor relaunches into the same job) would therefore collide with
# — or worse, silently CONSUME — the dead generation's keys: commit-
# barrier done/committed keys (a re-save of the same tag restarts its
# per-process seq counter at 0 in the fresh process), rendezvous
# addresses, gather payloads.  The supervisor exports DSTPU_INCARNATION
# (bumped on every relaunch, elasticity/supervisor.py) and EVERY key on
# this wire is namespaced by it, extending PR 8's generation-scoped
# gathers to the whole KV surface.  Incarnation 0 (no supervisor, or
# the first launch) keeps today's unprefixed keys.

INCARNATION_ENV = "DSTPU_INCARNATION"
_INCARNATION: Optional[int] = None


def incarnation() -> int:
    """The cached incarnation id this process runs as (env-derived;
    engines validate + log it at init via elasticity.elastic_env)."""
    global _INCARNATION
    if _INCARNATION is None:
        raw = os.environ.get(INCARNATION_ENV, "0").strip() or "0"
        try:
            _INCARNATION = max(0, int(raw))
        except ValueError:
            raise ValueError(
                f"hostwire: {INCARNATION_ENV}={raw!r} is not an integer "
                f"— the supervisor exports a numeric relaunch counter; "
                f"a garbled value would silently de-scope every KV key")
    return _INCARNATION


def set_incarnation(n: Optional[int]) -> None:
    """Pin (or with None re-read from env) the incarnation id — engine
    init after validating the elastic env, and tests."""
    global _INCARNATION
    _INCARNATION = None if n is None else max(0, int(n))


def scoped_key(key: str) -> str:
    """Namespace a KV key by the current incarnation.  Applied at every
    client call boundary in this module, so a survivor-generation run
    can never consume (or collide with) a dead generation's write-once
    keys."""
    inc = incarnation()
    return key if inc == 0 else f"dstpu-inc{inc}/{key}"

# -- scaling envelope (documented contract) ---------------------------------
# The KV store relays every value THROUGH the coordinator as one gRPC
# message, so a single huge value both hits the transport's message cap
# (4 MiB default gRPC, raised but not unbounded in the coordination
# service) and serializes the relay.  Payloads above CHUNK_BYTES are
# split into part keys and reassembled on the readers — transparent to
# callers.  Payloads above MAX_PAYLOAD_BYTES are refused loudly: at that
# size the host wire is the wrong substrate (coordinator upload is
# ~W × payload per step), use the XLA-collective backend or shrink the
# wire format (sign instead of int8).
CHUNK_BYTES = 2 << 20          # 2 MiB: safely under gRPC message caps
MAX_PAYLOAD_BYTES = 128 << 20  # 128 MiB/rank/step: the envelope edge


# Client API surface the wire depends on (ADVICE round-5 #4): these are
# asserted at construction so a jax upgrade that renames/removes one
# fails with a versioned message instead of an AttributeError deep
# inside a barrier mid-step.
_REQUIRED_CLIENT_API = ("key_value_set", "blocking_key_value_get",
                        "key_value_delete", "wait_at_barrier")


def _distributed_state():
    """The jax.distributed client state, via the public accessor when
    the installed jax exposes one, else the long-stable private module.
    Returns None when neither shape is recognized (API drift)."""
    import jax

    # newer jax releases export the state object publicly
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        try:
            from jax._src import distributed

            state = distributed.global_state
        except Exception:
            return None
    if not all(hasattr(state, a) for a in
               ("client", "process_id", "num_processes")):
        return None
    return state


def _client():
    import jax

    state = _distributed_state()
    if state is None:
        raise RuntimeError(
            f"hostwire: jax {jax.__version__} exposes neither "
            "jax.distributed.global_state nor jax._src.distributed."
            "global_state with the expected (client, process_id, "
            "num_processes) surface — the coordination-service KV "
            "transport cannot attach.  Pin a known-good jax or port "
            "runtime/comm/hostwire.py to the new client API.")
    if state.client is None:
        return None, 0, 1
    return state.client, state.process_id, state.num_processes


def _assert_client_api(client) -> None:
    """Fail fast (and versioned) when the KV client lacks a method the
    wire will call later."""
    if client is None:
        return
    import jax

    missing = [a for a in _REQUIRED_CLIENT_API if not hasattr(client, a)]
    if missing:
        raise RuntimeError(
            f"hostwire: the jax {jax.__version__} distributed client is "
            f"missing required method(s) {missing} (has: "
            f"{[a for a in _REQUIRED_CLIENT_API if hasattr(client, a)]}). "
            "The KV wire cannot run on this jax build — pin a version "
            "whose client exposes the full key-value + barrier surface, "
            "or port runtime/comm/hostwire.py.")


def _kv_set(client, key: str, payload: bytes) -> None:
    """Store bytes under `key` via the STRING KV entry points.

    The *_bytes variants segfault in some jaxlib builds (0.4.36
    observed, flat keys included), while key_value_set /
    blocking_key_value_get are stable everywhere — so the wire rides the
    string API with base64 framing.  The 4/3 expansion is priced into
    CHUNK_BYTES: a 2 MiB raw chunk is ~2.7 MiB encoded, still under the
    4 MiB gRPC message cap.

    Transient coordinator faults (UNAVAILABLE, connection reset,
    injected) retry with bounded backoff (runtime/resilience.py).  The
    wire's keys are write-once per (tag, step, gen), so a retry racing
    its own landed first attempt surfaces as ALREADY_EXISTS from the
    real coordination service — that means the value IS durably there,
    i.e. success."""
    import base64

    encoded = base64.b64encode(payload).decode("ascii")
    _kv_set_write_once(client, key, encoded, "hostwire.kv_set")


def _kv_set_write_once(client, key: str, value: str, site: str) -> None:
    """Transient-retried set of a WRITE-ONCE key.  The subtle invariant
    lives here exactly once: ALREADY_EXISTS counts as success ONLY on a
    retry (our own first attempt landed before its ack was lost); on
    the first attempt it means a FOREIGN writer holds the key
    (mis-ranked launch, seq bug) — proceeding would silently serve
    peers someone else's bytes, so that stays a loud failure."""
    attempt = [0]

    skey = scoped_key(key)

    def op():
        attempt[0] += 1
        fault_point(site)
        try:
            client.key_value_set(skey, value)
        except Exception as e:
            if attempt[0] > 1 and \
                    "ALREADY_EXISTS" in str(e).upper().replace(" ", "_"):
                return
            raise

    retry_transient(op, site=f"{site} {key}")


def _kv_put_bytes(client, key: str, payload: bytes,
                  chunk_bytes: int = CHUNK_BYTES) -> None:
    """Store an arbitrary-size byte payload under `key`, chunked into
    part keys so a single value never exceeds the KV relay's message
    envelope (see the scaling-envelope constants above).  The layout
    (`key/n` part count + `key/{i}` parts) matches HostWire's allgather
    framing; `_kv_get_bytes` reassembles.  Write-once semantics per
    part, like every other key on this wire — used by the overlap
    exchange's KV fallback transport (runtime/comm/overlap.py)."""
    cb = int(chunk_bytes)
    nparts = max(1, -(-len(payload) // cb))
    _kv_set(client, f"{key}/n", str(nparts).encode())
    for i in range(nparts):
        _kv_set(client, f"{key}/{i}", payload[i * cb:(i + 1) * cb])


def _kv_get_bytes(client, key: str, timeout_ms: int) -> bytes:
    """Reassemble a `_kv_put_bytes` payload.  One deadline across the
    part gets (the _kv_get discipline): a dead writer surfaces in
    ~timeout_ms regardless of payload size."""
    deadline = time.monotonic() + timeout_ms / 1000.0

    def remaining_ms():
        return max(1, int((deadline - time.monotonic()) * 1000))

    nparts = int(_kv_get(client, f"{key}/n", remaining_ms()))
    return b"".join(_kv_get(client, f"{key}/{i}", remaining_ms())
                    for i in range(nparts))


def _kv_get(client, key: str, timeout_ms: int) -> bytes:
    import base64

    # ONE deadline across retries: a DEADLINE_EXCEEDED first attempt
    # leaves ~nothing for the retries, so retrying a timeout cannot
    # multiply the caller's budget (genuine dead peers still surface in
    # ~timeout_ms); transient transport blips mid-budget retry with the
    # time that is left
    deadline = time.monotonic() + timeout_ms / 1000.0

    skey = scoped_key(key)

    def op():
        fault_point("hostwire.kv_get")
        left = max(1, int((deadline - time.monotonic()) * 1000))
        return base64.b64decode(
            client.blocking_key_value_get(skey, left))

    return retry_transient(op, site=f"hostwire.kv_get {key}")


class KVSignals:
    """Tiny point-to-point signal layer on the coordination-service KV —
    NOT a collective.  Used for per-rank done-keys in the checkpoint
    commit barrier (runtime/checkpointing.CommitBarrier): each process
    posts small string values under explicit keys and any process can
    block on a key appearing.  Values are plain strings (no base64
    framing — signals are tiny and never binary), keys are caller-scoped.

    `_endpoint=(client, rank, world)` drives the signals over a fake
    in-memory KV for tests, like HostWire."""

    def __init__(self, _endpoint=None):
        self.client, self.rank, self.world = (
            _endpoint if _endpoint is not None else _client())
        _assert_client_api(self.client)

    def post(self, key: str, value: str = "1") -> None:
        if self.client is None:
            return
        # write-once semantics shared with the data wire: a retry's
        # ALREADY_EXISTS resolves to success, a first attempt's stays
        # loud (_kv_set_write_once)
        _kv_set_write_once(self.client, key, str(value), "kv.post")

    def wait(self, key: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        if self.client is None:
            raise RuntimeError(
                "KVSignals.wait: no coordination-service client attached "
                "(single-process run?) — nothing ever posts keys here")

        skey = scoped_key(key)

        def op():
            fault_point("kv.wait")
            return self.client.blocking_key_value_get(skey, int(timeout_ms))

        # the blocking timeout IS the dead-peer detector here (commit
        # barrier): transient transport blips retry, deadlines do not —
        # retrying them would multiply commit_timeout_ms and delay the
        # CheckpointIntegrityError the caller exists to raise
        return retry_transient(op, site=f"kv.wait {key}",
                               classify=is_transient_not_timeout)

    def delete(self, key: str) -> None:
        if self.client is None:
            return
        self.client.key_value_delete(scoped_key(key))


class HostWire:
    """Allgather of byte payloads over the coordination-service KV store.

    Every call site must be entered by ALL processes (collective
    contract, like any allreduce). Keys are step-scoped and deleted
    after a barrier, so coordinator memory stays bounded."""

    def __init__(self, tag: str = "dstpu-hostwire",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS,
                 chunk_bytes: int = CHUNK_BYTES,
                 max_payload_bytes: int = MAX_PAYLOAD_BYTES,
                 _endpoint=None):
        # _endpoint=(client, rank, world) lets tests drive the wire over
        # a fake in-memory KV store without jax.distributed processes
        self.client, self.rank, self.world = (
            _endpoint if _endpoint is not None else _client())
        # fail at construction, not deep in a barrier, when the client
        # API surface is incomplete (jax version drift; fakes included)
        _assert_client_api(self.client)
        self.tag = tag
        self.timeout_ms = timeout_ms
        self.chunk_bytes = int(chunk_bytes)
        self.max_payload_bytes = int(max_payload_bytes)
        self._step = 0
        # generation/attempt id scoping the keys of each gather ATTEMPT:
        # bumped whenever a gather fails mid-flight, so a retried gather
        # (or one racing keys stranded by a rank that died between the
        # read and clean barriers — those are never deleted) posts and
        # reads under FRESH keys instead of consuming a dead attempt's
        # payload or colliding with its write-once keys.  Failures are
        # symmetric across ranks (a dead peer times everyone out; an
        # injected fault is scheduled on every rank or surfaces as the
        # others' barrier timeout), so collectively-retried gathers
        # re-agree on the generation.
        self._gen = 0

    def allgather_bytes(self, payload: bytes) -> list:
        """payload from every process, in rank order.

        Payloads above `chunk_bytes` ride multiple part keys (the KV
        relay's message envelope — see module constants); above
        `max_payload_bytes` the call refuses with a clear error instead
        of wedging the coordinator."""
        from ...monitor.counters import COUNTERS

        COUNTERS.add("hostwire.allgather", len(payload))
        fault_point("hostwire.allgather")
        if len(payload) > self.max_payload_bytes:
            raise ValueError(
                f"hostwire payload of {len(payload)} bytes exceeds the "
                f"host-wire envelope ({self.max_payload_bytes} bytes/rank/"
                f"step): the coordination-service KV relay is for SMALL "
                f"compressed payloads — use the XLA-collective backend "
                f"(runtime/comm/compressed.py) or a denser wire format "
                f"for tensors this large")
        if self.client is None or self.world == 1:
            self._step += 1
            return [payload]
        try:
            return self._allgather(payload)
        except BaseException:
            # the attempt died mid-protocol (peer timeout, injected
            # fault, operator interrupt): its keys may be stranded —
            # nobody can safely clean them (a dead rank couldn't have
            # either) — so the NEXT attempt moves to a fresh generation
            self._gen += 1
            raise

    def _allgather(self, payload: bytes) -> list:
        key = f"{self.tag}/{self._step}g{self._gen}"
        cb = self.chunk_bytes
        nparts = max(1, -(-len(payload) // cb))
        _kv_set(self.client, f"{key}/{self.rank}/n",
                str(nparts).encode())
        for i in range(nparts):
            _kv_set(self.client, f"{key}/{self.rank}/{i}",
                    payload[i * cb:(i + 1) * cb])
        # chaos hook for the nastiest window: this rank's payload is up
        # but it dies before the read/clean barriers, stranding keys
        fault_point("hostwire.allgather.posted")
        # ONE deadline for the whole gather: timeout_ms bounds the call,
        # not each of the W x nparts gets (a dead peer must surface in
        # ~timeout_ms regardless of payload size)
        deadline = time.monotonic() + self.timeout_ms / 1000.0

        def remaining_ms():
            return max(1, int((deadline - time.monotonic()) * 1000))

        out = []
        counts = {self.rank: nparts}
        for r in range(self.world):
            if r == self.rank:
                out.append(payload)
                continue
            counts[r] = int(_kv_get(self.client, f"{key}/{r}/n",
                                    remaining_ms()))
            out.append(b"".join(
                _kv_get(self.client, f"{key}/{r}/{i}", remaining_ms())
                for i in range(counts[r])))
        # nobody may delete until everyone has read; nobody may proceed
        # to the NEXT step's set() until this step's keys are gone
        # (barrier ids and deletes carry the same incarnation scope the
        # sets landed under)
        self.client.wait_at_barrier(scoped_key(f"{key}/read"),
                                    self.timeout_ms)
        if self.rank == 0:
            for r in range(self.world):
                self.client.key_value_delete(scoped_key(f"{key}/{r}/n"))
                for i in range(counts[r]):
                    self.client.key_value_delete(
                        scoped_key(f"{key}/{r}/{i}"))
        self.client.wait_at_barrier(scoped_key(f"{key}/clean"),
                                    self.timeout_ms)
        self._step += 1
        return out


def _pack_sign(c: np.ndarray) -> Tuple[bytes, float]:
    """sign-compress: 1 bit/element (bit=1 means +scale) + L1-mean scale."""
    scale = float(np.mean(np.abs(c)))
    return np.packbits(c >= 0).tobytes(), scale


def _unpack_sign(payload: bytes, scale: float, n: int) -> np.ndarray:
    bits = np.unpackbits(np.frombuffer(payload, np.uint8), count=n)
    return np.where(bits.astype(bool), scale, -scale).astype(np.float32)


class HostWireBackend:
    """Out-of-jit compressed-allreduce over the host wire — the same
    surface as CompressedBackend (runtime/comm/compressed.py) and the
    same two-stage error-compensated algorithm as the reference backends
    (deepspeed/runtime/comm/mpi.py:34-290):

      worker: c = x + worker_error; ship sign(c)·scale (packed 1-bit)
      server: rank r owns chunk r of the worker-mean; adds its server
              error, recompresses, ships; everyone reassembles

    wire="sign": 1 bit/element + 4-byte scale per stage (the true 1-bit
    wire). wire="int8": one byte/element + per-group scales (higher
    fidelity, 8x the bytes)."""

    INT8_GROUP = 2048

    def __init__(self, tag: str = "dstpu-onebit", wire: str = "sign",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS,
                 chunk_bytes: int = CHUNK_BYTES,
                 max_payload_bytes: int = MAX_PAYLOAD_BYTES,
                 _endpoint=None):
        if wire not in ("sign", "int8"):
            raise ValueError(f"wire must be 'sign' or 'int8', got {wire!r}")
        self.wire = HostWire(tag=tag, timeout_ms=timeout_ms,
                             chunk_bytes=chunk_bytes,
                             max_payload_bytes=max_payload_bytes,
                             _endpoint=_endpoint)
        self.mode = wire
        self._errors: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    @property
    def rank(self):
        return self.wire.rank

    @property
    def world(self):
        return self.wire.world

    # -- int8 helpers (numpy twins of compressed.py's _quant_grouped) ----
    def _quant(self, c: np.ndarray) -> Tuple[bytes, np.ndarray]:
        G = max(1, min(self.INT8_GROUP, c.size))
        pad = (-c.size) % G
        g = np.pad(c, (0, pad)).reshape(-1, G)
        scale = np.max(np.abs(g), axis=-1) / 127.0 + 1e-12
        q = np.clip(np.round(g / scale[:, None]), -127, 127).astype(np.int8)
        return q.tobytes(), scale.astype(np.float32)

    def _dequant(self, payload: bytes, scale: np.ndarray,
                 n: int) -> np.ndarray:
        q = np.frombuffer(payload, np.int8)
        g = q.astype(np.float32).reshape(len(scale), -1)
        return (g * scale[:, None]).ravel()[:n]

    def _compress(self, c: np.ndarray):
        if self.mode == "sign":
            payload, scale = _pack_sign(c)
            return payload, np.float32([scale])
        return self._quant(c)

    def _decompress(self, payload: bytes, scale: np.ndarray,
                    n: int) -> np.ndarray:
        if self.mode == "sign":
            return _unpack_sign(payload, float(scale[0]), n)
        return self._dequant(payload, scale, n)

    def compressed_allreduce(self, tensor, name: str = "default"):
        """Error-compensated compressed MEAN of `tensor` over all
        processes. tensor: host array (np or jax); returns np.float32 of
        the same shape. Must be called collectively."""
        x = np.asarray(tensor, np.float32)
        n = x.size
        W = self.world
        if name not in self._errors:
            self._errors[name] = (np.zeros(n, np.float32),
                                  np.zeros(n, np.float32))
        we, se = self._errors[name]

        # worker stage
        c = x.ravel() + we
        payload, scale = self._compress(c)
        deq_own = self._decompress(payload, scale, n)
        we_new = c - deq_own

        parts = self.wire.allgather_bytes(payload + scale.tobytes())
        sbytes = scale.nbytes
        mean = deq_own.copy()  # own payload already decompressed above
        for r, p in enumerate(parts):
            if r == self.rank:
                continue
            sc = np.frombuffer(p[len(p) - sbytes:], np.float32)
            mean += self._decompress(p[:len(p) - sbytes], sc, n)
        mean /= W

        # server stage: rank r owns chunk r (reference per-rank server
        # error slices, comm/mpi.py server_error)
        chunk = -(-n // W)
        lo, hi = self.rank * chunk, min(n, (self.rank + 1) * chunk)
        out = np.empty(n, np.float32)
        se_new = se.copy()
        if hi > lo:
            s = mean[lo:hi] + se[lo:hi]
            p2, sc2 = self._compress(s)
            se_new[lo:hi] = s - self._decompress(p2, sc2, hi - lo)
            # explicit payload-length prefix: the receiver must not
            # re-derive _quant's group/padding split (ragged last chunk)
            own = len(p2).to_bytes(4, "little") + p2 + sc2.tobytes()
        else:  # more ranks than chunks
            own = b""
        parts2 = self.wire.allgather_bytes(own)
        for r, p in enumerate(parts2):
            rlo, rhi = r * chunk, min(n, (r + 1) * chunk)
            if rhi <= rlo or not p:
                continue
            plen = int.from_bytes(p[:4], "little")
            sc = np.frombuffer(p[4 + plen:], np.float32)
            out[rlo:rhi] = self._decompress(p[4:4 + plen], sc, rhi - rlo)
        self._errors[name] = (we_new, se_new)
        return out.reshape(x.shape)
