"""Host-threaded wire exchange: comm/compute overlap for the bucketed
gradient wire and the qwZ parameter gather — now self-healing.

Why a HOST transport and not an XLA restructure: on the XLA:CPU runtime
this repo benches on, collective thunks execute inline in the per-device
thunk sequence — probed exhaustively while building this module: a
collective issued before / interleaved with / data-independent of the
remaining compute runs in exactly the same wall-clock as one issued
after it (fused == barrier-serialized, to the millisecond), and the
gloo wire's time is ~78% CPU-busy (process_time/wall), so even
thread-level concurrency cannot hide it on a saturated box.  What CAN
overlap is a transport whose waits are real OS blocking: raw sockets
move the same payload ~10x cheaper than the in-program collective and
spend most of that in `recv` — idle time the device pipeline runs
straight through.  On TPU fabrics the same schedule-driven structure
lets XLA's latency-hiding scheduler do the overlap in-program; on this
fabric the host exchange IS the overlap mechanism, and the bench
measures the exposure honestly either way (BENCH.md overlap round).

The pieces:

* `ExchangeTicket` — one in-flight exchange: `wait()` returns the
  rank-ordered `[world, nbytes]` payload matrix and records how long the
  caller was blocked (the EXPOSED wire time the monitor's
  `grad_wire.exposed_ms` counter reports).
* `LocalExchange` — single-process transport: every rank is addressable,
  so the "exchange" is a background-thread materialization of the local
  shards.  The threaded driver machinery (submit/wait ordering, ticket
  lifecycle, teardown) is exactly the multi-process one, so tier-1
  covers it without sockets.
* `SocketExchange` — N-process transport: a full mesh of persistent TCP
  connections (rendezvoused through the coordination-service KV the
  hostwire already rides), one receiver thread per peer demuxing
  sequence-tagged frames, one sender worker serializing submissions in
  order.  Frames are self-describing (per-rank payload table), so the
  receiver needs no topology assumptions.

Self-healing (the fail-fast wire died the moment a peer hiccuped —
erasing the overlap win at fabric scales where link resets are
routine).  Three layers, each bounded and LOUD:

1. **Reconnect + resend.**  Data frames are sequence-tagged and CRC'd;
   the sender retains every frame until each peer ACKs it, and the
   sender worker emits keepalive frames when idle so a dead connection
   surfaces in seconds instead of at the next (possibly far away)
   submit.  A dropped/corrupted connection is torn down and re-dialed
   with bounded exponential backoff (the `retry_transient()` taxonomy's
   RetryPolicy); the rendezvous address keys are GENERATION-scoped
   (`.../g{n}/addr{pid}`) because the coordination KV is write-once — a
   rebound listener publishes its new endpoint under the next
   generation instead of colliding with its old key.  After the
   handshake each side replays exactly the frames the peer never
   acknowledged (`exchange.reconnects` / `exchange.resends` counters).
2. **KV fallback transport.**  When the reconnect budget is exhausted
   (or a peer broadcasts a DEMOTE frame), the exchange stops trusting
   its sockets and serves every in-flight and future payload through
   the coordination-service KV (chunked write-once keys) — training
   stays CORRECT (bitwise: the same bytes reach the same combine
   programs) at degraded speed while the ranks agree on a demotion
   point.
3. **Coordinated demotion.**  `agree_demotion_step()` is the KVSignals-
   style barrier the engine runs at its next step boundary: every rank
   posts the boundary it reached, everyone reads all posts, and the MAX
   is the agreed demotion step — ranks behind it keep training over
   the KV transport until they get there, then every rank tears the
   exchange down and rebuilds its step programs through StepBuilder on
   the serial in-program wire (`exchange.demotions`).

Chaos sites (`runtime/resilience.py` FaultPlan): `exchange.connect`
(dial attempts), `exchange.send` (per peer per data frame),
`exchange.recv` (per received frame), and the payload filter
`exchange.payload` (corrupt rules truncate the received bytes; the CRC
turns that into a connection fault the resend path heals).

Exchanges are identified by a monotonically increasing sequence number.
Every process submits the same exchanges in the same order (the engine
step flow is deterministic across ranks), so a frame's sequence number
alone pairs it with its ticket.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ...monitor.counters import COUNTERS
from ..resilience import (RetryPolicy, TransientFault, fault_filter,
                          fault_point, is_transient_not_timeout,
                          retry_transient)
from ...utils.logging import logger

# frame: [ftype u8][seq u64][n_entries u32] then, for DATA frames, per
# entry [nbytes u64][rank u32][crc32 u32] and the concatenated payloads
# in entry order.  ACK frames carry the acked seq and no entries;
# KEEPALIVE/DEMOTE frames carry neither.
_HDR = struct.Struct("<BQI")
_ENT = struct.Struct("<QII")  # (nbytes, rank, crc32)
_HELLO = struct.Struct("<II")  # (pid, flags)

_FT_DATA = 0
_FT_ACK = 1
_FT_KEEPALIVE = 2
_FT_DEMOTE = 3

_HELLO_RECONNECT = 1

_CONNECT_TIMEOUT_S = 60.0
_ACCEPT_TIMEOUT_S = 60.0
# close() join budget per thread; stragglers are LOGGED by name, never
# silently discarded (a leaked receiver pins its socket and its peer)
_CLOSE_JOIN_S = 5.0

DEFAULT_KEEPALIVE_S = 5.0
DEFAULT_RECONNECT_ATTEMPTS = 8
DEFAULT_RECONNECT_WINDOW_S = 60.0


def _now() -> float:
    return time.perf_counter()


class ExchangeBroken(ConnectionError):
    """The exchange exhausted its reconnect budget AND has no KV
    fallback to serve payloads through — in-flight waits cannot
    complete.  The engine surfaces this as a fatal transport failure
    (supervisor-restart territory)."""


class ExchangeTicket:
    """One in-flight exchange.  `wait()` blocks until every expected
    rank's payload has landed and returns the `[world, nbytes]` uint8
    matrix (rank-major).  Timing:

    * `done_at`   when the last payload landed (transport-side stamp)
    * `wait_us`   how long wait() was actually blocked — the caller's
                  EXPOSED wire time (0 when the exchange finished
                  behind compute)
    """

    def __init__(self, seq: int, world: int):
        self.seq = seq
        self.world = world
        self._cond = threading.Condition()
        self._blocks: Dict[int, np.ndarray] = {}
        self._error: Optional[BaseException] = None
        self.created_at = _now()
        self.done_at: Optional[float] = None
        self.wait_us = 0

    # -- transport side -----------------------------------------------

    def post(self, rank: int, block: np.ndarray) -> None:
        with self._cond:
            self._blocks[int(rank)] = block
            if len(self._blocks) >= self.world:
                self.done_at = _now()
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    def missing_ranks(self) -> List[int]:
        with self._cond:
            return [r for r in range(self.world) if r not in self._blocks]

    # -- consumer side ------------------------------------------------

    @property
    def ready(self) -> bool:
        with self._cond:
            return self._error is not None or \
                len(self._blocks) >= self.world

    def wait(self, timeout_s: float = 300.0) -> np.ndarray:
        t0 = _now()
        with self._cond:
            deadline = t0 + timeout_s
            while self._error is None and len(self._blocks) < self.world:
                remaining = deadline - _now()
                if remaining <= 0:
                    raise TimeoutError(
                        f"overlap exchange seq={self.seq}: only "
                        f"{sorted(self._blocks)} of {self.world} rank "
                        f"payloads arrived within {timeout_s:.0f}s")
                self._cond.wait(remaining)
            self.wait_us += int((_now() - t0) * 1e6)
            if self._error is not None:
                raise RuntimeError(
                    f"overlap exchange seq={self.seq} failed"
                ) from self._error
            blocks = [self._blocks[r] for r in range(self.world)]
        return np.stack(blocks)


class _ExchangeBase:
    """Shared submit-worker machinery: one persistent worker thread
    materializes each submission's device shards (np.asarray blocks the
    WORKER on the producing program, never the driver) and hands the
    blocks to the transport in submission order.  When the task queue
    is idle the worker emits a liveness tick (`_idle_tick`) every
    `keepalive_s` — the socket transport turns that into keepalive
    frames so a dead connection surfaces between submits."""

    def __init__(self, world: int, keepalive_s: float = DEFAULT_KEEPALIVE_S):
        self.world = int(world)
        self._seq = 0
        self._tasks: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()
        self._keepalive_s = float(keepalive_s)
        # self-healing surface the engine polls at step boundaries
        self.demote_requested = False
        self.broken: Optional[BaseException] = None

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="dstpu-overlap-send",
                daemon=True)
            self._worker.start()

    def _worker_loop(self):
        while True:
            try:
                task = self._tasks.get(timeout=self._keepalive_s)
            except queue.Empty:
                try:
                    self._idle_tick()
                except Exception as e:  # keepalives must never kill send
                    logger.warning(f"overlap exchange keepalive: {e}")
                continue
            if task is None:
                return
            ticket, local_blocks = task
            try:
                blocks = [(rank, np.asarray(get()).view(np.uint8))
                          for rank, get in local_blocks]
            except BaseException as e:  # surfaced at ticket.wait()
                ticket.fail(e)
                continue
            # local blocks land in the ticket BEFORE the network send:
            # they are this process's ground truth, and keeping them
            # valid regardless of transport health is what lets the
            # demotion path settle an interrupted exchange losslessly
            for rank, block in blocks:
                ticket.post(rank, block)
            try:
                self._send(ticket, blocks)
            except BaseException as e:
                self._on_send_failure(ticket, e)

    def _idle_tick(self) -> None:
        """Idle-queue liveness hook (socket transport: keepalives)."""

    def _send(self, ticket: ExchangeTicket,
              blocks: List[Tuple[int, np.ndarray]]) -> None:
        raise NotImplementedError

    def _on_send_failure(self, ticket: ExchangeTicket,
                         exc: BaseException) -> None:
        ticket.fail(exc)

    def submit(self, local_blocks: List[Tuple[int, Callable[[], np.ndarray]]]
               ) -> ExchangeTicket:
        """Start one exchange.  `local_blocks` is [(global_rank, getter)]
        for every rank this process owns; `getter()` returns the rank's
        payload (a device array or shard — materialized on the worker
        thread, so calling submit never blocks on the producing
        program).  Returns the ticket to `wait()` on."""
        if self._closed:
            raise RuntimeError("exchange is closed")
        with self._lock:
            seq = self._seq
            self._seq += 1
            ticket = self._register(seq)
        self._ensure_worker()
        self._tasks.put((ticket, local_blocks))
        return ticket

    def _register(self, seq: int) -> ExchangeTicket:
        return ExchangeTicket(seq, self.world)

    def agree_demotion_step(self, step: int, timeout_ms: int = 120_000
                            ) -> int:
        """Coordinated-demotion barrier: every rank posts the step
        boundary it reached and the MAX across ranks is the agreed
        demotion point.  Single-process: the caller IS every rank."""
        return int(step)

    def threads(self) -> List[threading.Thread]:
        """Live transport threads — registered with the StepWatchdog so
        a hung exchange shows up named in the stall snapshot."""
        return [t for t in (self._worker,) if t is not None and t.is_alive()]

    def _log_leaked(self, threads: List[threading.Thread]) -> None:
        leaked = [t.name for t in threads if t is not None and t.is_alive()]
        if leaked:
            logger.warning(
                f"overlap exchange close: {len(leaked)} thread(s) still "
                f"alive after {_CLOSE_JOIN_S:.0f}s join: {leaked} — a "
                "receiver/sender is wedged (likely blocked in a socket "
                "or device materialization); its resources leak until "
                "process exit")

    def close(self):
        if self._closed:
            return
        self._closed = True
        worker = self._worker
        if worker is not None and worker.is_alive():
            self._tasks.put(None)
            worker.join(timeout=_CLOSE_JOIN_S)
        self._log_leaked([worker])
        self._worker = None


class LocalExchange(_ExchangeBase):
    """Single-process transport: every rank's payload is already
    addressable — the worker thread materializes them and the ticket
    completes.  No sockets, same driver surface (including the chaos
    `exchange.send` site and the demotion flags, so the coordinated-
    demotion engine path is tier-1-testable without processes)."""

    def _send(self, ticket, blocks):
        missing = self.world - len(blocks)
        if missing:
            raise RuntimeError(
                f"LocalExchange: {len(blocks)} local payloads for a "
                f"world of {self.world} — a multi-process mesh needs "
                "the socket transport")
        # transient faults here model a flaky transport hop: absorbed by
        # the bounded-backoff retry exactly like the hostwire KV sites
        retry_transient(lambda: fault_point("exchange.send"),
                        site="overlap exchange send")

    def _on_send_failure(self, ticket, exc):
        if ticket.ready:
            # every rank's payload is already local and posted: nothing
            # was lost — flag coordinated demotion instead of dying
            logger.warning(
                "overlap exchange: send-side fault with all payloads "
                f"local ({type(exc).__name__}: {exc}); requesting "
                "coordinated demotion to the serial wire")
            self.demote_requested = True
            self.broken = exc
        else:
            ticket.fail(exc)


class _PeerConn:
    """One live connection to a peer process."""

    __slots__ = ("sock", "lock", "thread", "gen")

    def __init__(self, sock: socket.socket, gen: int):
        self.sock = sock
        self.lock = threading.Lock()
        self.thread: Optional[threading.Thread] = None
        self.gen = gen


class SocketExchange(_ExchangeBase):
    """N-process transport over a full mesh of persistent TCP
    connections.  Rendezvous rides the coordination-service KV (each
    process publishes `host:port` under a GENERATION-scoped key);
    processes with a lower pid accept, higher pids connect, and the
    hello frame identifies the dialing process (and whether this is a
    reconnect).  One receiver thread per peer demuxes frames by
    sequence number into the matching ticket.

    `_endpoint=(client, pid, nproc)` drives the rendezvous over a fake
    in-memory KV for tests, like HostWire."""

    def __init__(self, world: int, *, tag: str = "ox0",
                 host: Optional[str] = None,
                 keepalive_s: float = DEFAULT_KEEPALIVE_S,
                 reconnect_attempts: int = DEFAULT_RECONNECT_ATTEMPTS,
                 reconnect_window_s: float = DEFAULT_RECONNECT_WINDOW_S,
                 reconnect_policy: Optional[RetryPolicy] = None,
                 _endpoint=None):
        super().__init__(world, keepalive_s=keepalive_s)
        from .hostwire import _client

        if _endpoint is not None:
            self._kv, self.pid, self.nproc = _endpoint
        else:
            import jax

            self.pid = jax.process_index()
            self.nproc = jax.process_count()
            self._kv, _, _ = _client()
        self.tag = tag
        self._scope = f"dstpu/overlap/{tag}"
        self.reconnect_attempts = int(reconnect_attempts)
        self.reconnect_window_s = float(reconnect_window_s)
        self._reconnect_policy = reconnect_policy or RetryPolicy(
            max_attempts=max(1, self.reconnect_attempts),
            base_delay_ms=100.0, max_delay_ms=2000.0, jitter=0.25)

        self._conns: Dict[int, _PeerConn] = {}
        self._conn_epoch: Dict[int, int] = {}  # installs per peer
        self._conn_cv = threading.Condition()
        self._tickets: Dict[int, ExchangeTicket] = {}
        self._tickets_lock = threading.Lock()
        self._stash: Dict[int, List[Tuple[int, np.ndarray]]] = {}
        self._retired_max = -1
        # sender-side resend buffer: seq -> [(rank, block)], retained
        # until every peer ACKed the frame; _unacked tracks who has not
        self._resend: Dict[int, List[Tuple[int, np.ndarray]]] = {}
        self._unacked: Dict[int, set] = {}
        self._resend_lock = threading.Lock()
        self._host = host
        self._gen = 0
        self._peer_gen: Dict[int, int] = {q: 0 for q in range(self.nproc)
                                          if q != self.pid}
        self._kv_mode = False
        self._kv_published: set = set()
        self._kv_thread: Optional[threading.Thread] = None
        self._aux_threads: List[threading.Thread] = []
        self._demote_vote_posted = False
        self._demote_arrive_posted = False

        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        try:
            self._bind_listener()
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name="dstpu-overlap-accept",
                daemon=True)
            self._accept_thread.start()

            # higher pids dial lower pids; the hello names the dialer
            for q in range(self.pid):
                s = self._dial(q, reconnect=False)
                self._install_conn(q, s, reconnected=False)
            deadline = time.monotonic() + _ACCEPT_TIMEOUT_S
            with self._conn_cv:
                expected = set(range(self.pid + 1, self.nproc))
                while not expected <= set(self._conns):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        missing = sorted(expected - set(self._conns))
                        raise TimeoutError(
                            f"overlap exchange {tag}: processes {missing} "
                            f"never dialed in within "
                            f"{_ACCEPT_TIMEOUT_S:.0f}s")
                    self._conn_cv.wait(left)
        except BaseException:
            # a half-built mesh must not leak its accept loop, bound
            # listener, or already-installed peer conns — a supervisor
            # catching the init failure and retrying in-process would
            # accumulate one set per attempt
            self.close()
            raise

    # -- rendezvous ---------------------------------------------------

    def _addr_key(self, pid: int, gen: int) -> str:
        return f"{self._scope}/g{gen}/addr{pid}"

    def _bind_listener(self):
        from .hostwire import _kv_set

        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", 0))
        self._listener.listen(self.nproc)
        port = self._listener.getsockname()[1]
        my_host = self._host or socket.gethostbyname(socket.gethostname())
        # write-once KV: a rebound listener cannot overwrite its old
        # endpoint, so each bind publishes under the NEXT generation
        _kv_set(self._kv, self._addr_key(self.pid, self._gen),
                f"{my_host}:{port}".encode())

    def _dial(self, peer: int, reconnect: bool) -> socket.socket:
        """Connect to `peer` with bounded exponential backoff through
        the transient-fault taxonomy.  Each attempt re-reads the peer's
        generation-scoped address key; a refused connection probes the
        NEXT generation (the peer may have rebound its listener)."""
        from .hostwire import _kv_get

        policy = self._reconnect_policy
        attempts = max(1, self.reconnect_attempts) if reconnect \
            else policy.max_attempts
        # a reconnect's TOTAL budget is the window: it matches the
        # accepting side's re-dial wait, and (unlike attempts x 60 s
        # connect timeouts, which can exceed the ticket deadline) it is
        # sized below overlap_timeout_ms — a blackholed peer must reach
        # the KV fallback + coordinated demotion BEFORE an in-flight
        # ticket's wait fires and kills the run
        deadline = (time.monotonic() + self.reconnect_window_s) \
            if reconnect else None
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            if self._closed:
                # bail promptly mid-redial: close() only joins 5 s, and
                # a daemon thread still inside a coordination-KV RPC at
                # interpreter exit aborts the whole process (the peer
                # whose exit dropped this conn often WAS the KV host)
                raise ConnectionError(
                    f"overlap exchange closed while dialing process "
                    f"{peer}") from last
            step_timeout = _CONNECT_TIMEOUT_S
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                step_timeout = min(step_timeout, left)
            try:
                fault_point("exchange.connect")
                addr = _kv_get(
                    self._kv, self._addr_key(peer, self._peer_gen[peer]),
                    int(step_timeout * 1000)).decode()
                h, p = addr.rsplit(":", 1)
                s = socket.create_connection((h, int(p)),
                                             timeout=step_timeout)
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.sendall(_HELLO.pack(
                    self.pid, _HELLO_RECONNECT if reconnect else 0))
                return s
            except (OSError, TransientFault, TimeoutError) as e:
                last = e
                if isinstance(e, ConnectionRefusedError):
                    # the peer may have rebound (new port, next gen)
                    self._probe_peer_gen(peer)
                if attempt >= attempts or (
                        deadline is not None
                        and time.monotonic() >= deadline):
                    break
                delay = policy.delay_s(min(attempt, policy.max_attempts))
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                logger.warning(
                    f"overlap exchange: connect to process {peer} failed "
                    f"(attempt {attempt}/{attempts}): "
                    f"{type(e).__name__}: {e}; retrying in "
                    f"{delay * 1000:.0f} ms")
                time.sleep(delay)
        budget = (f"{attempts} attempt(s) / "
                  f"{self.reconnect_window_s:.0f}s window") if reconnect \
            else f"{attempts} attempt(s)"
        raise ConnectionError(
            f"overlap exchange: could not reach process {peer} in "
            f"{budget}") from last

    def _probe_peer_gen(self, peer: int) -> None:
        """A refused dial may mean the peer rebound its listener under
        the next generation key — adopt it when present."""
        from .hostwire import _kv_get

        try:
            _kv_get(self._kv,
                    self._addr_key(peer, self._peer_gen[peer] + 1), 500)
            self._peer_gen[peer] += 1
        except Exception:
            pass

    def _accept_loop(self):
        """Persistent accept thread: initial mesh construction AND
        re-accepts after a drop ride the same listener for the
        exchange's lifetime."""
        while not self._closed:
            try:
                s, _ = self._listener.accept()
            except OSError:
                if self._closed:
                    return
                # the listener itself died: rebind under the next
                # generation so dialers can find the new endpoint
                try:
                    self._gen += 1
                    self._bind_listener()
                    logger.warning(
                        "overlap exchange: listener rebound (generation "
                        f"{self._gen})")
                    continue
                except OSError as e:
                    logger.error(f"overlap exchange: listener rebind "
                                 f"failed: {e}")
                    return
            try:
                s.settimeout(_CONNECT_TIMEOUT_S)
                hello = _read_exact(s, _HELLO.size)
                if hello is None:
                    s.close()
                    continue
                q, flags = _HELLO.unpack(hello)
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except (OSError, struct.error):
                try:
                    s.close()
                except OSError:
                    pass
                continue
            self._install_conn(q, s, reconnected=bool(
                flags & _HELLO_RECONNECT))

    def _install_conn(self, peer: int, sock: socket.socket,
                      reconnected: bool):
        with self._conn_cv:
            old = self._conns.pop(peer, None)
            # the install epoch is per PEER, not per live conn: the
            # broken conn is popped before its replacement installs, so
            # a conn-local counter would restart and the re-accept
            # waiter could never observe progress
            epoch = self._conn_epoch.get(peer, -1) + 1
            self._conn_epoch[peer] = epoch
            conn = _PeerConn(sock, gen=epoch)
            self._conns[peer] = conn
            self._conn_cv.notify_all()
        if old is not None:
            _close_sock(old.sock)
            self._track_aux(old.thread)
        t = threading.Thread(target=self._recv_loop, args=(peer, conn),
                             name=f"dstpu-overlap-recv{peer}", daemon=True)
        conn.thread = t
        t.start()
        if reconnected:
            COUNTERS.add("exchange.reconnects")
            logger.warning(
                f"overlap exchange: connection to process {peer} "
                f"re-established (conn generation {conn.gen}); replaying "
                "unacknowledged frames")
            self._replay_unacked(peer)
        if self._kv_mode:
            # a peer that connects AFTER the one-shot DEMOTE broadcast
            # (its conn was down, or the broadcast send to it failed)
            # must still learn of the demotion, or it keeps training on
            # sockets while this rank blocks in the demotion barrier
            self._send_frame(peer, self._frame(_FT_DEMOTE, 0))

    # -- frames -------------------------------------------------------

    def _frame(self, ftype: int, seq: int,
               blocks: Optional[List[Tuple[int, np.ndarray]]] = None
               ) -> bytes:
        blocks = blocks or []
        table = b"".join(
            _ENT.pack(b.nbytes, rank, zlib.crc32(b) & 0xFFFFFFFF)
            for rank, b in blocks)
        payload = b"".join(b.tobytes() for _, b in blocks)
        return _HDR.pack(ftype, seq, len(blocks)) + table + payload

    def _send_frame(self, peer: int, frame: bytes) -> bool:
        """One frame to one peer; a failure tears the connection down
        (the reconnect path owns recovery) and returns False — it never
        raises, because the resend buffer still holds the frame."""
        with self._conn_cv:
            conn = self._conns.get(peer)
        if conn is None:
            return False
        try:
            with conn.lock:
                conn.sock.sendall(frame)
            return True
        except (OSError, TransientFault) as e:
            self._mark_conn_broken(peer, conn, e)
            return False

    def _send(self, ticket, blocks):
        # register-then-check ordering matters: _enter_kv_mode snapshots
        # _unacked under _resend_lock after raising the flag, so every
        # seq is either in its snapshot or sees _kv_mode here — never
        # neither (a frame that is neither socket-sent nor KV-published
        # would strand its peers until the ticket timeout)
        with self._resend_lock:
            self._resend[ticket.seq] = blocks
            self._unacked[ticket.seq] = set(self._peer_gen)
        if self._kv_mode:
            self._kv_publish(ticket.seq, blocks)
            # the write-once KV keys are the durable store and no ACKs
            # ride this transport — dropping the registration keeps the
            # resend buffer from growing a full payload per step while
            # ranks behind the demotion target keep training
            with self._resend_lock:
                self._unacked.pop(ticket.seq, None)
                self._resend.pop(ticket.seq, None)
            return
        frame = self._frame(_FT_DATA, ticket.seq, blocks)
        for q in sorted(self._peer_gen):
            try:
                fault_point("exchange.send")
            except BaseException as e:
                with self._conn_cv:
                    conn = self._conns.get(q)
                if conn is not None:
                    self._mark_conn_broken(q, conn, e)
                continue
            self._send_frame(q, frame)

    def _idle_tick(self):
        if self._kv_mode or self._closed:
            return
        frame = self._frame(_FT_KEEPALIVE, 0)
        for q in list(self._peer_gen):
            self._send_frame(q, frame)

    def _replay_unacked(self, peer: int):
        with self._resend_lock:
            todo = sorted(seq for seq, peers in self._unacked.items()
                          if peer in peers)
            frames = [(seq, self._resend[seq]) for seq in todo]
        for seq, blocks in frames:
            nbytes = sum(b.nbytes for _, b in blocks)
            if self._send_frame(peer, self._frame(_FT_DATA, seq, blocks)):
                COUNTERS.add("exchange.resends", nbytes)
                logger.warning(
                    f"overlap exchange: resent frame seq={seq} "
                    f"({nbytes} B) to process {peer}")
            else:
                return  # connection died again; the next install replays

    def _handle_ack(self, peer: int, seq: int):
        with self._resend_lock:
            peers = self._unacked.get(seq)
            if peers is None:
                return
            peers.discard(peer)
            if not peers:
                del self._unacked[seq]
                self._resend.pop(seq, None)

    def _recv_loop(self, peer: int, conn: _PeerConn):
        s = conn.sock
        try:
            while True:
                hdr = _read_exact(s, _HDR.size)
                if hdr is None:
                    if self._closed or self._kv_mode:
                        return
                    raise ConnectionError("peer closed the connection")
                fault_point("exchange.recv")
                ftype, seq, n = _HDR.unpack(hdr)
                if ftype == _FT_ACK:
                    self._handle_ack(peer, seq)
                    continue
                if ftype == _FT_KEEPALIVE:
                    continue
                if ftype == _FT_DEMOTE:
                    self._enter_kv_mode(
                        f"process {peer} requested demotion")
                    continue
                entries = []
                for _ in range(n):
                    nbytes, rank, crc = _ENT.unpack(
                        _read_exact(s, _ENT.size))
                    entries.append((rank, nbytes, crc))
                for rank, nbytes, crc in entries:
                    raw = _read_exact(s, nbytes)
                    raw = fault_filter("exchange.payload", raw)
                    if len(raw) != nbytes or \
                            (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
                        raise ConnectionError(
                            f"corrupt frame seq={seq} rank={rank} from "
                            f"process {peer} ({len(raw)}/{nbytes} B, "
                            "CRC mismatch)")
                    self._route(seq, rank,
                                np.frombuffer(raw, dtype=np.uint8))
                # receipt acknowledged only once every entry verified:
                # the sender may now drop the frame from its buffer
                self._send_frame(peer, self._frame(_FT_ACK, seq))
        except (OSError, ValueError, TypeError, struct.error,
                ConnectionError, TransientFault) as e:
            if not self._closed and not self._kv_mode:
                self._mark_conn_broken(peer, conn, e)

    # -- connection failure / healing ---------------------------------

    def _mark_conn_broken(self, peer: int, conn: _PeerConn,
                          exc: BaseException):
        with self._conn_cv:
            if self._conns.get(peer) is not conn:
                return  # already replaced by a newer connection
            del self._conns[peer]
        _close_sock(conn.sock)
        # keep the dead conn's receiver tracked: close() must join it
        # and LOG it by name if it is wedged (a recv blocked on an fd
        # closed out from under it never wakes), never silently drop it
        self._track_aux(conn.thread)
        if self._closed or self._kv_mode:
            return
        logger.warning(
            f"overlap exchange: connection to process {peer} dropped "
            f"({type(exc).__name__}: {exc}); "
            + ("re-dialing with bounded backoff" if peer < self.pid
               else "awaiting the peer's re-dial"))
        if peer < self.pid:
            t = threading.Thread(target=self._reconnect, args=(peer,),
                                 name=f"dstpu-overlap-redial{peer}",
                                 daemon=True)
        else:
            t = threading.Thread(target=self._await_reaccept,
                                 args=(peer, conn.gen),
                                 name=f"dstpu-overlap-await{peer}",
                                 daemon=True)
        self._track_aux(t)
        t.start()

    def _track_aux(self, t: Optional[threading.Thread]) -> None:
        """Track a service thread no longer owned by a live connection
        (dead conns' receivers, redial/await workers) so close() joins
        it and the watchdog's thread report sees it."""
        if t is None or t is threading.current_thread():
            return
        with self._conn_cv:
            self._aux_threads = [a for a in self._aux_threads
                                 if a.is_alive() and a is not t]
            if t.is_alive() or not t.ident:
                self._aux_threads.append(t)

    def _reconnect(self, peer: int):
        if self.reconnect_attempts <= 0:
            self._declare_broken(ConnectionError(
                "reconnection disabled (overlap_reconnect_attempts=0)"))
            return
        try:
            s = self._dial(peer, reconnect=True)
        except BaseException as e:
            self._declare_broken(e)
            return
        if self._closed or self._kv_mode:
            _close_sock(s)
            return
        # _install_conn counts this side's exchange.reconnects and
        # replays our unacked frames; the acceptor side does the same
        # when it sees the reconnect hello
        self._install_conn(peer, s, reconnected=True)

    def _await_reaccept(self, peer: int, old_gen: int):
        deadline = time.monotonic() + self.reconnect_window_s
        with self._conn_cv:
            while True:
                conn = self._conns.get(peer)
                if conn is not None and conn.gen > old_gen:
                    return  # the peer re-dialed; _install_conn replayed
                if self._closed or self._kv_mode:
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._conn_cv.wait(left)
        self._declare_broken(ConnectionError(
            f"process {peer} did not re-dial within "
            f"{self.reconnect_window_s:.0f}s"))

    def _declare_broken(self, exc: BaseException):
        """Reconnect budget exhausted: fall back to the KV transport
        (correct, slower) and flag coordinated demotion; with no KV
        client there is nothing to serve payloads through — fail every
        in-flight ticket loudly."""
        if self._closed:
            return
        if self._kv is not None:
            self._enter_kv_mode(
                f"reconnect budget exhausted ({type(exc).__name__}: "
                f"{exc})", exc)
            return
        self.broken = exc
        with self._tickets_lock:
            tickets = list(self._tickets.values())
        err = ExchangeBroken(
            f"overlap exchange is down and has no KV fallback: {exc}")
        err.__cause__ = exc
        for t in tickets:
            t.fail(err)

    # -- KV fallback transport ----------------------------------------

    def _demote_pending_key(self) -> str:
        return f"{self._scope}/demote/pending"

    def poll_peer_demotion(self) -> bool:
        """Cheap pre-dispatch probe the engine runs while this exchange
        is unhealthy (a peer connection is down): a peer that entered
        the KV fallback posted the demote-pending key the moment it did.
        Learning about it BEFORE dispatching the next step's programs
        closes a real deadlock: that peer parks in the demotion barrier
        at its step boundary and never joins this step's in-program
        collectives, so a rank that dispatches first blocks in a psum
        until the barrier timeout.  Healthy mesh or already-flagged:
        no KV traffic."""
        if self.demote_requested or self._closed:
            return self.demote_requested
        with self._conn_cv:
            if len(self._conns) == len(self._peer_gen):
                return False  # all conns up — nothing to suspect
        from .hostwire import _kv_get

        try:
            raw = _kv_get(self._kv, self._demote_pending_key(), 50)
        except Exception:
            return False  # not posted (or a KV hiccup): keep training
        self._enter_kv_mode("peer demotion pending: "
                            + raw.decode("utf-8", "replace"))
        return True

    def _enter_kv_mode(self, reason: str,
                       exc: Optional[BaseException] = None):
        with self._conn_cv:
            if self._kv_mode or self._closed:
                return
            self._kv_mode = True
            self.demote_requested = True
            if exc is not None:
                self.broken = exc
            conns = list(self._conns.items())
            self._conn_cv.notify_all()
        logger.warning(
            f"overlap exchange: {reason} — switching to the "
            "coordination-KV fallback transport and requesting "
            "coordinated demotion to the serial wire (training stays "
            "bitwise; throughput degrades until the ranks agree)")
        # durable fast flag for peers whose conn to us is already gone
        # (the DEMOTE frame below only reaches live conns): their
        # pre-dispatch poll_peer_demotion() picks this up
        from .hostwire import _kv_set

        try:
            _kv_set(self._kv, self._demote_pending_key(),
                    reason.encode()[:256])
        except Exception:
            pass  # another rank posted first — same signal
        # tell every still-reachable peer, then serve everything a peer
        # might still be missing through write-once KV keys
        demote = self._frame(_FT_DEMOTE, 0)
        for q, _ in conns:
            # a failed send scraps the dead conn (_send_frame marks it
            # broken) so a later re-accept installs a fresh one —
            # _install_conn re-sends DEMOTE to it
            self._send_frame(q, demote)
        with self._resend_lock:
            outstanding = sorted(self._unacked)
            frames = [(seq, self._resend[seq]) for seq in outstanding]
        for seq, blocks in frames:
            self._kv_publish(seq, blocks)
        with self._resend_lock:
            for seq, _ in frames:
                self._unacked.pop(seq, None)
                self._resend.pop(seq, None)
        self._kv_thread = threading.Thread(
            target=self._kv_fetch_loop, name="dstpu-overlap-kvfetch",
            daemon=True)
        self._kv_thread.start()

    def _kv_publish(self, seq: int, blocks: List[Tuple[int, np.ndarray]]):
        from .hostwire import _kv_put_bytes

        for rank, b in blocks:
            key = (seq, int(rank))
            # claim atomically: the sender worker (kv-mode _send) and
            # the healer thread (_enter_kv_mode's outstanding replay)
            # can race on the same seq, and a duplicate put on the
            # write-once KV key is a LOUD failure — exactly one side
            # may publish each (seq, rank)
            with self._resend_lock:
                if key in self._kv_published:
                    continue
                self._kv_published.add(key)
            _kv_put_bytes(self._kv, f"{self._scope}/kvx/s{seq}/r{rank}",
                          b.tobytes())

    def _kv_fetch_loop(self):
        from .hostwire import _kv_get_bytes

        while not self._closed:
            with self._tickets_lock:
                live = sorted(self._tickets.items())
            progressed = False
            for seq, ticket in live:
                for r in ticket.missing_ranks():
                    if self._closed:
                        return
                    try:
                        raw = _kv_get_bytes(
                            self._kv, f"{self._scope}/kvx/s{seq}/r{r}",
                            2000)
                    except Exception:
                        continue  # not posted yet; retry next sweep
                    ticket.post(r, np.frombuffer(raw, dtype=np.uint8))
                    progressed = True
            if not progressed:
                time.sleep(0.05)

    def agree_demotion_step(self, step: int, timeout_ms: int = 120_000
                            ) -> Optional[int]:
        """Non-parking demotion agreement (engine, at step boundaries).

        A naive blocking barrier here deadlocks the mesh: a rank that
        parks waiting for peers stops dispatching device programs, and
        a peer that was already mid-step blocks forever inside an
        in-program collective the parked rank never joins (observed on
        the 2-proc TCP campaign, both orderings).  Instead:

        1. VOTE: post this rank's first flagged boundary under a
           write-once key, then read every rank's vote NON-blocking.
           Any vote missing -> return None: the engine keeps training
           (the KV fallback transport stays bitwise) and retries at the
           next boundary — nobody ever parks while a peer might still
           be mid-dispatch.
        2. TARGET = max(votes) + 1.  The +1 means every vote is a full
           step old (posted at or before boundary max(votes)) by the
           time any rank reaches the target, so all ranks compute the
           SAME target from the same frozen write-once set.
        3. ARRIVE: a rank at the target posts an arrival key and
           blocking-reads every rank's arrival.  Parking here is safe:
           this rank has dispatched every program up to the target, so
           all peers can reach the target without it.  Returns
           max(arrivals) — the step every rank demotes at together.

        The blocking phase is bounded by timeout_ms (shared deadline,
        deadline-exceeded NOT retried: the barrier timeout IS the
        dead-peer detector, the KVSignals.wait precedent)."""
        from .hostwire import _kv_get, _kv_set

        b = int(step)
        if not self._demote_vote_posted:
            try:
                _kv_set(self._kv,
                        f"{self._scope}/demote/vote/r{self.pid}",
                        str(b).encode())
            except Exception:
                pass  # a crash-relaunch may find its old vote: same value
            self._demote_vote_posted = True
        votes = []
        for q in range(self.nproc):
            try:
                votes.append(int(_kv_get(
                    self._kv, f"{self._scope}/demote/vote/r{q}", 50)))
            except Exception:
                return None  # a rank has not flagged yet — keep training
        target = max(votes) + 1
        if b < target:
            return target
        if not self._demote_arrive_posted:
            try:
                _kv_set(self._kv,
                        f"{self._scope}/demote/arrive/r{self.pid}",
                        str(b).encode())
            except Exception:
                pass
            self._demote_arrive_posted = True
        deadline = time.monotonic() + timeout_ms / 1000.0

        def read(q: int):
            # raw read (values ride the wire base64'd, like _kv_get, and
            # the key carries the same incarnation scope _kv_set wrote
            # it under): remaining time recomputed per attempt from ONE
            # shared deadline, and deadline-exceeded NOT retried
            import base64

            from .hostwire import scoped_key

            left = max(1, int((deadline - time.monotonic()) * 1000))
            return base64.b64decode(self._kv.blocking_key_value_get(
                scoped_key(f"{self._scope}/demote/arrive/r{q}"), left))

        final = target
        for q in range(self.nproc):
            if q == self.pid:
                continue
            val = retry_transient(lambda q=q: read(q),
                                  site=f"exchange.demote r{q}",
                                  classify=is_transient_not_timeout)
            final = max(final, int(val))
        return final

    # -- ticket routing / lifecycle -----------------------------------

    def _register(self, seq: int) -> ExchangeTicket:
        ticket = ExchangeTicket(seq, self.world)
        with self._tickets_lock:
            self._tickets[seq] = ticket
            for rank, block in self._stash.pop(seq, []):
                ticket.post(rank, block)
        return ticket

    def _route(self, seq: int, rank: int, block: np.ndarray):
        with self._tickets_lock:
            t = self._tickets.get(seq)
            if t is None:
                if seq <= self._retired_max:
                    return  # duplicate of an already-combined frame
                # frame arrived before submit() registered the ticket
                self._stash.setdefault(seq, []).append((rank, block))
                return
        t.post(rank, block)

    def retire(self, ticket: ExchangeTicket):
        """Drop a completed ticket's registration (the engine retires
        tickets after combining, bounding the map to in-flight ones)."""
        with self._tickets_lock:
            self._tickets.pop(ticket.seq, None)
            if ticket.seq > self._retired_max:
                self._retired_max = ticket.seq

    def threads(self) -> List[threading.Thread]:
        with self._conn_cv:
            recv = [c.thread for c in self._conns.values()]
        cand = ([self._worker, self._accept_thread, self._kv_thread]
                + recv + list(self._aux_threads))
        return [t for t in cand if t is not None and t.is_alive()]

    def close(self):
        was_closed = self._closed
        super().close()
        if was_closed:
            return
        if self._listener is not None:
            _close_sock(self._listener)
        with self._conn_cv:
            conns = list(self._conns.values())
            self._conns.clear()
            self._conn_cv.notify_all()
        for c in conns:
            _close_sock(c.sock)
        join = [self._accept_thread, self._kv_thread] + \
            [c.thread for c in conns] + list(self._aux_threads)
        for t in join:
            if t is not None and t is not threading.current_thread():
                t.join(timeout=_CLOSE_JOIN_S)
        self._log_leaked([t for t in join
                          if t is not threading.current_thread()])
        self._aux_threads = []
        self._kv_thread = None
        # drop the payload buffers: a demoted engine keeps the process
        # alive long after this close, and these can hold a gradient
        # payload per in-flight step
        with self._resend_lock:
            self._resend.clear()
            self._unacked.clear()
            self._kv_published.clear()
        with self._tickets_lock:
            self._stash.clear()


def _close_sock(s) -> None:
    try:
        s.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        s.close()
    except OSError:
        pass


def _read_exact(s: socket.socket, n: int) -> Optional[bytes]:
    parts = []
    got = 0
    while got < n:
        chunk = s.recv(min(1 << 20, n - got))
        if not chunk:
            if parts:  # EOF mid-frame: the peer died mid-send
                raise ConnectionError("peer closed mid-frame")
            return None  # clean EOF at a frame boundary (shutdown)
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


_EXCHANGE_SEQ = [0]


def make_exchange(world: int, tag: Optional[str] = None, **kwargs):
    """The right transport for the current topology: sockets across
    processes, the in-process fast path otherwise.  Each construction
    gets a fresh rendezvous tag (the coordination KV is write-once and
    engine construction order is identical on every process, so the
    per-process counter agrees globally).  `kwargs` (keepalive_s,
    reconnect_attempts, reconnect_window_s) tune the self-healing
    machinery; the engine derives them from the comm config."""
    import jax

    if jax.process_count() > 1:
        if tag is None:
            tag = f"ox{_EXCHANGE_SEQ[0]}"
            _EXCHANGE_SEQ[0] += 1
        return SocketExchange(world, tag=tag, **kwargs)
    return LocalExchange(world,
                         keepalive_s=kwargs.get("keepalive_s",
                                                DEFAULT_KEEPALIVE_S))
