"""Host-threaded wire exchange: comm/compute overlap for the bucketed
gradient wire and the qwZ parameter gather.

Why a HOST transport and not an XLA restructure: on the XLA:CPU runtime
this repo benches on, collective thunks execute inline in the per-device
thunk sequence — probed exhaustively while building this module: a
collective issued before / interleaved with / data-independent of the
remaining compute runs in exactly the same wall-clock as one issued
after it (fused == barrier-serialized, to the millisecond), and the
gloo wire's time is ~78% CPU-busy (process_time/wall), so even
thread-level concurrency cannot hide it on a saturated box.  What CAN
overlap is a transport whose waits are real OS blocking: raw sockets
move the same payload ~10x cheaper than the in-program collective and
spend most of that in `recv` — idle time the device pipeline runs
straight through.  On TPU fabrics the same schedule-driven structure
lets XLA's latency-hiding scheduler do the overlap in-program; on this
fabric the host exchange IS the overlap mechanism, and the bench
measures the exposure honestly either way (BENCH.md overlap round).

The pieces:

* `ExchangeTicket` — one in-flight exchange: `wait()` returns the
  rank-ordered `[world, nbytes]` payload matrix and records how long the
  caller was blocked (the EXPOSED wire time the monitor's
  `grad_wire.exposed_ms` counter reports).
* `LocalExchange` — single-process transport: every rank is addressable,
  so the "exchange" is a background-thread materialization of the local
  shards.  The threaded driver machinery (submit/wait ordering, ticket
  lifecycle, teardown) is exactly the multi-process one, so tier-1
  covers it without sockets.
* `SocketExchange` — N-process transport: a full mesh of persistent TCP
  connections (rendezvoused through the coordination-service KV the
  hostwire already rides), one receiver thread per peer demuxing
  sequence-tagged frames, one sender worker serializing submissions in
  order.  Frames are self-describing (per-rank payload table), so the
  receiver needs no topology assumptions.

Exchanges are identified by a monotonically increasing sequence number.
Every process submits the same exchanges in the same order (the engine
step flow is deterministic across ranks), so a frame's sequence number
alone pairs it with its ticket.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ...utils.logging import logger

# frame: [seq u64][n_entries u32] then per entry [rank u32][nbytes u64],
# then the concatenated payloads in entry order
_HDR = struct.Struct("<QI")
_ENT = struct.Struct("<QI")  # (nbytes, rank) — fixed width, order below

_CONNECT_TIMEOUT_S = 60.0
_ACCEPT_TIMEOUT_S = 60.0


def _now() -> float:
    return time.perf_counter()


class ExchangeTicket:
    """One in-flight exchange.  `wait()` blocks until every expected
    rank's payload has landed and returns the `[world, nbytes]` uint8
    matrix (rank-major).  Timing:

    * `done_at`   when the last payload landed (transport-side stamp)
    * `wait_us`   how long wait() was actually blocked — the caller's
                  EXPOSED wire time (0 when the exchange finished
                  behind compute)
    """

    def __init__(self, seq: int, world: int):
        self.seq = seq
        self.world = world
        self._cond = threading.Condition()
        self._blocks: Dict[int, np.ndarray] = {}
        self._error: Optional[BaseException] = None
        self.created_at = _now()
        self.done_at: Optional[float] = None
        self.wait_us = 0

    # -- transport side -----------------------------------------------

    def post(self, rank: int, block: np.ndarray) -> None:
        with self._cond:
            self._blocks[int(rank)] = block
            if len(self._blocks) >= self.world:
                self.done_at = _now()
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    # -- consumer side ------------------------------------------------

    @property
    def ready(self) -> bool:
        with self._cond:
            return self._error is not None or \
                len(self._blocks) >= self.world

    def wait(self, timeout_s: float = 300.0) -> np.ndarray:
        t0 = _now()
        with self._cond:
            deadline = t0 + timeout_s
            while self._error is None and len(self._blocks) < self.world:
                remaining = deadline - _now()
                if remaining <= 0:
                    raise TimeoutError(
                        f"overlap exchange seq={self.seq}: only "
                        f"{sorted(self._blocks)} of {self.world} rank "
                        f"payloads arrived within {timeout_s:.0f}s")
                self._cond.wait(remaining)
            self.wait_us += int((_now() - t0) * 1e6)
            if self._error is not None:
                raise RuntimeError(
                    f"overlap exchange seq={self.seq} failed"
                ) from self._error
            blocks = [self._blocks[r] for r in range(self.world)]
        return np.stack(blocks)


class _ExchangeBase:
    """Shared submit-worker machinery: one persistent worker thread
    materializes each submission's device shards (np.asarray blocks the
    WORKER on the producing program, never the driver) and hands the
    blocks to the transport in submission order."""

    def __init__(self, world: int):
        self.world = int(world)
        self._seq = 0
        self._tasks: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="dstpu-overlap-send",
                daemon=True)
            self._worker.start()

    def _worker_loop(self):
        while True:
            task = self._tasks.get()
            if task is None:
                return
            ticket, local_blocks = task
            try:
                blocks = [(rank, np.asarray(get()).view(np.uint8))
                          for rank, get in local_blocks]
                self._send(ticket, blocks)
                for rank, block in blocks:
                    ticket.post(rank, block)
            except BaseException as e:  # surfaced at ticket.wait()
                ticket.fail(e)

    def _send(self, ticket: ExchangeTicket,
              blocks: List[Tuple[int, np.ndarray]]) -> None:
        raise NotImplementedError

    def submit(self, local_blocks: List[Tuple[int, Callable[[], np.ndarray]]]
               ) -> ExchangeTicket:
        """Start one exchange.  `local_blocks` is [(global_rank, getter)]
        for every rank this process owns; `getter()` returns the rank's
        payload (a device array or shard — materialized on the worker
        thread, so calling submit never blocks on the producing
        program).  Returns the ticket to `wait()` on."""
        if self._closed:
            raise RuntimeError("exchange is closed")
        with self._lock:
            seq = self._seq
            self._seq += 1
            ticket = self._register(seq)
        self._ensure_worker()
        self._tasks.put((ticket, local_blocks))
        return ticket

    def _register(self, seq: int) -> ExchangeTicket:
        return ExchangeTicket(seq, self.world)

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._worker is not None and self._worker.is_alive():
            self._tasks.put(None)
            self._worker.join(timeout=10)
        self._worker = None


class LocalExchange(_ExchangeBase):
    """Single-process transport: every rank's payload is already
    addressable — the worker thread materializes them and the ticket
    completes.  No sockets, same driver surface."""

    def _send(self, ticket, blocks):
        missing = self.world - len(blocks)
        if missing:
            raise RuntimeError(
                f"LocalExchange: {len(blocks)} local payloads for a "
                f"world of {self.world} — a multi-process mesh needs "
                "the socket transport")


class SocketExchange(_ExchangeBase):
    """N-process transport over a full mesh of persistent TCP
    connections.  Rendezvous rides the coordination-service KV (each
    process publishes `host:port`); processes with a lower pid accept,
    higher pids connect, and a 4-byte hello identifies the dialing
    process.  One receiver thread per peer demuxes frames by sequence
    number into the matching ticket."""

    def __init__(self, world: int, *, tag: str = "ox0",
                 host: Optional[str] = None):
        super().__init__(world)
        from .hostwire import _client, _kv_get, _kv_set

        import jax

        self.pid = jax.process_index()
        self.nproc = jax.process_count()
        client, _, _ = _client()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", 0))
        self._listener.listen(self.nproc)
        port = self._listener.getsockname()[1]
        my_host = host or socket.gethostbyname(socket.gethostname())
        _kv_set(client, f"dstpu/overlap/{tag}/addr{self.pid}",
                f"{my_host}:{port}".encode())

        self._peers: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._tickets: Dict[int, ExchangeTicket] = {}
        self._tickets_lock = threading.Lock()
        self._stash: Dict[int, List[Tuple[int, np.ndarray]]] = {}
        self._receivers: List[threading.Thread] = []

        # higher pids dial lower pids; the 4-byte hello names the dialer
        for q in range(self.pid):
            addr = _kv_get(client, f"dstpu/overlap/{tag}/addr{q}",
                           int(_CONNECT_TIMEOUT_S * 1000)).decode()
            h, p = addr.rsplit(":", 1)
            s = socket.create_connection((h, int(p)),
                                         timeout=_CONNECT_TIMEOUT_S)
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(struct.pack("<I", self.pid))
            self._peers[q] = s
        self._listener.settimeout(_ACCEPT_TIMEOUT_S)
        for _ in range(self.pid + 1, self.nproc):
            s, _ = self._listener.accept()
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = _read_exact(s, 4)
            q = struct.unpack("<I", hello)[0]
            self._peers[q] = s
        self._listener.close()

        for q, s in self._peers.items():
            self._send_locks[q] = threading.Lock()
            t = threading.Thread(target=self._recv_loop, args=(q, s),
                                 name=f"dstpu-overlap-recv{q}",
                                 daemon=True)
            t.start()
            self._receivers.append(t)

    # -- transport ----------------------------------------------------

    def _register(self, seq: int) -> ExchangeTicket:
        ticket = ExchangeTicket(seq, self.world)
        with self._tickets_lock:
            self._tickets[seq] = ticket
            for rank, block in self._stash.pop(seq, []):
                ticket.post(rank, block)
        return ticket

    def _send(self, ticket, blocks):
        table = b"".join(_ENT.pack(b.nbytes, rank) for rank, b in blocks)
        header = _HDR.pack(ticket.seq, len(blocks)) + table
        payload = b"".join(b.tobytes() for _, b in blocks)
        for q in self._peers:
            with self._send_locks[q]:
                self._peers[q].sendall(header + payload)

    def _recv_loop(self, peer: int, s: socket.socket):
        try:
            while True:
                hdr = _read_exact(s, _HDR.size)
                if hdr is None:
                    return
                seq, n = _HDR.unpack(hdr)
                entries = []
                for _ in range(n):
                    nbytes, rank = _ENT.unpack(_read_exact(s, _ENT.size))
                    entries.append((rank, nbytes))
                for rank, nbytes in entries:
                    buf = np.frombuffer(_read_exact(s, nbytes),
                                        dtype=np.uint8)
                    self._route(seq, rank, buf)
        except (OSError, ValueError, TypeError, struct.error):
            if not self._closed:
                logger.warning(
                    f"overlap exchange: connection to process {peer} "
                    "dropped; in-flight exchanges will fail")
                with self._tickets_lock:
                    tickets = list(self._tickets.values())
                for t in tickets:
                    t.fail(ConnectionError(f"peer {peer} dropped"))

    def _route(self, seq: int, rank: int, block: np.ndarray):
        with self._tickets_lock:
            t = self._tickets.get(seq)
            if t is None:
                # frame arrived before submit() registered the ticket
                self._stash.setdefault(seq, []).append((rank, block))
                return
        t.post(rank, block)

    def retire(self, ticket: ExchangeTicket):
        """Drop a completed ticket's registration (the engine retires
        tickets after combining, bounding the map to in-flight ones)."""
        with self._tickets_lock:
            self._tickets.pop(ticket.seq, None)

    def close(self):
        was_closed = self._closed
        super().close()
        if was_closed:
            return
        for s in self._peers.values():
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        for t in self._receivers:
            t.join(timeout=5)
        self._receivers = []


def _read_exact(s: socket.socket, n: int) -> Optional[bytes]:
    parts = []
    got = 0
    while got < n:
        chunk = s.recv(min(1 << 20, n - got))
        if not chunk:
            if parts:  # EOF mid-frame: the peer died mid-send
                raise ConnectionError("peer closed mid-frame")
            return None  # clean EOF at a frame boundary (shutdown)
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


_EXCHANGE_SEQ = [0]


def make_exchange(world: int, tag: Optional[str] = None):
    """The right transport for the current topology: sockets across
    processes, the in-process fast path otherwise.  Each construction
    gets a fresh rendezvous tag (the coordination KV is write-once and
    engine construction order is identical on every process, so the
    per-process counter agrees globally)."""
    import jax

    if jax.process_count() > 1:
        if tag is None:
            tag = f"ox{_EXCHANGE_SEQ[0]}"
            _EXCHANGE_SEQ[0] += 1
        return SocketExchange(world, tag=tag)
    return LocalExchange(world)
