"""Blockwise symmetric int8/int4 wire quantization (ZeRO++ qwZ/qgZ).

ZeRO++ (arXiv:2306.10209) pairs the hierarchical wire's hpZ secondary
shards with two quantized collectives: qwZ (blockwise-int8 parameter
all-gather) and qgZ (quantized hierarchical gradient reduce-scatter).
This module owns the jittable kernels both ride:

* `quantize_blockwise`  fp32/bf16 flat tensor -> (int8 payload | packed
  int4 nibbles, one fp16 scale per `block` elements).  Symmetric: the
  per-block scale is amax/qmax, zero-point free, so dequantization is a
  single multiply and an all-zero block round-trips exactly.
* `dequantize_blockwise`  the inverse; accepts arbitrary leading batch
  dims (gathered payloads arrive as [world, nblocks, ...]) and slices
  the block padding back off.
* `quantize_rows` / `dequantize_rows`  the row-wise variant the paged
  KV cache stores blocks through (serving/kv_cache.py): one fp16 scale
  per trailing-axis row, no padding — a scatter of N rows into a block
  pool stays row-local, which is what keeps quantized KV writes as
  cheap as dense ones.
* `payload_bytes` / `padded_elems`  EXACT wire-byte accounting
  (payload + scales), consumed by BucketPlan and the qwZ gather so the
  `grad_wire.*` / `qwz.*` counters prove the compression instead of
  estimating it.

Range-safety mirrors `compressed_ar.decompose_int8_safe`:

* fp32 subnormals flush to zero BEFORE the amax (a lone subnormal must
  not poison a block's scale, and the values are unrepresentable at
  int8 granularity anyway);
* non-finite elements (±inf / NaN) are carried as a reserved marker
  code (-qmax-1, the one two's-complement value symmetric quantization
  never produces) and reconstruct as NaN, so downstream overflow checks
  fire instead of receiving a silently clipped value;
* a block whose scale overflows fp16 (amax > qmax * 65504: ~8.3e6 for
  int8, ~4.6e5 for int4) dequantizes non-finite — a LOUD skip rather
  than a silent ~1e3x shrink of the block.  Note this is a narrower
  finite range than the fp32/bf16/split wires: under dynamic loss
  scaling the scaler adapts (the skip halves the scale until scaled
  gradients fit), but fp32-static trainings with legitimately huge
  gradients should prefer int8 over int4 or keep the slow hop on bf16
  (accuracy guidance in docs/tutorials/comm_tuning.md);
* a block whose scale underflows fp16 (amax < qmax * 2^-24) flushes to
  zero — the quantized-wire analogue of the subnormal flush.

Accumulation never happens in the quantized domain: callers (the
bucketed wire's inter-group hop, the qwZ gather) dequantize each rank's
contribution to fp32 and sum locally — the qgZ trick of reducing in a
wider accumulator so quantization error does not compound across ranks.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# wire name -> (integer levels per side, i.e. qmax)
QUANT_WIRES = ("int8", "int4")
_QMAX = {"int8": 127, "int4": 7}

DEFAULT_BLOCK_SIZE = 256

_F32_MIN_NORMAL = float(np.float32(2.0 ** -126))


def validate_block_size(block) -> int:
    """Block sizes must be positive EVEN ints: int4 packs two elements
    per byte, so an odd block would split a byte across blocks."""
    if isinstance(block, bool) or not isinstance(block, (int, np.integer)):
        raise ValueError(
            f"quant_block_size must be a positive even int, got {block!r}")
    block = int(block)
    if block <= 0 or block % 2:
        raise ValueError(
            f"quant_block_size must be a positive even int, got {block}")
    return block


def qmax(wire: str) -> int:
    if wire not in _QMAX:
        raise ValueError(
            f"unknown quantized wire {wire!r}; choose from {QUANT_WIRES}")
    return _QMAX[wire]


def padded_elems(n_elems: int, block: int) -> int:
    """Elements after zero-padding to a whole number of blocks."""
    block = validate_block_size(block)
    return n_elems + (-n_elems % block)


def payload_bytes(n_elems: int, wire: str, block: int, *,
                  padded: bool = True) -> int:
    """Exact wire bytes ONE rank contributes for `n_elems` elements:
    quantized payload plus the fp16 scales riding alongside.

    padded=True prices what actually crosses the fabric (elements
    rounded up to whole blocks); padded=False is the logical payload —
    the same wire with zero padding overhead — for the
    `grad_wire.*_logical` counters that keep BENCH comparisons honest.
    """
    q = qmax(wire)
    if padded:
        n = padded_elems(n_elems, block)
        n_blocks = n // block
    else:
        n = n_elems
        n_blocks = -(-n_elems // block) if n_elems else 0
    data = n if q == 127 else -(-n // 2)  # int4: two elements per byte
    return data + n_blocks * 2            # + one fp16 scale per block


def _flush_subnormals(f32):
    return jnp.where(jnp.abs(f32) < jnp.float32(_F32_MIN_NORMAL),
                     jnp.float32(0.0), f32)


def quantize_blockwise(x, block: int, wire: str = "int8"):
    """Flat (or any-shape) tensor -> (payload, fp16 scales), routed
    through the kernel registry: the Pallas codec when probing selects
    it (kernels/quant_codec.py, BIT-identical payload), this module's
    `quantize_blockwise_ref` otherwise.  Same contract either way —
    the docstring below describes both."""
    from ...kernels import registry

    return registry.dispatch("quant_codec", x, block, wire,
                             variant="quantize", info={"block": block})


def dequantize_blockwise(payload, scales, wire: str, n_elems: int):
    """Registry-dispatching inverse; see `dequantize_blockwise_ref`."""
    from ...kernels import registry

    width = payload.shape[-1]
    block = width if wire == "int8" else width * 2
    return registry.dispatch("quant_codec", payload, scales, wire,
                             n_elems, variant="dequantize",
                             info={"block": block})


def quantize_blockwise_ref(x, block: int, wire: str = "int8"):
    """Flat (or any-shape) tensor -> (payload, fp16 scales).

    payload: int8 [n_blocks, block] for "int8", uint8 [n_blocks,
    block//2] packed low-nibble-first for "int4".  scales: fp16
    [n_blocks].  The input is flattened and zero-padded to a whole
    number of blocks; `dequantize_blockwise(..., n_elems=x.size)`
    restores the original length.
    """
    q = qmax(wire)
    block = validate_block_size(block)
    marker = -q - 1  # -128 / -8: unreachable by the symmetric clip

    f32 = _flush_subnormals(x.reshape(-1).astype(jnp.float32))
    n = f32.shape[0]
    pad = -n % block
    if pad:
        f32 = jnp.concatenate([f32, jnp.zeros((pad,), jnp.float32)])
    blocks = f32.reshape(-1, block)

    finite = jnp.isfinite(blocks)
    amax = jnp.max(jnp.where(finite, jnp.abs(blocks), 0.0), axis=1)
    # the wire-visible (fp16-rounded) scale is also the quantization
    # scale, so encode/decode agree bit-for-bit; fp16 overflow -> inf
    # scale (block dequantizes non-finite), underflow -> 0 (block
    # flushes to zero) — both intentional, see module doc
    scales = (amax / q).astype(jnp.float16)
    eff = scales.astype(jnp.float32)[:, None]
    inv = jnp.where((eff > 0) & jnp.isfinite(eff), 1.0 / eff, 0.0)
    codes = jnp.clip(jnp.round(blocks * inv), -q, q).astype(jnp.int8)
    codes = jnp.where(finite, codes, jnp.int8(marker))

    if q == 127:
        return codes, scales
    u = codes.astype(jnp.uint8) & jnp.uint8(0x0F)  # two's-complement nibble
    packed = u[:, 0::2] | (u[:, 1::2] << 4)
    return packed, scales


def dequantize_blockwise_ref(payload, scales, wire: str, n_elems: int):
    """(payload, scales) -> fp32 [..., n_elems].

    Broadcasts over leading batch dims: an all-gathered wire arrives as
    payload [world, n_blocks, w] + scales [world, n_blocks] and comes
    back [world, n_elems] — each rank's contribution dequantized
    independently, ready for the fp32 accumulate.
    """
    q = qmax(wire)
    marker = -q - 1
    if q == 127:
        codes = payload.astype(jnp.int8)
    else:
        lo = (payload & jnp.uint8(0x0F)).astype(jnp.int8)
        hi = ((payload >> 4) & jnp.uint8(0x0F)).astype(jnp.int8)
        lo = jnp.where(lo > 7, lo - 16, lo)
        hi = jnp.where(hi > 7, hi - 16, hi)
        codes = jnp.stack([lo, hi], axis=-1).reshape(
            payload.shape[:-1] + (payload.shape[-1] * 2,))
    vals = codes.astype(jnp.float32) * \
        scales.astype(jnp.float32)[..., None]
    vals = jnp.where(codes == marker, jnp.float32(jnp.nan), vals)
    flat = vals.reshape(vals.shape[:-2] + (-1,))
    return flat[..., :n_elems]


def quantize_rows(x, wire: str = "int8"):
    """Row-wise variant for the serving KV cache: quantize the TRAILING
    axis of `x` [..., D] with ONE fp16 scale per leading-index row —
    (codes int8 [..., D] | packed uint8 [..., D // 2], scales fp16
    [...]).  No padding: the row IS the block, so a scatter of N rows
    into a larger pool stays row-local (payload.at[idx] + scales.at[idx]
    touch exactly the written rows, never a neighbour's scale).

    Same range semantics as `quantize_blockwise` (subnormal flush before
    the amax, the -qmax-1 marker for non-finites, the fp16-rounded scale
    doubling as the quantization scale so encode/decode agree
    bit-for-bit).  "int4" packs two codes per byte low-nibble-first and
    requires an even trailing axis.
    """
    q = qmax(wire)
    marker = -q - 1
    d = x.shape[-1]
    if q != 127 and d % 2:
        raise ValueError(
            f"int4 row quantization needs an even trailing axis "
            f"(two codes per byte), got {d}")
    f32 = _flush_subnormals(x.astype(jnp.float32))
    finite = jnp.isfinite(f32)
    amax = jnp.max(jnp.where(finite, jnp.abs(f32), 0.0), axis=-1)
    scales = (amax / q).astype(jnp.float16)
    eff = scales.astype(jnp.float32)[..., None]
    inv = jnp.where((eff > 0) & jnp.isfinite(eff), 1.0 / eff, 0.0)
    codes = jnp.clip(jnp.round(f32 * inv), -q, q).astype(jnp.int8)
    codes = jnp.where(finite, codes, jnp.int8(marker))
    if q == 127:
        return codes, scales
    u = codes.astype(jnp.uint8) & jnp.uint8(0x0F)
    packed = u[..., 0::2] | (u[..., 1::2] << 4)
    return packed, scales


def dequantize_rows(payload, scales, wire: str):
    """Inverse of `quantize_rows`: (payload [..., D | D // 2], scales
    [...]) -> fp32 [..., D].  Marker codes reconstruct as NaN (the
    blockwise contract); an all-zero row round-trips exactly (scale 0,
    codes 0)."""
    q = qmax(wire)
    marker = -q - 1
    if q == 127:
        codes = payload.astype(jnp.int8)
    else:
        lo = (payload & jnp.uint8(0x0F)).astype(jnp.int8)
        hi = ((payload >> 4) & jnp.uint8(0x0F)).astype(jnp.int8)
        lo = jnp.where(lo > 7, lo - 16, lo)
        hi = jnp.where(hi > 7, hi - 16, hi)
        codes = jnp.stack([lo, hi], axis=-1).reshape(
            payload.shape[:-1] + (payload.shape[-1] * 2,))
    vals = codes.astype(jnp.float32) * \
        scales.astype(jnp.float32)[..., None]
    return jnp.where(codes == marker, jnp.float32(jnp.nan), vals)


def pack_wire(payload, scales):
    """(payload, scales) -> ONE flat uint8 buffer: payload bytes then
    the scales bitcast to bytes.  On latency-bound fabrics two
    collectives cost two round-trips; fusing the scale sideband into
    the payload buffer keeps the quantized wire at ONE collective per
    bucket — the scales literally ride alongside the payload."""
    p = jax.lax.bitcast_convert_type(payload, jnp.uint8).reshape(-1)
    s = jax.lax.bitcast_convert_type(scales, jnp.uint8).reshape(-1)
    return jnp.concatenate([p, s])


def unpack_wire(buf, wire: str, block: int, n_elems: int):
    """Inverse of `pack_wire`, with leading batch dims (a gathered wire
    arrives as [world, nbytes]): -> (payload, scales) shaped for
    `dequantize_blockwise`."""
    q = qmax(wire)
    n = padded_elems(n_elems, block)
    n_blocks = n // block
    width = block if q == 127 else block // 2
    data = n_blocks * width
    p = buf[..., :data]
    if q == 127:
        p = jax.lax.bitcast_convert_type(p.astype(jnp.uint8), jnp.int8)
    p = p.reshape(buf.shape[:-1] + (n_blocks, width))
    s_bytes = buf[..., data:].reshape(buf.shape[:-1] + (n_blocks, 2))
    scales = jax.lax.bitcast_convert_type(s_bytes, jnp.float16)
    return p, scales


def quantized_all_gather(x, axes, block: int, wire: str, record=None):
    """The whole quantized-gather wire protocol in one place, shared by
    the gradient wire (BucketPlan._quant_gather_sum) and the qwZ
    parameter gather (zero/partition.QuantizedWeightGather): quantize
    `x` blockwise, fuse payload+scales into one buffer, all-gather it
    over `axes` (innermost-first sequential hops — a later hop resends
    the accumulated buffer, exactly how the byte accounting prices it),
    and return every rank's contribution dequantized to fp32 as
    [world, n_elems] (world = product of the axis sizes, outermost
    leading).  `record(nbytes)` fires once per hop with this rank's
    payload bytes.  Callers sum (qgZ) or reassemble (qwZ) — both in the
    wide domain, never the quantized one."""
    n_elems = x.size
    payload, scales = quantize_blockwise(x, block, wire)
    buf = pack_wire(payload, scales)
    nbytes = buf.shape[0]
    for a in reversed(tuple(axes)):
        if record is not None:
            record(int(buf.size))
        buf = jax.lax.all_gather(buf, a, axis=0, tiled=False)
    buf = buf.reshape((-1, nbytes))
    p, s = unpack_wire(buf, wire, block, n_elems)
    return dequantize_blockwise(p, s, wire, n_elems)
