"""Bucketed gradient-reduction wire for the dense data-parallel path.

Round-5 measured the dense DP step at 270 ms on the 2-process TCP fabric
vs 53 ms for the onebit `sign` wire carrying the SAME bytes: the gap is
~40 per-leaf collectives (XLA's implicit psum at the loss-mean boundary)
vs one fused buffer, and per-collective latency dominates on
serialization-bound fabrics.  This module is the reference's bucketing
recipe (stage2.py:614-745 flatten/reduce machinery, ZeRO §5 of
1910.02054) rebuilt as a STATIC plan the jitted step consumes:

* `BucketPlan` is computed ONCE at `initialize()` from the gradient tree
  — dtype-segregated, size-capped flat buckets (honoring the config's
  `reduce_bucket_size`, in elements like the reference) with precomputed
  per-leaf offsets.  No per-step Python walks the tree to decide layout.
* Inside the jitted step (under `shard_map` over the `data` axis) the
  local gradients concatenate into the plan's buckets and ride ONE
  collective per bucket instead of one per leaf.
* Wire modes select what crosses the fabric:
    - "fp32"  psum of the fp32 bucket (the `fp32_allreduce` /
              `allreduce_always_fp32` behaviour; default).
    - "bf16"  bucket cast to bf16 before the psum — half the bytes,
              ~8-bit mantissa accumulation (XLA sums bf16 natively).
    - "split" the EleutherAI 24-bit frexp wire (compressed_ar.py) riding
              GATHER semantics: each rank's bucket decomposes into an
              fp16 mantissa + int8 exponent (3 bytes/elem), both
              all-gathered, then ldexp-reconstructed in fp32 and summed
              locally.  Per-contribution relative error is ≤ 2^-11
              (fp16 mantissa) — tighter than bf16's 2^-8 — and, unlike
              an arithmetic reduce (which XLA upcasts BEFORE the
              transfer, see BENCH.md round-5 methodology note), gather
              semantics keep the narrow dtype ON the wire.
* For ZeRO stage >= 2 the bucket reduction lowers to `psum_scatter`
  (reduce-scatter): each dp rank materializes only the bucket shards its
  optimizer partition owns; the post-step parameter all-gather rides
  XLA's sharding propagation exactly as before (zero/partition.py).
* With a HIERARCHICAL data axis (comm/mesh.py `data_outer`/`data_inner`
  sub-axes; the ZeRO++ two-level recipe, arXiv:2306.10209) each bucket
  lowers per level: `psum_scatter` over `data_inner` (fast fabric, full
  bucket) -> inter-group collective over `data_outer` on the 1/inner
  shard only (slow fabric — each level selects its own wire mode, so
  this hop can ride bf16, the 24-bit split gather, or the blockwise
  int8/int4 quantized gather while the fast hop stays exact) ->
  `all_gather` over `data_inner` back to the full bucket.  Slow-fabric
  bytes drop by the inner-group factor vs the flat wire.  Under
  ZeRO >= 2 the final gather is skipped entirely: buckets leave sharded
  over `data_inner`, which is exactly where the hpZ-style secondary
  optimizer partitions live (zero/partition.py places shards on
  `data_inner` only), so the post-step parameter all-gather is
  intra-group and the inter-group cost is just the scatter already
  paid.
* The "int8" / "int4" wires are qgZ's compression half (comm/quant.py):
  each rank blockwise-quantizes its contribution ONCE (per-block fp16
  scales ride the wire alongside the payload), the narrow bytes
  all-gather, and every rank dequantizes to fp32 and sums locally — the
  reduction always happens in the wide accumulator, so quantization
  error never compounds across ranks.  Like "split" they are
  gather-structured (a psum cannot carry scales), so they cannot run
  the intra-group scatter level; placed on the OUTER hop they are
  priced per outer group, exactly where the Frontier-class
  low-bandwidth-partitioning recipe wants the hardest compression.

Every traced collective records its payload into the monitor COUNTERS
(`bucket.*`, traced-occurrence semantics like `dist.*`); the engine adds
per-dispatch `grad_wire.reduce` counts from `wire_bytes_per_reduction` /
`collectives_per_reduction` so byte accounting is auditable per step
(tests/test_grad_bucketing.py pins the two against each other).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.mesh import DATA_AXIS
from .quant import (DEFAULT_BLOCK_SIZE, QUANT_WIRES, payload_bytes,
                    validate_block_size)

WIRE_MODES = ("fp32", "bf16", "split", "int8", "int4")

# wires that ride all-gather semantics (narrow dtypes + sideband data
# stay ON the wire; an arithmetic reduce would upcast before the
# transfer and, for the quantized wires, has no way to carry scales)
GATHER_WIRES = ("split",) + QUANT_WIRES

# bytes per element actually handed to the collective, per fixed-width
# wire mode (the quantized wires price via quant.payload_bytes — their
# per-element cost depends on the block size)
_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "split": 3}  # fp16 m + int8 e


def wire_nbytes(n_elems: int, wire: str, block: int, *,
                padded: bool = True) -> int:
    """Exact per-rank wire bytes for `n_elems` elements in `wire` mode.
    `padded=False` prices the logical payload (no block-padding
    overhead) for the `*_logical` counters; fixed-width wires have no
    block padding, so both views agree there."""
    if wire in QUANT_WIRES:
        return payload_bytes(n_elems, wire, block, padded=padded)
    return n_elems * _WIRE_ITEMSIZE[wire]


def _record(op: str, nbytes: int) -> None:
    """Traced-occurrence counter (once per compiled program, like the
    `dist.*` wrappers) — never raises into a trace."""
    try:
        from ...monitor.counters import COUNTERS

        COUNTERS.add(f"bucket.{op}", nbytes)
    except Exception:
        pass


class WireLevel(NamedTuple):
    """One level of a hierarchical reduction: the mesh axis it rides,
    the group size, and the wire mode its payload crosses the fabric
    in."""

    axis: str             # mesh axis name ("data_inner" / "data_outer")
    size: int             # group size along that axis
    wire: str             # "fp32" | "bf16" | "split" (outer level only)


class LeafSlot(NamedTuple):
    """Where one gradient leaf lives inside its bucket."""

    leaf_id: int          # index in tree_flatten order
    offset: int           # element offset into the flat bucket
    size: int             # element count
    shape: Tuple[int, ...]


class BucketSpec(NamedTuple):
    dtype: Any            # numpy dtype of the leaves in this bucket
    slots: Tuple[LeafSlot, ...]
    n_elems: int          # payload elements (sum of slot sizes)
    padded: int           # n_elems rounded up for reduce-scatter


class BucketPlan:
    """Static flat-bucket layout + the in-jit reduce that consumes it.

    Built once from the gradient tree STRUCTURE (shapes/dtypes — arrays
    or ShapeDtypeStructs both work); all methods taking gradient values
    are pure and trace-safe.
    """

    def __init__(self, grad_tree, *, dp_size: int, axis: str = DATA_AXIS,
                 bucket_elems: int, wire: str = "fp32",
                 scatter: bool = False,
                 levels: Optional[Tuple[WireLevel, WireLevel]] = None,
                 quant_block: int = DEFAULT_BLOCK_SIZE):
        if wire not in WIRE_MODES:
            raise ValueError(
                f"unknown wire mode {wire!r}; choose from {WIRE_MODES}")
        if bucket_elems <= 0:
            raise ValueError(f"reduce_bucket_size must be > 0, "
                             f"got {bucket_elems}")
        if levels is not None:
            inner, outer = levels[0], levels[1]
            for name, lvl in (("inner", inner), ("outer", outer)):
                if lvl.wire not in WIRE_MODES:
                    raise ValueError(
                        f"unknown {name}-level wire mode {lvl.wire!r}; "
                        f"choose from {WIRE_MODES}")
            if inner.size * outer.size != int(dp_size):
                raise ValueError(
                    f"hierarchy levels {outer.size} x {inner.size} do not "
                    f"factor the data-parallel size {dp_size}")
            if inner.size <= 1 or outer.size <= 1:
                raise ValueError(
                    f"hierarchy levels must both be > 1 (got outer="
                    f"{outer.size}, inner={inner.size}); use a flat plan "
                    "for a single-level reduction")
            if inner.wire in GATHER_WIRES:
                # gather-structured: an intra-level gather wire would
                # re-materialize the full bucket on every rank and hand
                # the OUTER hop full-width payloads — the hierarchy's
                # whole point inverted (and a psum_scatter has no way to
                # carry the quantized wires' per-block scales).  Config
                # sanitizes an inherited request to fp32; direct
                # constructions must not slip through.
                raise ValueError(
                    f"the {inner.wire} wire is gather-structured and "
                    "cannot run the intra-group scatter level; use fp32 "
                    "or bf16 for the inner wire")
            self.levels: Optional[Tuple[WireLevel, WireLevel]] = \
                (inner, outer)
        else:
            self.levels = None
        if scatter and wire in GATHER_WIRES and levels is None:
            # gather wires re-materialize the full bucket on every rank
            # anyway, so a scattered lowering buys nothing.  Callers
            # (engine._build_bucket_plan) log the fallback.
            scatter = False
        self.axis = axis
        self.dp_size = int(dp_size)
        self.wire = wire
        self.scatter = bool(scatter)
        self.bucket_elems = int(bucket_elems)
        self.quant_block = validate_block_size(quant_block)

        leaves, self.treedef = jax.tree_util.tree_flatten(grad_tree)
        self._leaf_shapes = [tuple(l.shape) for l in leaves]
        self._leaf_dtypes = [np.dtype(l.dtype) for l in leaves]

        self.buckets: List[BucketSpec] = []
        open_by_dtype = {}  # dtype -> (slots, fill)
        for lid, leaf in enumerate(leaves):
            shape = tuple(leaf.shape)
            size = int(np.prod(shape or (1,), dtype=np.int64))
            dt = np.dtype(leaf.dtype)
            slots, fill = open_by_dtype.get(dt, ([], 0))
            if slots and fill + size > self.bucket_elems:
                self._close(dt, slots, fill)
                slots, fill = [], 0
            slots.append(LeafSlot(lid, fill, size, shape))
            fill += size
            open_by_dtype[dt] = (slots, fill)
            if fill >= self.bucket_elems:
                self._close(dt, slots, fill)
                open_by_dtype[dt] = ([], 0)
        for dt, (slots, fill) in open_by_dtype.items():
            if slots:
                self._close(dt, slots, fill)

        # wire accounting, fixed at plan-build time.  For hierarchical
        # plans the intra/inter split is the headline number: inter
        # (slow-fabric) bytes are the 1/inner-size shard per bucket.
        # Each figure also gets a *_logical twin pricing the same wire
        # with zero padding overhead — bucket padding to inner/block
        # multiples otherwise inflates the byte counters and masks part
        # of a compression win in BENCH comparisons.
        blk = self.quant_block
        if self.levels is not None:
            inner, outer = self.levels
            # dense: scatter + gather legs on the fast fabric; ZeRO>=2
            # keeps buckets scattered — the gather leg never runs
            intra_legs = 1 if self.scatter else 2
            self.wire_bytes_intra_per_reduction = sum(
                wire_nbytes(b.padded, inner.wire, blk) * intra_legs
                for b in self.buckets)
            self.wire_bytes_intra_logical_per_reduction = sum(
                wire_nbytes(b.n_elems, inner.wire, blk, padded=False)
                * intra_legs for b in self.buckets)
            self.collectives_intra_per_reduction = (
                intra_legs * len(self.buckets))
            self.wire_bytes_inter_per_reduction = sum(
                wire_nbytes(b.padded // inner.size, outer.wire, blk)
                for b in self.buckets)
            self.wire_bytes_inter_logical_per_reduction = sum(
                wire_nbytes(-(-b.n_elems // inner.size), outer.wire, blk,
                            padded=False) for b in self.buckets)
            # split ships mantissa + exponent as TWO gathers; the
            # quantized wires fuse payload + scales into ONE buffer
            self.collectives_inter_per_reduction = (
                (2 if outer.wire == "split" else 1) * len(self.buckets))
            self.wire_bytes_per_reduction = (
                self.wire_bytes_intra_per_reduction
                + self.wire_bytes_inter_per_reduction)
            self.wire_bytes_logical_per_reduction = (
                self.wire_bytes_intra_logical_per_reduction
                + self.wire_bytes_inter_logical_per_reduction)
            self.collectives_per_reduction = (
                self.collectives_intra_per_reduction
                + self.collectives_inter_per_reduction)
        else:
            self.wire_bytes_per_reduction = sum(
                wire_nbytes(b.padded, self.wire, blk)
                for b in self.buckets)
            self.wire_bytes_logical_per_reduction = sum(
                wire_nbytes(b.n_elems, self.wire, blk, padded=False)
                for b in self.buckets)
            self.collectives_per_reduction = (
                (2 if self.wire == "split" else 1) * len(self.buckets))
            self.wire_bytes_intra_per_reduction = 0
            self.wire_bytes_inter_per_reduction = 0
            self.wire_bytes_intra_logical_per_reduction = 0
            self.wire_bytes_inter_logical_per_reduction = 0
            self.collectives_intra_per_reduction = 0
            self.collectives_inter_per_reduction = 0

    def _close(self, dtype, slots, fill):
        # scatter shards over the (inner) axis; hierarchical plans also
        # psum_scatter dense buckets over the inner group — both need
        # the bucket length to divide evenly
        chunks = 1
        if self.levels is not None:
            chunks = self.levels[0].size
        elif self.scatter:
            chunks = self.dp_size
        pad = -fill % chunks if chunks > 1 else 0
        self.buckets.append(BucketSpec(dtype, tuple(slots), fill,
                                       fill + pad))

    # -- in-jit layout ops --------------------------------------------

    def flatten(self, grads) -> List[jnp.ndarray]:
        """Gradient tree -> list of flat buckets (zero-padded for the
        reduce-scatter lowering)."""
        leaves = jax.tree_util.tree_leaves(grads)
        out = []
        for b in self.buckets:
            parts = [leaves[s.leaf_id].reshape(-1) for s in b.slots]
            if b.padded > b.n_elems:
                parts.append(jnp.zeros((b.padded - b.n_elems,), b.dtype))
            out.append(jnp.concatenate(parts)
                       if len(parts) > 1 else parts[0])
        return out

    def unflatten(self, buckets) -> Any:
        """List of flat (reduced) buckets -> gradient tree."""
        leaves: List[Optional[jnp.ndarray]] = [None] * len(self._leaf_shapes)
        for b, flat in zip(self.buckets, buckets):
            for s in b.slots:
                leaves[s.leaf_id] = lax.slice(
                    flat, (s.offset,), (s.offset + s.size,)).reshape(s.shape)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- in-jit reduction (call inside shard_map over self.axis) ------

    def reduce(self, buckets) -> List[jnp.ndarray]:
        """Mean-reduce each flat bucket over the data axis: ONE collective
        per bucket (two for the split wire).  Must run in a manual-mesh
        region (shard_map) with `self.axis` (or, hierarchical, both level
        axes) bound."""
        if self.levels is not None:
            return [self._reduce_one_hier(flat, b) for flat, b in
                    zip(buckets, self.buckets)]
        return [self._reduce_one(flat, b) for flat, b in
                zip(buckets, self.buckets)]

    @staticmethod
    def _split_gather_sum(x, n_elems: int, axis: str, prefix: str):
        """The 24-bit frexp wire, shared by the flat split mode and the
        hierarchical outer hop: fp16 mantissa + int8 exponent of `x`
        all-gather over `axis`, ldexp-reconstruct and sum locally in
        fp32.  Gather semantics keep the narrow dtypes ON the wire — an
        arithmetic reduce upcasts before the transfer (BENCH.md round-5
        methodology note)."""
        from .compressed_ar import decompose_int8_safe

        mantissa, exponent = decompose_int8_safe(x)
        _record(f"{prefix}all_gather", n_elems * 2)
        m_all = lax.all_gather(mantissa, axis, axis=0, tiled=False)
        _record(f"{prefix}all_gather", n_elems * 1)
        e_all = lax.all_gather(exponent.astype(jnp.int8), axis,
                               axis=0, tiled=False)
        return jnp.sum(jnp.ldexp(m_all.astype(jnp.float32),
                                 e_all.astype(jnp.int32)), axis=0)

    def _quant_gather_sum(self, x, wire: str, axis: str, prefix: str):
        """The blockwise-quantized gather wire (qgZ compression half,
        comm/quant.py): int8/int4 payload + per-block fp16 scales fused
        into ONE uint8 buffer (pack_wire) and all-gathered over `axis`;
        every rank dequantizes each peer's contribution to fp32 and
        sums LOCALLY — accumulate always in the wide domain, quantize
        only for the wire, so the error never compounds across ranks.
        One buffer matters: on latency-bound fabrics a separate scales
        collective would cost a second round-trip and hand the latency
        win right back (BENCH.md round-11 methodology note)."""
        from .quant import quantized_all_gather

        per_rank = quantized_all_gather(
            x, (axis,), self.quant_block, wire,
            record=lambda nb: _record(f"{prefix}all_gather", nb))
        return jnp.sum(per_rank, axis=0)

    def _reduce_one_hier(self, flat, spec: BucketSpec):
        """Two-level lowering: intra-group reduce-scatter (full bucket,
        fast fabric) -> inter-group collective on the 1/inner shard
        (slow fabric, its own wire mode) -> intra-group all-gather
        (skipped under ZeRO>=2: the bucket leaves sharded over the inner
        axis, where the hpZ optimizer partitions live)."""
        inner, outer = self.levels
        isz_in = _WIRE_ITEMSIZE[inner.wire]
        shard_elems = spec.padded // inner.size

        wired = flat.astype(jnp.bfloat16 if inner.wire == "bf16"
                            else jnp.float32)
        _record("intra.psum_scatter", spec.padded * isz_in)
        shard = lax.psum_scatter(wired, inner.axis, scatter_dimension=0,
                                 tiled=True).astype(jnp.float32)

        if outer.wire == "split":
            # the 24-bit frexp gather on the SLOW hop only — priced per
            # outer group, not per rank
            shard = self._split_gather_sum(shard, shard_elems,
                                           outer.axis, "inter.")
        elif outer.wire in QUANT_WIRES:
            # blockwise int8/int4 + fp16 scales on the slow hop only:
            # the qgZ placement — compression hardest on the slowest
            # fabric, fp32 accumulation everywhere
            shard = self._quant_gather_sum(shard, outer.wire, outer.axis,
                                           "inter.")
        elif outer.wire == "bf16":
            _record("inter.psum", shard_elems * 2)
            shard = lax.psum(shard.astype(jnp.bfloat16),
                             outer.axis).astype(jnp.float32)
        else:
            _record("inter.psum", shard_elems * 4)
            shard = lax.psum(shard, outer.axis)
        shard = shard / self.dp_size

        if self.scatter:
            return shard.astype(flat.dtype)
        gathered = shard.astype(jnp.bfloat16) if inner.wire == "bf16" \
            else shard
        _record("intra.all_gather", spec.padded * isz_in)
        out = lax.all_gather(gathered, inner.axis, axis=0, tiled=True)
        return out.astype(flat.dtype)

    def _reduce_one(self, flat, spec: BucketSpec):
        axis, dp = self.axis, self.dp_size
        nbytes = wire_nbytes(spec.padded, self.wire, self.quant_block)
        if self.wire == "bf16":
            wired = flat.astype(jnp.bfloat16)
            if self.scatter:
                _record("psum_scatter", nbytes)
                red = lax.psum_scatter(wired, axis, scatter_dimension=0,
                                       tiled=True)
            else:
                _record("psum", nbytes)
                red = lax.psum(wired, axis)
            return red.astype(flat.dtype) / dp
        if self.wire == "split":
            # 24-bit gather wire (compressed_ar.decompose_int8_safe —
            # subnormals flushed, the >= 2^127 tail pushed to inf so
            # overflow checks fire; the int8 exponent never wraps)
            total = self._split_gather_sum(flat, spec.padded, axis, "")
            return (total / dp).astype(flat.dtype)
        if self.wire in QUANT_WIRES:
            # blockwise-quantized gather wire (comm/quant.py: subnormal
            # flush + non-finite marker codes so overflow checks fire)
            total = self._quant_gather_sum(flat, self.wire, axis, "")
            return (total / dp).astype(flat.dtype)
        # fp32-accumulate (allreduce_always_fp32 semantics)
        wired = flat.astype(jnp.float32)
        if self.scatter:
            _record("psum_scatter", nbytes)
            red = lax.psum_scatter(wired, axis, scatter_dimension=0,
                                   tiled=True)
        else:
            _record("psum", nbytes)
            red = lax.psum(wired, axis)
        return (red / dp).astype(flat.dtype)

    # -- overlap lowering (runtime/comm/overlap.py host exchange) -----
    #
    # The overlapped wire splits each bucket's reduction in two at the
    # point where bytes would cross the slow fabric: `overlap_encode`
    # runs in the GRADS program (after the hierarchical plan's
    # intra-group psum_scatter — the fast-fabric leg stays an XLA
    # collective) and emits this rank's wire payload as one flat uint8
    # buffer; the host exchange moves every rank's buffer while the
    # device runs the next micro-step's program; `overlap_combine` runs
    # in the COMBINE program over the gathered [world, nbytes] matrix
    # and reduces with EXPRESSIONS BIT-IDENTICAL to the serial path's:
    # an explicit rank-ordered linear fold where the serial wire rides
    # psum/psum_scatter (XLA:CPU lowers both to exactly that ordered
    # sum — pinned by tests), and the gather wires' own jnp.sum
    # accumulation where the serial wire is gather-structured.  Losses
    # and params under overlap are bitwise those of the serial wire.

    def _encode_elems(self, spec: BucketSpec) -> int:
        """Elements one rank contributes to the exchange for `spec`:
        the full padded bucket on a flat plan, the 1/inner-size shard
        after the intra-group scatter on a hierarchical one."""
        if self.levels is not None:
            return spec.padded // self.levels[0].size
        return spec.padded

    def _overlap_wire(self) -> str:
        """The wire mode whose payload crosses the host exchange: the
        outer level's on hierarchical plans, the single wire flat."""
        return self.levels[1].wire if self.levels is not None else self.wire

    @property
    def overlap_layout(self):
        """[(offset, nbytes, elems)] of each bucket inside the fused
        per-rank exchange buffer + the buffer's total size."""
        wire = self._overlap_wire()
        layout, off = [], 0
        for b in self.buckets:
            elems = self._encode_elems(b)
            nb = wire_nbytes(elems, wire, self.quant_block)
            layout.append((off, nb, elems))
            off += nb
        return layout, off

    def _encode_one(self, x, wire: str):
        """fp32 values -> this rank's uint8 wire bytes for one bucket
        (sized exactly `wire_nbytes(x.size, wire, quant_block)`)."""
        if wire == "fp32":
            return lax.bitcast_convert_type(
                x.astype(jnp.float32), jnp.uint8).reshape(-1)
        if wire == "bf16":
            return lax.bitcast_convert_type(
                x.astype(jnp.bfloat16), jnp.uint8).reshape(-1)
        if wire == "split":
            from .compressed_ar import decompose_int8_safe

            m, e = decompose_int8_safe(x)
            return jnp.concatenate([
                lax.bitcast_convert_type(m, jnp.uint8).reshape(-1),
                lax.bitcast_convert_type(e.astype(jnp.int8),
                                         jnp.uint8).reshape(-1)])
        from .quant import pack_wire, quantize_blockwise

        payload, scales = quantize_blockwise(x, self.quant_block, wire)
        return pack_wire(payload, scales)

    def overlap_encode(self, buckets) -> jnp.ndarray:
        """Flat local-grad buckets -> ONE fused uint8 exchange buffer
        for this rank.  Must run inside the grads program's shard_map
        region: hierarchical plans run the intra-group psum_scatter
        here (the fast-fabric leg — identical op to the serial path's),
        so only the 1/inner shard rides the host exchange."""
        wire = self._overlap_wire()
        parts = []
        for flat, spec in zip(buckets, self.buckets):
            x = flat
            if self.levels is not None:
                inner = self.levels[0]
                isz_in = _WIRE_ITEMSIZE[inner.wire]
                wired = flat.astype(jnp.bfloat16 if inner.wire == "bf16"
                                    else jnp.float32)
                _record("intra.psum_scatter", spec.padded * isz_in)
                x = lax.psum_scatter(wired, inner.axis,
                                     scatter_dimension=0,
                                     tiled=True).astype(jnp.float32)
            parts.append(self._encode_one(x, wire))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def overlap_encode_out_spec(self):
        """Out spec stacking each rank's exchange buffer rank-major:
        (outer, inner) on hierarchical meshes, the data axis flat."""
        if self.levels is not None:
            return P((self.levels[1].axis, self.levels[0].axis))
        return P(self.axis)

    @staticmethod
    def _decode_rows(rows, wire: str, elems: int, block: int):
        """[world, nbytes] uint8 -> per-rank fp32/narrow values, shaped
        [world, elems] (bf16 rows stay bf16 so the fold accumulates at
        the same width the serial psum did)."""
        if wire == "fp32":
            return lax.bitcast_convert_type(
                rows.reshape(rows.shape[0], elems, 4), jnp.float32)
        if wire == "bf16":
            return lax.bitcast_convert_type(
                rows.reshape(rows.shape[0], elems, 2), jnp.bfloat16)
        raise ValueError(wire)  # split/quant decode inline in combine

    @staticmethod
    def _fold(vals):
        """Rank-ordered linear sum over the leading world dim — the
        association XLA:CPU's psum/psum_scatter lowers to (pinned by
        tests/test_step_overlap.py), NOT jnp.sum's pairwise tree."""
        acc = vals[0]
        for r in range(1, vals.shape[0]):
            acc = acc + vals[r]
        return acc

    def _combine_one(self, rows, spec: BucketSpec, dtype):
        """One bucket's gathered [world, nbytes] rows -> the reduced
        bucket (or this rank's shard under a scattered lowering),
        mirroring `_reduce_one` / `_reduce_one_hier` expression for
        expression."""
        elems = self._encode_elems(spec)
        wire = self._overlap_wire()
        blk = self.quant_block

        if self.levels is not None:
            inner, outer = self.levels
            # this rank consumes its outer peers' shards at its own
            # inner index (rank-major rows: rank = o * inner + i)
            i = lax.axis_index(inner.axis)
            rows = jnp.take(rows, jnp.arange(outer.size) * inner.size + i,
                            axis=0)

        if wire == "split":
            m = lax.bitcast_convert_type(
                rows[:, :elems * 2].reshape(rows.shape[0], elems, 2),
                jnp.float16)
            e = lax.bitcast_convert_type(
                rows[:, elems * 2:].reshape(rows.shape[0], elems, 1),
                jnp.int8).reshape(rows.shape[0], elems)
            total = jnp.sum(jnp.ldexp(m.astype(jnp.float32),
                                      e.astype(jnp.int32)), axis=0)
        elif wire in QUANT_WIRES:
            from .quant import unpack_wire, dequantize_blockwise

            p, s = unpack_wire(rows, wire, blk, elems)
            total = jnp.sum(dequantize_blockwise(p, s, wire, elems),
                            axis=0)
        else:
            vals = self._decode_rows(rows, wire, elems, blk)
            if wire == "bf16":
                # XLA's bf16 psum/psum_scatter accumulate at f32 width
                # and round the RESULT to bf16 (pinned by
                # tests/test_step_overlap.py) — mirror exactly
                vals = vals.astype(jnp.float32)
            if self.scatter and self.levels is None:
                chunk = spec.padded // self.dp_size
                r = lax.axis_index(self.axis)
                vals = lax.dynamic_slice_in_dim(vals, r * chunk, chunk,
                                                axis=1)
            total = self._fold(vals)
            if wire == "bf16":
                total = total.astype(jnp.bfloat16)
            if self.levels is None:
                # flat psum parity: bf16 casts the (rounded) result up
                # then divides (serial: psum(bf16).astype(f32)/dp);
                # fp32 divides first then casts
                if wire == "bf16":
                    return total.astype(dtype) / self.dp_size
                return (total.astype(jnp.float32) / self.dp_size
                        ).astype(dtype)

        if self.levels is None:
            return (total / self.dp_size).astype(dtype)

        # hierarchical tail: mirror _reduce_one_hier after the outer hop
        inner, outer = self.levels
        shard = total.astype(jnp.float32) / self.dp_size
        if self.scatter:
            return shard.astype(dtype)
        gathered = shard.astype(jnp.bfloat16) if inner.wire == "bf16" \
            else shard
        isz_in = _WIRE_ITEMSIZE[inner.wire]
        _record("intra.all_gather", spec.padded * isz_in)
        out = lax.all_gather(gathered, inner.axis, axis=0, tiled=True)
        return out.astype(dtype)

    def overlap_combine(self, matrix) -> List[jnp.ndarray]:
        """Gathered [world, total_nbytes] exchange matrix -> reduced
        buckets.  Must run inside the combine program's shard_map
        region (same axis names as the grads program)."""
        layout, _total = self.overlap_layout
        out = []
        for (off, nb, _elems), spec in zip(layout, self.buckets):
            rows = lax.slice(matrix, (0, off),
                             (matrix.shape[0], off + nb))
            out.append(self._combine_one(rows, spec, jnp.float32))
        return out

    # -- shard_map plumbing -------------------------------------------

    def bucket_out_specs(self):
        """Out specs for the reduced buckets: scattered buckets leave the
        manual region sharded over the data axis (each rank holds only
        its shard — the ZeRO-2 wire contract), full reductions leave
        replicated.  Hierarchical scattered buckets are sharded over the
        INNER axis only (replicated across outer groups): exactly the
        hpZ secondary-shard placement zero/partition.py gives the
        optimizer state, so the post-step gather stays intra-group."""
        if self.scatter:
            spec = P(self.levels[0].axis if self.levels is not None
                     else self.axis)
        else:
            spec = P()
        return [spec for _ in self.buckets]

    # -- introspection ------------------------------------------------

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def n_leaves(self) -> int:
        return len(self._leaf_shapes)

    @property
    def total_elems(self) -> int:
        return sum(b.n_elems for b in self.buckets)

    @property
    def hierarchical(self) -> bool:
        return self.levels is not None

    @property
    def exact_fp32(self) -> bool:
        """True when every hop accumulates at full fp32 width — the
        `allreduce_always_fp32` contract the engine reports."""
        if self.levels is not None:
            return all(lvl.wire == "fp32" for lvl in self.levels)
        return self.wire == "fp32"

    @property
    def quantized(self) -> bool:
        """True when any hop rides a blockwise-quantized wire."""
        if self.levels is not None:
            return any(lvl.wire in QUANT_WIRES for lvl in self.levels)
        return self.wire in QUANT_WIRES

    def describe(self) -> str:
        sizes = ", ".join(f"{b.n_elems}" + (f"+{b.padded - b.n_elems}pad"
                                            if b.padded > b.n_elems else "")
                          for b in self.buckets)
        lowering = "reduce-scatter" if self.scatter else "allreduce"
        if self.quantized:
            lowering += f", quant block={self.quant_block}"
        if self.levels is not None:
            inner, outer = self.levels
            return (f"BucketPlan: {self.n_leaves} grad leaves -> "
                    f"{self.n_buckets} bucket(s) [{sizes}] elems, "
                    f"hierarchical ({lowering}): intra {inner.axis}="
                    f"{inner.size} wire={inner.wire} "
                    f"({self.wire_bytes_intra_per_reduction} B / "
                    f"{self.collectives_intra_per_reduction} coll), "
                    f"inter {outer.axis}={outer.size} wire={outer.wire} "
                    f"({self.wire_bytes_inter_per_reduction} B / "
                    f"{self.collectives_inter_per_reduction} coll) "
                    f"per reduction over dp={self.dp_size}")
        return (f"BucketPlan: {self.n_leaves} grad leaves -> "
                f"{self.n_buckets} bucket(s) [{sizes}] elems, "
                f"wire={self.wire} ({lowering}), "
                f"{self.wire_bytes_per_reduction} wire bytes / "
                f"{self.collectives_per_reduction} collective(s) per "
                f"reduction over dp={self.dp_size}")
