"""Bucketed gradient-reduction wire for the dense data-parallel path.

Round-5 measured the dense DP step at 270 ms on the 2-process TCP fabric
vs 53 ms for the onebit `sign` wire carrying the SAME bytes: the gap is
~40 per-leaf collectives (XLA's implicit psum at the loss-mean boundary)
vs one fused buffer, and per-collective latency dominates on
serialization-bound fabrics.  This module is the reference's bucketing
recipe (stage2.py:614-745 flatten/reduce machinery, ZeRO §5 of
1910.02054) rebuilt as a STATIC plan the jitted step consumes:

* `BucketPlan` is computed ONCE at `initialize()` from the gradient tree
  — dtype-segregated, size-capped flat buckets (honoring the config's
  `reduce_bucket_size`, in elements like the reference) with precomputed
  per-leaf offsets.  No per-step Python walks the tree to decide layout.
* Inside the jitted step (under `shard_map` over the `data` axis) the
  local gradients concatenate into the plan's buckets and ride ONE
  collective per bucket instead of one per leaf.
* Wire modes select what crosses the fabric:
    - "fp32"  psum of the fp32 bucket (the `fp32_allreduce` /
              `allreduce_always_fp32` behaviour; default).
    - "bf16"  bucket cast to bf16 before the psum — half the bytes,
              ~8-bit mantissa accumulation (XLA sums bf16 natively).
    - "split" the EleutherAI 24-bit frexp wire (compressed_ar.py) riding
              GATHER semantics: each rank's bucket decomposes into an
              fp16 mantissa + int8 exponent (3 bytes/elem), both
              all-gathered, then ldexp-reconstructed in fp32 and summed
              locally.  Per-contribution relative error is ≤ 2^-11
              (fp16 mantissa) — tighter than bf16's 2^-8 — and, unlike
              an arithmetic reduce (which XLA upcasts BEFORE the
              transfer, see BENCH.md round-5 methodology note), gather
              semantics keep the narrow dtype ON the wire.
* For ZeRO stage >= 2 the bucket reduction lowers to `psum_scatter`
  (reduce-scatter): each dp rank materializes only the bucket shards its
  optimizer partition owns; the post-step parameter all-gather rides
  XLA's sharding propagation exactly as before (zero/partition.py).

Every traced collective records its payload into the monitor COUNTERS
(`bucket.*`, traced-occurrence semantics like `dist.*`); the engine adds
per-dispatch `grad_wire.reduce` counts from `wire_bytes_per_reduction` /
`collectives_per_reduction` so byte accounting is auditable per step
(tests/test_grad_bucketing.py pins the two against each other).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.mesh import DATA_AXIS

WIRE_MODES = ("fp32", "bf16", "split")

# bytes per element actually handed to the collective, per wire mode
_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "split": 3}  # fp16 m + int8 e


def _record(op: str, nbytes: int) -> None:
    """Traced-occurrence counter (once per compiled program, like the
    `dist.*` wrappers) — never raises into a trace."""
    try:
        from ...monitor.counters import COUNTERS

        COUNTERS.add(f"bucket.{op}", nbytes)
    except Exception:
        pass


class LeafSlot(NamedTuple):
    """Where one gradient leaf lives inside its bucket."""

    leaf_id: int          # index in tree_flatten order
    offset: int           # element offset into the flat bucket
    size: int             # element count
    shape: Tuple[int, ...]


class BucketSpec(NamedTuple):
    dtype: Any            # numpy dtype of the leaves in this bucket
    slots: Tuple[LeafSlot, ...]
    n_elems: int          # payload elements (sum of slot sizes)
    padded: int           # n_elems rounded up for reduce-scatter


class BucketPlan:
    """Static flat-bucket layout + the in-jit reduce that consumes it.

    Built once from the gradient tree STRUCTURE (shapes/dtypes — arrays
    or ShapeDtypeStructs both work); all methods taking gradient values
    are pure and trace-safe.
    """

    def __init__(self, grad_tree, *, dp_size: int, axis: str = DATA_AXIS,
                 bucket_elems: int, wire: str = "fp32",
                 scatter: bool = False):
        if wire not in WIRE_MODES:
            raise ValueError(
                f"unknown wire mode {wire!r}; choose from {WIRE_MODES}")
        if bucket_elems <= 0:
            raise ValueError(f"reduce_bucket_size must be > 0, "
                             f"got {bucket_elems}")
        if scatter and wire == "split":
            # the split wire is gather-structured; a scattered gather
            # would re-materialize the full bucket anyway.  Callers
            # (engine._build_bucket_plan) log the fallback.
            scatter = False
        self.axis = axis
        self.dp_size = int(dp_size)
        self.wire = wire
        self.scatter = bool(scatter)
        self.bucket_elems = int(bucket_elems)

        leaves, self.treedef = jax.tree_util.tree_flatten(grad_tree)
        self._leaf_shapes = [tuple(l.shape) for l in leaves]
        self._leaf_dtypes = [np.dtype(l.dtype) for l in leaves]

        self.buckets: List[BucketSpec] = []
        open_by_dtype = {}  # dtype -> (slots, fill)
        for lid, leaf in enumerate(leaves):
            shape = tuple(leaf.shape)
            size = int(np.prod(shape or (1,), dtype=np.int64))
            dt = np.dtype(leaf.dtype)
            slots, fill = open_by_dtype.get(dt, ([], 0))
            if slots and fill + size > self.bucket_elems:
                self._close(dt, slots, fill)
                slots, fill = [], 0
            slots.append(LeafSlot(lid, fill, size, shape))
            fill += size
            open_by_dtype[dt] = (slots, fill)
            if fill >= self.bucket_elems:
                self._close(dt, slots, fill)
                open_by_dtype[dt] = ([], 0)
        for dt, (slots, fill) in open_by_dtype.items():
            if slots:
                self._close(dt, slots, fill)

        # wire accounting, fixed at plan-build time
        itemsize = _WIRE_ITEMSIZE[self.wire]
        self.wire_bytes_per_reduction = sum(
            b.padded * itemsize for b in self.buckets)
        self.collectives_per_reduction = (
            (2 if self.wire == "split" else 1) * len(self.buckets))

    def _close(self, dtype, slots, fill):
        pad = 0
        if self.scatter and self.dp_size > 1 and fill % self.dp_size:
            pad = self.dp_size - fill % self.dp_size
        self.buckets.append(BucketSpec(dtype, tuple(slots), fill,
                                       fill + pad))

    # -- in-jit layout ops --------------------------------------------

    def flatten(self, grads) -> List[jnp.ndarray]:
        """Gradient tree -> list of flat buckets (zero-padded for the
        reduce-scatter lowering)."""
        leaves = jax.tree_util.tree_leaves(grads)
        out = []
        for b in self.buckets:
            parts = [leaves[s.leaf_id].reshape(-1) for s in b.slots]
            if b.padded > b.n_elems:
                parts.append(jnp.zeros((b.padded - b.n_elems,), b.dtype))
            out.append(jnp.concatenate(parts)
                       if len(parts) > 1 else parts[0])
        return out

    def unflatten(self, buckets) -> Any:
        """List of flat (reduced) buckets -> gradient tree."""
        leaves: List[Optional[jnp.ndarray]] = [None] * len(self._leaf_shapes)
        for b, flat in zip(self.buckets, buckets):
            for s in b.slots:
                leaves[s.leaf_id] = lax.slice(
                    flat, (s.offset,), (s.offset + s.size,)).reshape(s.shape)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- in-jit reduction (call inside shard_map over self.axis) ------

    def reduce(self, buckets) -> List[jnp.ndarray]:
        """Mean-reduce each flat bucket over the data axis: ONE collective
        per bucket (two for the split wire).  Must run in a manual-mesh
        region (shard_map) with `self.axis` bound."""
        return [self._reduce_one(flat, b) for flat, b in
                zip(buckets, self.buckets)]

    def _reduce_one(self, flat, spec: BucketSpec):
        axis, dp = self.axis, self.dp_size
        itemsize = _WIRE_ITEMSIZE[self.wire]
        nbytes = spec.padded * itemsize
        if self.wire == "bf16":
            wired = flat.astype(jnp.bfloat16)
            if self.scatter:
                _record("psum_scatter", nbytes)
                red = lax.psum_scatter(wired, axis, scatter_dimension=0,
                                       tiled=True)
            else:
                _record("psum", nbytes)
                red = lax.psum(wired, axis)
            return red.astype(flat.dtype) / dp
        if self.wire == "split":
            # 24-bit gather wire: the frexp split
            # (compressed_ar.decompose_int8_safe — subnormals flushed,
            # the >= 2^127 tail pushed to inf so overflow checks fire;
            # the int8 exponent never wraps) rides all_gather so
            # fp16+int8 stay narrow ON the wire (an arithmetic reduce
            # upcasts before the transfer — BENCH.md round-5 methodology
            # note); reconstruction and the cross-rank sum run locally
            # in fp32.
            from .compressed_ar import decompose_int8_safe

            mantissa, exponent = decompose_int8_safe(flat)
            _record("all_gather", spec.padded * 2)
            m_all = lax.all_gather(mantissa, axis, axis=0, tiled=False)
            _record("all_gather", spec.padded * 1)
            e_all = lax.all_gather(exponent.astype(jnp.int8), axis,
                                   axis=0, tiled=False)
            contrib = jnp.ldexp(m_all.astype(jnp.float32),
                                e_all.astype(jnp.int32))
            return (jnp.sum(contrib, axis=0) / dp).astype(flat.dtype)
        # fp32-accumulate (allreduce_always_fp32 semantics)
        wired = flat.astype(jnp.float32)
        if self.scatter:
            _record("psum_scatter", nbytes)
            red = lax.psum_scatter(wired, axis, scatter_dimension=0,
                                   tiled=True)
        else:
            _record("psum", nbytes)
            red = lax.psum(wired, axis)
        return (red / dp).astype(flat.dtype)

    # -- shard_map plumbing -------------------------------------------

    def bucket_out_specs(self):
        """Out specs for the reduced buckets: scattered buckets leave the
        manual region sharded over the data axis (each rank holds only
        its shard — the ZeRO-2 wire contract), full reductions leave
        replicated."""
        spec = P(self.axis) if self.scatter else P()
        return [spec for _ in self.buckets]

    # -- introspection ------------------------------------------------

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def n_leaves(self) -> int:
        return len(self._leaf_shapes)

    @property
    def total_elems(self) -> int:
        return sum(b.n_elems for b in self.buckets)

    def describe(self) -> str:
        sizes = ", ".join(f"{b.n_elems}" + (f"+{b.padded - b.n_elems}pad"
                                            if b.padded > b.n_elems else "")
                          for b in self.buckets)
        lowering = "reduce-scatter" if self.scatter else "allreduce"
        return (f"BucketPlan: {self.n_leaves} grad leaves -> "
                f"{self.n_buckets} bucket(s) [{sizes}] elems, "
                f"wire={self.wire} ({lowering}), "
                f"{self.wire_bytes_per_reduction} wire bytes / "
                f"{self.collectives_per_reduction} collective(s) per "
                f"reduction over dp={self.dp_size}")
