from .bucketing import BucketPlan, WIRE_MODES
from .compressed import CompressedBackend, compressed_allreduce
from .compressed_ar import (compressed_all_reduce, decompose,
                            decompose_int8_safe, reconstruct)
from .hostwire import HostWire, HostWireBackend

__all__ = ["BucketPlan", "WIRE_MODES", "CompressedBackend",
           "compressed_allreduce", "compressed_all_reduce", "decompose",
           "decompose_int8_safe", "reconstruct", "HostWire",
           "HostWireBackend"]
