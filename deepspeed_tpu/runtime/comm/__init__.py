from .bucketing import BucketPlan, GATHER_WIRES, WIRE_MODES
from .compressed import CompressedBackend, compressed_allreduce
from .compressed_ar import (compressed_all_reduce, decompose,
                            decompose_int8_safe, reconstruct)
from .hostwire import HostWire, HostWireBackend
from .quant import (QUANT_WIRES, dequantize_blockwise, payload_bytes,
                    quantize_blockwise)

__all__ = ["BucketPlan", "WIRE_MODES", "GATHER_WIRES", "QUANT_WIRES",
           "CompressedBackend", "compressed_allreduce",
           "compressed_all_reduce", "decompose", "decompose_int8_safe",
           "reconstruct", "quantize_blockwise", "dequantize_blockwise",
           "payload_bytes", "HostWire", "HostWireBackend"]
