from .compressed import CompressedBackend, compressed_allreduce
from .compressed_ar import (compressed_all_reduce, decompose, reconstruct)

__all__ = ["CompressedBackend", "compressed_allreduce",
           "compressed_all_reduce", "decompose", "reconstruct"]
