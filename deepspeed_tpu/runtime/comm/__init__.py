from .compressed import CompressedBackend, compressed_allreduce
from .compressed_ar import (compressed_all_reduce, decompose, reconstruct)
from .hostwire import HostWire, HostWireBackend

__all__ = ["CompressedBackend", "compressed_allreduce",
           "compressed_all_reduce", "decompose", "reconstruct",
           "HostWire", "HostWireBackend"]
