"""Error-compensated 1-bit compressed collectives.

Reference: deepspeed/runtime/comm/nccl.py:47-186 (NcclBackend) and
mpi.py:34-290 (MpiBackend): sign-compress with worker error feedback,
all_to_all the sign bits + allgather the scales, server-side recompress
with server error feedback, allgather the result. CuPy packbits supplies
the bit-packing (runtime/compression/cupy.py).

TPU redesign: ICI is bandwidth-rich and XLA has no packed-int1 wire
format, so the same ALGORITHM (two-stage sign compression with both error
feedbacks — that is what 1-bit Adam's convergence proof needs) runs as a
pure function on mesh axes: signs travel through psum/pmean. The
`CompressedBackend` class mirrors the reference backend surface for
out-of-jit callers by shard_map-ping the pure function over the mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.mesh import peek_mesh


def compressed_allreduce(x, worker_error, server_error, axis: Optional[str]):
    """1-bit compress with error feedback, average over `axis`, recompress.

    Returns (averaged_tensor, new_worker_error, new_server_error).
    Mirrors NcclBackend.compressed_allreduce (reference comm/nccl.py:47-186):
      worker: c = x + worker_error; scale = ||c||_1/n; send sign(c)*scale
      server: s = avg + server_error; rescale and sign again
    Call inside jit/shard_map with `axis` a mesh axis name, or axis=None
    for the single-shard (no-comm) case.
    """
    c = x + worker_error
    scale = jnp.mean(jnp.abs(c))
    compressed = jnp.sign(c) * scale
    new_worker_error = c - compressed

    if axis is not None:
        avg = lax.pmean(compressed, axis)
    else:
        avg = compressed

    s = avg + server_error
    server_scale = jnp.mean(jnp.abs(s))
    out = jnp.sign(s) * server_scale
    new_server_error = s - out
    return out, new_worker_error, new_server_error


INT8_GROUP = 2048  # elements per quantization scale (reference chunking)


def _quant_grouped(t, group=INT8_GROUP):
    """t: [..., k] with k % group == 0 -> (int8 same shape, fp32 scales
    [..., k/group]). Per-group scales keep small-magnitude regions
    (layernorm/bias momentum) from quantizing to zero under a layer with
    1000x larger values — the reference's per-chunk scale behavior
    (comm/nccl.py), at ~4 bytes per `group` wire bytes."""
    g = t.reshape(*t.shape[:-1], -1, group)
    scale = jnp.max(jnp.abs(g), axis=-1) / 127.0 + 1e-12
    q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
    return q.astype(jnp.int8).reshape(t.shape), scale


def _dequant_grouped(q, scale, group=INT8_GROUP):
    g = q.astype(jnp.float32).reshape(*q.shape[:-1], -1, group)
    return (g * scale[..., None]).reshape(q.shape)


def _group_for(n: int, W: int) -> int:
    """Quantization group sized to the tensor: full INT8_GROUP for large
    buffers, shrunk for small ones so a 16-element bias doesn't pad to
    W * 2048 (a ~1000x wire blowup for per-leaf callers)."""
    k0 = -(-n // W)  # ceil(n / W): per-worker chunk before rounding
    return max(1, min(INT8_GROUP, k0))


def int8_compressed_allreduce(x, worker_error, server_error, axis):
    """Error-compensated INT8 compressed mean over `axis` — the
    TPU-native compression SURVEY §2.3 recommends in place of bit-packing:
    XLA has no packed-int1 wire format (sign compression rides pmean at
    full width, measured in BENCH.md), but int8 collectives transmit
    int8, so this genuinely cuts wire bytes ~4x vs fp32.

    Same two-stage structure as the reference's 1-bit backends
    (comm/nccl.py:47-186) with both error feedbacks:
      worker: q = round((x + we) / scale_w) int8; all_to_all chunks
      server: owner sums its chunk, adds se, requantizes; allgather
    Wire per device: ~1 byte/elem a2a + ~1 byte/elem allgather + scales
    (dense fp32 ring allreduce moves ~8 bytes/elem).

    Call inside jit/shard_map with `axis` a mesh axis name (or None for
    the single-shard no-comm case). Returns (mean, new_we, new_se)."""
    if axis is None:
        n = x.size
        G = _group_for(n, 1)
        pad = (-n) % G
        c = jnp.pad((x + worker_error).ravel(), (0, pad))
        q, sw = _quant_grouped(c, G)
        deq = _dequant_grouped(q, sw, G)
        new_we = (c - deq)[:n].reshape(x.shape)
        s = deq + jnp.pad(server_error.ravel(), (0, pad))
        q2, ss = _quant_grouped(s, G)
        out = _dequant_grouped(q2, ss, G)
        return (out[:n].reshape(x.shape), new_we,
                (s - out)[:n].reshape(server_error.shape))

    W = lax.psum(1, axis)
    n = x.size
    G = _group_for(n, W)
    pad = (-n) % (W * G)  # rows must split into whole groups
    c = jnp.pad((x + worker_error).ravel(), (0, pad)).reshape(W, -1)
    q, sw = _quant_grouped(c, G)         # q [W, k] int8, sw [W, k/G]
    new_we = ((c - _dequant_grouped(q, sw, G)).ravel()[:n]
              .reshape(x.shape))
    # phase 1 (wire: int8 + fp32/2048 scales): worker j receives chunk
    # ROW j from everyone
    recv = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                          tiled=False)                 # [W, k] int8
    rscale = lax.all_to_all(sw, axis, split_axis=0, concat_axis=0,
                            tiled=False)               # [W, k/G]
    avg = jnp.sum(_dequant_grouped(recv, rscale, G), axis=0) / W

    # server stage: per-owner error feedback on the owned chunk (the
    # state keeps the full-shape buffer for a static pytree; only the
    # owned row is meaningful on each worker, like the reference's
    # per-rank server_error slices)
    idx = lax.axis_index(axis)
    se_full = jnp.pad(server_error.ravel(), (0, pad)).reshape(W, -1)
    se_chunk = lax.dynamic_index_in_dim(se_full, idx, 0, keepdims=False)
    s = avg + se_chunk
    q2, ss = _quant_grouped(s, G)
    se_new_chunk = s - _dequant_grouped(q2, ss, G)
    new_se = jnp.zeros_like(se_full).at[idx].set(se_new_chunk)
    new_se = new_se.ravel()[:n].reshape(server_error.shape)

    # phase 2 (wire: int8 + fp32/2048 scales per owner)
    allq = lax.all_gather(q2, axis)    # [W, k] int8
    allsc = lax.all_gather(ss, axis)   # [W, k/G]
    out = _dequant_grouped(allq, allsc, G).ravel()[:n]
    return out.reshape(x.shape), new_we, new_se


class CompressedBackend:
    """Out-of-jit backend surface (reference NcclBackend/MpiBackend).

    Holds the persistent worker/server error-feedback buffers per named
    tensor (the reference attaches them to optimizer state; standalone
    callers get the same behavior keyed by `name`).
    """

    def __init__(self, axis: str = "data", mpu=None):
        self.axis = axis
        self._errors = {}
        self._fns = {}  # per-mesh compiled reduction (avoid re-tracing)

    def _get_errors(self, name, shaped_like):
        if name not in self._errors:
            zeros = jnp.zeros(shaped_like.shape, jnp.float32)
            self._errors[name] = (zeros, zeros)
        return self._errors[name]

    def compressed_allreduce(self, tensor, name: str = "default"):
        """Average `tensor`'s per-device shards over the axis with 1-bit
        compression. The input is interpreted as already sharded over
        `axis` on dim 0 (each shard is one worker's contribution)."""
        info = peek_mesh()
        if info is None or self.axis not in info.mesh.shape or \
                info.mesh.shape[self.axis] == 1:
            we, se = self._get_errors(name, tensor)
            out, we, se = compressed_allreduce(tensor, we, se, None)
            self._errors[name] = (we, se)
            return out

        mesh = info.mesh
        we, se = self._get_errors(name, tensor)

        if mesh not in self._fns:
            @partial(jax.shard_map, mesh=mesh,
                     in_specs=(P(self.axis), P(self.axis), P(self.axis)),
                     out_specs=(P(self.axis), P(self.axis), P(self.axis)),
                     check_vma=False)
            def run(x, we, se):
                return compressed_allreduce(x, we, se, self.axis)

            # jit gives shape/dtype-keyed caching: repeated reductions of
            # the same tensor compile once, not once per call
            self._fns[mesh] = jax.jit(run)

        out, we, se = self._fns[mesh](tensor, we, se)
        self._errors[name] = (we, se)
        return out
