"""Error-compensated 1-bit compressed collectives.

Reference: deepspeed/runtime/comm/nccl.py:47-186 (NcclBackend) and
mpi.py:34-290 (MpiBackend): sign-compress with worker error feedback,
all_to_all the sign bits + allgather the scales, server-side recompress
with server error feedback, allgather the result. CuPy packbits supplies
the bit-packing (runtime/compression/cupy.py).

TPU redesign: ICI is bandwidth-rich and XLA has no packed-int1 wire
format, so the same ALGORITHM (two-stage sign compression with both error
feedbacks — that is what 1-bit Adam's convergence proof needs) runs as a
pure function on mesh axes: signs travel through psum/pmean. The
`CompressedBackend` class mirrors the reference backend surface for
out-of-jit callers by shard_map-ping the pure function over the mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.mesh import peek_mesh


def compressed_allreduce(x, worker_error, server_error, axis: Optional[str]):
    """1-bit compress with error feedback, average over `axis`, recompress.

    Returns (averaged_tensor, new_worker_error, new_server_error).
    Mirrors NcclBackend.compressed_allreduce (reference comm/nccl.py:47-186):
      worker: c = x + worker_error; scale = ||c||_1/n; send sign(c)*scale
      server: s = avg + server_error; rescale and sign again
    Call inside jit/shard_map with `axis` a mesh axis name, or axis=None
    for the single-shard (no-comm) case.
    """
    c = x + worker_error
    scale = jnp.mean(jnp.abs(c))
    compressed = jnp.sign(c) * scale
    new_worker_error = c - compressed

    if axis is not None:
        avg = lax.pmean(compressed, axis)
    else:
        avg = compressed

    s = avg + server_error
    server_scale = jnp.mean(jnp.abs(s))
    out = jnp.sign(s) * server_scale
    new_server_error = s - out
    return out, new_worker_error, new_server_error


class CompressedBackend:
    """Out-of-jit backend surface (reference NcclBackend/MpiBackend).

    Holds the persistent worker/server error-feedback buffers per named
    tensor (the reference attaches them to optimizer state; standalone
    callers get the same behavior keyed by `name`).
    """

    def __init__(self, axis: str = "data", mpu=None):
        self.axis = axis
        self._errors = {}
        self._fns = {}  # per-mesh compiled reduction (avoid re-tracing)

    def _get_errors(self, name, shaped_like):
        if name not in self._errors:
            zeros = jnp.zeros(shaped_like.shape, jnp.float32)
            self._errors[name] = (zeros, zeros)
        return self._errors[name]

    def compressed_allreduce(self, tensor, name: str = "default"):
        """Average `tensor`'s per-device shards over the axis with 1-bit
        compression. The input is interpreted as already sharded over
        `axis` on dim 0 (each shard is one worker's contribution)."""
        info = peek_mesh()
        if info is None or self.axis not in info.mesh.shape or \
                info.mesh.shape[self.axis] == 1:
            we, se = self._get_errors(name, tensor)
            out, we, se = compressed_allreduce(tensor, we, se, None)
            self._errors[name] = (we, se)
            return out

        mesh = info.mesh
        we, se = self._get_errors(name, tensor)

        if mesh not in self._fns:
            @partial(jax.shard_map, mesh=mesh,
                     in_specs=(P(self.axis), P(self.axis), P(self.axis)),
                     out_specs=(P(self.axis), P(self.axis), P(self.axis)),
                     check_vma=False)
            def run(x, we, se):
                return compressed_allreduce(x, we, se, self.axis)

            # jit gives shape/dtype-keyed caching: repeated reductions of
            # the same tensor compile once, not once per call
            self._fns[mesh] = jax.jit(run)

        out, we, se = self._fns[mesh](tensor, we, se)
        self._errors[name] = (we, se)
        return out
