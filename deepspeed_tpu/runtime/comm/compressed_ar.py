"""bf16-safe split allreduce (EleutherAI addition).

Reference: deepspeed/runtime/comm/compressed_ar.py:22-48 — NCCL of that
era couldn't sum bf16 reliably, so the tensor is frexp-decomposed into an
fp16 mantissa and int8 exponent, each allreduced separately, then
ldexp-recombined ("24-bit allreduce").

TPU note: XLA psum handles bf16 natively, so this exists for config/API
parity and for hosts exchanging grads outside jit; the decomposition is
numerically faithful (frexp/ldexp roundtrip is exact for bf16 inputs).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...comm.mesh import peek_mesh


def decompose(t):
    """bf16/float -> (fp16 mantissa in [0.5, 1), int8 exponent)."""
    mantissa, exponent = jnp.frexp(t.astype(jnp.float32))
    return mantissa.astype(jnp.float16), exponent.astype(jnp.int8)


def reconstruct(mantissa, exponent, original_dtype=jnp.bfloat16):
    return jnp.ldexp(mantissa.astype(jnp.float32),
                     exponent.astype(jnp.int32)).astype(original_dtype)


def compressed_all_reduce(tensor, axis: Optional[str] = "data",
                          wire_parity: bool = False):
    """Sum `tensor`'s per-device dim-0 shards over the mesh axis.

    Default mode: fp32-accumulate psum — what the reference's
    mantissa/exponent split BUYS (bf16-safe summation), achieved directly
    because XLA collectives sum in any dtype; strictly more accurate than
    the reference's wire format.

    wire_parity=True: the reference's EXACT wire behaviour
    (compressed_ar.py:33-38) — allreduce the fp16 mantissas and int8
    exponents SEPARATELY, then ldexp-recombine. Note this is a lossy
    approximation (frexp is not linear); it exists for behavioural parity
    and A/B testing against the accurate mode.

    Single-axis meshes degrade to a local identity (sum of one shard)."""
    original_dtype = tensor.dtype
    info = peek_mesh()
    if info is None or axis is None or axis not in info.mesh.shape or \
            info.mesh.shape[axis] == 1:
        return tensor

    return _compiled_ar(info.mesh, axis, wire_parity,
                        str(original_dtype))(tensor)


@lru_cache(maxsize=64)
def _compiled_ar(mesh, axis, wire_parity, dtype_name):
    dtype = jnp.dtype(dtype_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axis),
             out_specs=P(axis), check_vma=False)
    def run(x):
        if wire_parity:
            m, e = decompose(x)
            m_sum = jax.lax.psum(m.astype(jnp.float32), axis)
            e_sum = jax.lax.psum(e.astype(jnp.int32), axis)
            return reconstruct(m_sum.astype(jnp.float16), e_sum, dtype)
        total = jax.lax.psum(x.astype(jnp.float32), axis)
        return total.astype(dtype)

    return jax.jit(run)
