"""bf16-safe split allreduce (EleutherAI addition).

Reference: deepspeed/runtime/comm/compressed_ar.py:22-48 — NCCL of that
era couldn't sum bf16 reliably, so the tensor is frexp-decomposed into an
fp16 mantissa and int8 exponent, each allreduced separately, then
ldexp-recombined ("24-bit allreduce").

TPU note: XLA psum handles bf16 natively, so this exists for config/API
parity and for hosts exchanging grads outside jit; the decomposition is
numerically faithful (frexp/ldexp roundtrip is exact for bf16 inputs).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...comm.mesh import peek_mesh


def decompose(t):
    """bf16/float -> (fp16 mantissa in [0.5, 1), int8 exponent).

    Reference-exact: the int8 cast WRAPS for fp32 frexp exponents
    outside [-128, 127] (subnormals reach -148, values >= 2^127 carry
    128), like the reference's wire did.  Callers that must reconstruct
    faithfully from the int8 exponent use decompose_int8_safe."""
    mantissa, exponent = jnp.frexp(t.astype(jnp.float32))
    return mantissa.astype(jnp.float16), exponent.astype(jnp.int8)


def decompose_int8_safe(t):
    """`decompose` with the int8 exponent range made safe for faithful
    reconstruction (the bucketed split gradient wire,
    runtime/comm/bucketing.py): fp32 subnormals flush to zero (their
    exponents would wrap to ~+108 and reconstruct as ~2^108 monsters),
    and the >= 2^127 tail pushes the mantissa to inf so downstream
    overflow checks fire instead of receiving a silently shrunk value.
    Returns (fp16 mantissa, int8-range int32 exponent)."""
    f32 = t.astype(jnp.float32)
    f32 = jnp.where(jnp.abs(f32) < jnp.float32(2.0 ** -126),
                    jnp.float32(0.0), f32)
    mantissa, exponent = jnp.frexp(f32)
    mantissa = jnp.where(exponent > 127,
                         jnp.sign(mantissa) * jnp.float32(jnp.inf),
                         mantissa)
    return (mantissa.astype(jnp.float16),
            jnp.clip(exponent, -127, 127))


def reconstruct(mantissa, exponent, original_dtype=jnp.bfloat16):
    return jnp.ldexp(mantissa.astype(jnp.float32),
                     exponent.astype(jnp.int32)).astype(original_dtype)


def compressed_all_reduce(tensor, axis: Optional[str] = "data",
                          wire_parity: bool = False):
    """Sum `tensor`'s per-device dim-0 shards over the mesh axis.

    Default mode: fp32-accumulate psum — what the reference's
    mantissa/exponent split BUYS (bf16-safe summation), achieved directly
    because XLA collectives sum in any dtype; strictly more accurate than
    the reference's wire format.

    wire_parity=True: the reference's EXACT wire behaviour
    (compressed_ar.py:33-38) — allreduce the fp16 mantissas and int8
    exponents SEPARATELY, then ldexp-recombine. Note this is a lossy
    approximation (frexp is not linear); it exists for behavioural parity
    and A/B testing against the accurate mode.

    Single-axis meshes degrade to a local identity (sum of one shard)."""
    original_dtype = tensor.dtype
    info = peek_mesh()
    if info is None or axis is None or axis not in info.mesh.shape or \
            info.mesh.shape[axis] == 1:
        return tensor

    return _compiled_ar(info.mesh, axis, wire_parity,
                        str(original_dtype))(tensor)


@lru_cache(maxsize=64)
def _compiled_ar(mesh, axis, wire_parity, dtype_name):
    dtype = jnp.dtype(dtype_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axis),
             out_specs=P(axis), check_vma=False)
    def run(x):
        if wire_parity:
            m, e = decompose(x)
            m_sum = jax.lax.psum(m.astype(jnp.float32), axis)
            e_sum = jax.lax.psum(e.astype(jnp.int32), axis)
            return reconstruct(m_sum.astype(jnp.float16), e_sum, dtype)
        total = jax.lax.psum(x.astype(jnp.float32), axis)
        return total.astype(dtype)

    return jax.jit(run)
