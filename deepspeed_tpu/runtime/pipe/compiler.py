"""Pipeline schedule compiler — flat per-rank programs for the 1F1B walk.

BENCH.md round-5 measured the interpreted canonical walk at ~300 µs of
serialized Python per schedule event (schedule-stream regeneration +
dependency re-simulation + isinstance dispatch + counter/dict/mail
bookkeeping, every train_batch), 12-16 % of step time on CPU-mesh grains
and projected ~150 ms/step at 8 stages x 16 micro batches. This module
removes the interpreter from that inner loop:

* `compile_schedule` lowers the canonical event order (the output of
  engine._simulate_order — identical on every process, the property that
  keeps the channel handoffs deadlock-free) ONCE into a flat, immutable
  program: parallel tuples of opcode / model-chunk / micro-id / buffer
  slots.  Micro ids are precomputed, so the run-time recv/send/fwd/bwd
  counters disappear entirely.

* every Send+Recv pair is FUSED into a single transfer op placed at the
  send's position.  The data transfer already happens at the send event
  in the interpreted walk (the recv is pure mail-dict bookkeeping), so
  the collective entry order across processes is unchanged — only the
  Python disappears.  Fusion is made unconditionally safe by giving the
  fused write a liveness-fresh buffer slot (below) instead of the
  schedule's recv-time slot.

* buffer slots are resolved once by liveness analysis into preallocated
  per-stage pools (plain lists — the double-buffered pool): each
  (chunk, micro) value gets a slot live from its writing event to its
  last reading event.  No dict hashing, no (mc, mb) tuple keys, no mail
  dict at run time.

* `bind_program` turns the flat program into a list of zero-argument
  closures with every static decision (stage runtime, slot indices, rng
  fold constants, transfer plans/shardings) resolved at bind time.  The
  executor loop in engine.py is then `for f in steps: f()` — it touches
  no Python objects besides the program list and the pools.  On
  multi-host ranks, events with no local role are pruned at bind time
  (the interpreted walk pays Python for every remote event).

The interpreted walk stays available as `pipeline.debug_schedule: true`
— the parity oracle (tests pin bit-identical losses) and the
reference-shaped executor for new-instruction bring-up.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

import jax

from .p2p import batch_shardable
from .schedule import (BackwardPass, ForwardPass, LoadMicroBatch,
                       OptimizerStep, RecvActivation, RecvGrad, ReduceGrads,
                       ReduceTiedGrads, SendActivation, SendGrad)

# opcodes (flat-program ISA)
OP_LOAD = 0        # (mc, mb, x_slot)
OP_FWD = 1         # (mc, mb, x_slot, y_slot)   y_slot < 0: output unused
OP_XFER_ACT = 2    # (src_mc, mb, y_slot, dst_x_slot)    fused send+recv
OP_BWD = 3         # (mc, mb, x_slot, dy_slot, dx_slot)  dy<0: last stage
OP_XFER_GRAD = 4   # (src_mc, mb, dx_slot, dst_dy_slot)  fused send+recv
OP_TIED = 5        # ()
OP_STEP = 6        # ()

OP_NAMES = {OP_LOAD: "load", OP_FWD: "fwd", OP_XFER_ACT: "xfer_act",
            OP_BWD: "bwd", OP_XFER_GRAD: "xfer_grad", OP_TIED: "tied",
            OP_STEP: "step"}


class PipeProgram:
    """Immutable lowered schedule: one entry per executed event.

    events: tuple of tuples — (op, mc, mb, a, b, c) with slot fields per
    the opcode table above (unused fields -1).  pool_sizes maps
    (mc, kind) -> required slot count, kind in {x, y, dy, dx}; the `x`
    pool also carries the forward rng (identical liveness).
    """

    __slots__ = ("events", "pool_sizes", "n_mc", "micro_batches",
                 "n_source_events")

    def __init__(self, events, pool_sizes, n_mc, micro_batches,
                 n_source_events):
        self.events = tuple(events)
        self.pool_sizes = dict(pool_sizes)
        self.n_mc = n_mc
        self.micro_batches = micro_batches
        # pre-fusion event count (for dispatch-rate accounting)
        self.n_source_events = n_source_events

    def __repr__(self):
        ops = ", ".join(OP_NAMES[e[0]] for e in self.events[:8])
        return (f"PipeProgram({len(self.events)} events from "
                f"{self.n_source_events}, n_mc={self.n_mc}, "
                f"M={self.micro_batches}, [{ops}...])")


def compile_schedule(events, mc_of: Callable[[int, Any], int], n_mc: int,
                     micro_batches: int) -> PipeProgram:
    """Lower a canonical (stage, instruction) event list to a PipeProgram.

    `events` is engine._simulate_order's output; `mc_of` maps
    (stage, cmd) to the model-chunk index (engine._mc).  Pure structural
    lowering — no engine state is touched, so the result is reusable for
    every train_batch with the same (M, stages, interleave).
    """
    # -- pass 1: assign micro ids with the same counters the interpreted
    # dispatch uses, and drop bookkeeping-only instructions --------------
    events = list(events)
    fwd_cnt = [0] * n_mc
    bwd_cnt = [0] * n_mc
    sent_act = [0] * n_mc
    sent_grad = [0] * n_mc
    recv_act = [0] * n_mc
    recv_grad = [0] * n_mc
    load_cnt = 0
    mid: List[Tuple[int, int, int]] = []   # (kind, mc, mb)
    # one OP_TIED / OP_STEP per batch, placed at the LAST canonical
    # occurrence: every stage's stream carries one of each, and only at
    # the last one (stage 0's, after the globally final backward) are all
    # gradients complete.  Emitting at the first occurrence would apply
    # the optimizer while earlier stages' cooldown backwards are still
    # accumulating — dropped gradients this step, leakage into the next.
    tied_left = sum(isinstance(c, ReduceTiedGrads) for _, c in events)
    step_left = sum(isinstance(c, OptimizerStep) for _, c in events)
    n_source = 0
    for s, cmd in events:
        n_source += 1
        mc = mc_of(s, cmd)
        if isinstance(cmd, LoadMicroBatch):
            mid.append((OP_LOAD, mc, load_cnt))
            load_cnt += 1
        elif isinstance(cmd, ForwardPass):
            mid.append((OP_FWD, mc, fwd_cnt[mc]))
            fwd_cnt[mc] += 1
        elif isinstance(cmd, SendActivation):
            mid.append((OP_XFER_ACT, mc, sent_act[mc]))
            sent_act[mc] += 1
        elif isinstance(cmd, RecvActivation):
            # fused into the matching send (the transfer happens at the
            # send position in the interpreted walk too); assert the
            # canonical order really delivered before consumption
            mb = recv_act[mc]
            recv_act[mc] += 1
            if sent_act[mc - 1] < mb + 1:
                raise AssertionError(
                    f"recv_act before send for chunk {mc} micro {mb}")
        elif isinstance(cmd, BackwardPass):
            mid.append((OP_BWD, mc, bwd_cnt[mc]))
            bwd_cnt[mc] += 1
        elif isinstance(cmd, SendGrad):
            mid.append((OP_XFER_GRAD, mc, sent_grad[mc]))
            sent_grad[mc] += 1
        elif isinstance(cmd, RecvGrad):
            mb = recv_grad[mc]
            recv_grad[mc] += 1
            if sent_grad[mc + 1] < mb + 1:
                raise AssertionError(
                    f"recv_grad before send for chunk {mc} micro {mb}")
        elif isinstance(cmd, ReduceTiedGrads):
            tied_left -= 1
            if tied_left == 0:
                mid.append((OP_TIED, -1, -1))
        elif isinstance(cmd, OptimizerStep):
            step_left -= 1
            if step_left == 0:
                mid.append((OP_STEP, -1, -1))
        elif isinstance(cmd, ReduceGrads):
            pass  # within-stage dp reduction is implicit in the jitted loss
        else:
            raise NotImplementedError(f"instruction {cmd!r}")

    # -- pass 2: find each value's last reader (liveness) ----------------
    # value keys: ("x"|"y"|"dy"|"dx", mc, mb)
    last_read: Dict[Tuple[str, int, int], int] = {}
    for i, (kind, mc, mb) in enumerate(mid):
        if kind == OP_FWD:
            last_read[("x", mc, mb)] = i          # read again by BWD below
        elif kind == OP_XFER_ACT:
            last_read[("y", mc, mb)] = i
        elif kind == OP_BWD:
            last_read[("x", mc, mb)] = i
            last_read[("dy", mc, mb)] = i
        elif kind == OP_XFER_GRAD:
            last_read[("dx", mc, mb)] = i

    # -- pass 3: slot allocation + final event emission ------------------
    free: Dict[Tuple[int, str], List[int]] = {}
    high: Dict[Tuple[int, str], int] = {}
    slot_of: Dict[Tuple[str, int, int], int] = {}

    def alloc(kind, mc, mb):
        pool = free.setdefault((mc, kind), [])
        if pool:
            s = pool.pop()
        else:
            s = high.get((mc, kind), 0)
            high[(mc, kind)] = s + 1
        slot_of[(kind, mc, mb)] = s
        return s

    def read(kind, mc, mb, i):
        s = slot_of[(kind, mc, mb)]
        if last_read.get((kind, mc, mb)) == i:
            free.setdefault((mc, kind), []).append(s)
        return s

    out: List[Tuple[int, int, int, int, int]] = []
    for i, (kind, mc, mb) in enumerate(mid):
        if kind == OP_LOAD:
            out.append((OP_LOAD, mc, mb, alloc("x", mc, mb), -1, -1))
        elif kind == OP_FWD:
            x = read("x", mc, mb, i)
            y = -1
            if ("y", mc, mb) in last_read:      # someone will send it
                y = alloc("y", mc, mb)
            out.append((OP_FWD, mc, mb, x, y, -1))
        elif kind == OP_XFER_ACT:
            y = read("y", mc, mb, i)
            x = alloc("x", mc + 1, mb)
            out.append((OP_XFER_ACT, mc, mb, y, x, -1))
        elif kind == OP_BWD:
            x = read("x", mc, mb, i)
            dy = (read("dy", mc, mb, i)
                  if ("dy", mc, mb) in slot_of else -1)
            dx = (alloc("dx", mc, mb)
                  if ("dx", mc, mb) in last_read else -1)
            out.append((OP_BWD, mc, mb, x, dy, dx))
        elif kind == OP_XFER_GRAD:
            dx = read("dx", mc, mb, i)
            dy = alloc("dy", mc - 1, mb)
            out.append((OP_XFER_GRAD, mc, mb, dx, dy, -1))
        else:
            out.append((kind, -1, -1, -1, -1, -1))

    pool_sizes = {k: v for k, v in high.items()}
    return PipeProgram(out, pool_sizes, n_mc, micro_batches, n_source)


# ---------------------------------------------------------------------------
# binding: flat program -> list of zero-arg closures
# ---------------------------------------------------------------------------

def _leaf_shardings(rt, avals):
    """Per-leaf placement tree for a payload landing on stage rt — the
    SAME batch_shardable rule the interpreted path applies per event,
    resolved once here."""
    G = len(rt.devices)
    return jax.tree_util.tree_map(
        lambda a: rt.batch_sharding if batch_shardable(a.shape, G)
        else rt.replicated, avals)


def bind_program(engine, prog: PipeProgram, out_avals) -> List[Callable]:
    """Lower a PipeProgram to executable closures against `engine`.

    out_avals[mc] is the output aval tree of model chunk mc (from
    engine._chunk_out_avals).  Every static decision — stage runtime,
    slot index, rng fold constant, device_put sharding or channel
    transfer plan — is resolved here; the returned closures only index
    pools and call the already-jitted stage programs.  Closures read
    mutable engine/runtime state (params, scaler, micro-batch cache)
    through attribute access so checkpoint reloads keep working.

    Multi-host: events with no local role on this process are pruned
    (channel ops keep their collective entry order — both endpoints bind
    them at the same program positions).
    """
    mh = engine._mh
    n_mc = prog.n_mc
    fold_in = jax.random.fold_in

    def rt_of(mc):
        if mh:
            return engine._local.get(mc)
        return engine.stages[mc]

    # preallocated double-buffered pools (the x pool rides rng + x)
    pools: Dict[Tuple[int, str], List[Any]] = {
        k: [None] * n for k, n in prog.pool_sizes.items()}
    rngs: Dict[int, List[Any]] = {
        mc: [None] * n for (mc, kind), n in prog.pool_sizes.items()
        if kind == "x"}
    labels_pool: List[Any] = [None] * prog.micro_batches

    steps: List[Callable[[], None]] = []
    for op, mc, mb, a, b, c in prog.events:
        if op == OP_LOAD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, slot = pools[(mc, "x")], a
            place = rt.place_batch

            def f_load(eng=engine, xp=xp, slot=slot, mb=mb, place=place):
                xp[slot] = place(eng._mb_cache[mb][0])
            steps.append(f_load)
        elif op == OP_FWD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, rp = pools[(mc, "x")], rngs[mc]
            fold_const = mb * n_mc + mc
            if rt.is_last:
                def f_fwd_last(eng=engine, rt=rt, xp=xp, rp=rp, slot=a,
                               mb=mb, fc=fold_const, fold_in=fold_in,
                               labels_pool=labels_pool):
                    rng = fold_in(eng._batch_key, fc)
                    rp[slot] = rng
                    labels = rt.place_batch(
                        np.asarray(eng._mb_cache[mb][1]))
                    labels_pool[mb] = labels
                    rt.losses.append(rt.loss_j(rt.own, rt.ro_tied,
                                               xp[slot], labels, rng))
                steps.append(f_fwd_last)
            else:
                yp = pools.get((mc, "y"))
                def f_fwd(eng=engine, rt=rt, xp=xp, rp=rp, yp=yp,
                          xs=a, ys=b, fc=fold_const, fold_in=fold_in):
                    rng = fold_in(eng._batch_key, fc)
                    rp[xs] = rng
                    y = rt.fwd_j(rt.own, rt.ro_tied, xp[xs], rng)
                    if ys >= 0:
                        yp[ys] = y
                steps.append(f_fwd)
        elif op == OP_BWD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, rp = pools[(mc, "x")], rngs[mc]
            dxp = pools.get((mc, "dx"))
            if rt.is_last:
                def f_bwd_last(eng=engine, rt=rt, xp=xp, rp=rp, dxp=dxp,
                               xs=a, dxs=c, mb=mb, labels_pool=labels_pool):
                    x = xp[xs]
                    xp[xs] = None
                    rng = rp[xs]
                    rp[xs] = None
                    labels = labels_pool[mb]
                    labels_pool[mb] = None
                    scale = eng._scaler_state["cur_scale"]
                    dx, rt.acc, rt.acc_ro = rt.bwd_j(
                        rt.own, rt.ro_tied, x, labels, rng, scale,
                        rt.acc, rt.acc_ro)
                    if dxs >= 0:
                        dxp[dxs] = dx
                steps.append(f_bwd_last)
            else:
                dyp = pools[(mc, "dy")]
                def f_bwd(rt=rt, xp=xp, rp=rp, dyp=dyp, dxp=dxp,
                          xs=a, dys=b, dxs=c):
                    x = xp[xs]
                    xp[xs] = None
                    rng = rp[xs]
                    rp[xs] = None
                    dy = dyp[dys]
                    dyp[dys] = None
                    dx, rt.acc, rt.acc_ro = rt.bwd_j(
                        rt.own, rt.ro_tied, x, rng, dy, rt.acc, rt.acc_ro)
                    if dxs >= 0:
                        dxp[dxs] = dx
                steps.append(f_bwd)
        elif op == OP_XFER_ACT:
            f = _bind_xfer(engine, mh, src_mc=mc, dst_mc=mc + 1,
                           avals=out_avals[mc],
                           src_pool=pools.get((mc, "y")), src_slot=a,
                           dst_pool=pools[(mc + 1, "x")], dst_slot=b,
                           chan=(engine._chan_act.get(mc) if mh else None),
                           rt_of=rt_of)
            if f is not None:
                steps.append(f)
        elif op == OP_XFER_GRAD:
            f = _bind_xfer(engine, mh, src_mc=mc, dst_mc=mc - 1,
                           avals=out_avals[mc - 1],
                           src_pool=pools.get((mc, "dx")), src_slot=a,
                           dst_pool=pools[(mc - 1, "dy")], dst_slot=b,
                           chan=(engine._chan_grad.get(mc) if mh else None),
                           rt_of=rt_of)
            if f is not None:
                steps.append(f)
        elif op == OP_TIED:
            steps.append(engine._reduce_tied_grads_mh if mh
                         else engine._reduce_tied_grads)
        elif op == OP_STEP:
            steps.append(engine._pipe_optimizer_step_mh if mh
                         else engine._pipe_optimizer_step)
        else:
            raise NotImplementedError(f"opcode {op}")
    return steps


def _bind_xfer(engine, mh, src_mc, dst_mc, avals, src_pool, src_slot,
               dst_pool, dst_slot, chan, rt_of):
    """One fused send+recv: returns a closure or None (no local role)."""
    if not mh:
        # single-controller: a device_put resharding, target layout
        # resolved once from the aval (the interpreted path re-derives it
        # per event from the runtime value's shape)
        rt_dst = rt_of(dst_mc)
        sh = _leaf_shardings(rt_dst, avals)
        device_put = jax.device_put

        def f_put(sp=src_pool, ss=src_slot, dp=dst_pool, ds=dst_slot,
                  sh=sh, device_put=device_put):
            y = sp[ss]
            sp[ss] = None
            dp[ds] = device_put(y, sh)
        return f_put
    if chan is None:
        return None  # this process is not an endpoint: prune
    plan = chan.plan(avals)
    src_local = rt_of(src_mc) is not None
    dst_local = rt_of(dst_mc) is not None
    if src_local and dst_local:
        def f_both(sp=src_pool, ss=src_slot, dp=dst_pool, ds=dst_slot,
                   plan=plan):
            y = sp[ss]
            sp[ss] = None
            dp[ds] = plan(y)
        return f_both
    if src_local:
        def f_src(sp=src_pool, ss=src_slot, plan=plan):
            y = sp[ss]
            sp[ss] = None
            plan(y)
        return f_src

    def f_dst(dp=dst_pool, ds=dst_slot, plan=plan):
        dp[ds] = plan(None)
    return f_dst
