"""Pipeline schedule compiler — flat per-rank programs for the 1F1B walk.

BENCH.md round-5 measured the interpreted canonical walk at ~300 µs of
serialized Python per schedule event (schedule-stream regeneration +
dependency re-simulation + isinstance dispatch + counter/dict/mail
bookkeeping, every train_batch), 12-16 % of step time on CPU-mesh grains
and projected ~150 ms/step at 8 stages x 16 micro batches. This module
removes the interpreter from that inner loop:

* `compile_schedule` lowers the canonical event order (the output of
  engine._simulate_order — identical on every process, the property that
  keeps the channel handoffs deadlock-free) ONCE into a flat, immutable
  program: parallel tuples of opcode / model-chunk / micro-id / buffer
  slots.  Micro ids are precomputed, so the run-time recv/send/fwd/bwd
  counters disappear entirely.

* every Send+Recv pair is FUSED into a single transfer op placed at the
  send's position.  The data transfer already happens at the send event
  in the interpreted walk (the recv is pure mail-dict bookkeeping), so
  the collective entry order across processes is unchanged — only the
  Python disappears.  Fusion is made unconditionally safe by giving the
  fused write a liveness-fresh buffer slot (below) instead of the
  schedule's recv-time slot.

* buffer slots are resolved once by liveness analysis into preallocated
  per-stage pools (plain lists — the double-buffered pool): each
  (chunk, micro) value gets a slot live from its writing event to its
  last reading event.  No dict hashing, no (mc, mb) tuple keys, no mail
  dict at run time.

* `bind_program` turns the flat program into a list of zero-argument
  closures with every static decision (stage runtime, slot indices, rng
  fold constants, transfer plans/shardings) resolved at bind time.  The
  executor loop in engine.py is then `for f in steps: f()` — it touches
  no Python objects besides the program list and the pools.  On
  multi-host ranks, events with no local role are pruned at bind time
  (the interpreted walk pays Python for every remote event).

The interpreted walk stays available as `pipeline.debug_schedule: true`
— the parity oracle (tests pin bit-identical losses) and the
reference-shaped executor for new-instruction bring-up.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax

from ...monitor.counters import COUNTERS, tree_bytes
from .p2p import batch_shardable
from .schedule import (BackwardPass, ForwardPass, LoadMicroBatch,
                       OptimizerStep, RecvActivation, RecvGrad, ReduceGrads,
                       ReduceTiedGrads, SendActivation, SendGrad)

# opcodes (flat-program ISA)
OP_LOAD = 0        # (mc, mb, x_slot)
OP_FWD = 1         # (mc, mb, x_slot, y_slot)   y_slot < 0: output unused
OP_XFER_ACT = 2    # (src_mc, mb, y_slot, dst_x_slot)    fused send+recv
OP_BWD = 3         # (mc, mb, x_slot, dy_slot, dx_slot)  dy<0: last stage
OP_XFER_GRAD = 4   # (src_mc, mb, dx_slot, dst_dy_slot)  fused send+recv
OP_TIED = 5        # ()
OP_STEP = 6        # ()

OP_NAMES = {OP_LOAD: "load", OP_FWD: "fwd", OP_XFER_ACT: "xfer_act",
            OP_BWD: "bwd", OP_XFER_GRAD: "xfer_grad", OP_TIED: "tied",
            OP_STEP: "step"}


class PipeProgram:
    """Immutable lowered schedule: one entry per executed event.

    events: tuple of tuples — (op, mc, mb, a, b, c) with slot fields per
    the opcode table above (unused fields -1).  pool_sizes maps
    (mc, kind) -> required slot count, kind in {x, y, dy, dx}; the `x`
    pool also carries the forward rng (identical liveness).
    """

    __slots__ = ("events", "pool_sizes", "n_mc", "micro_batches",
                 "n_source_events")

    def __init__(self, events, pool_sizes, n_mc, micro_batches,
                 n_source_events):
        self.events = tuple(events)
        self.pool_sizes = dict(pool_sizes)
        self.n_mc = n_mc
        self.micro_batches = micro_batches
        # pre-fusion event count (for dispatch-rate accounting)
        self.n_source_events = n_source_events

    def __repr__(self):
        ops = ", ".join(OP_NAMES[e[0]] for e in self.events[:8])
        return (f"PipeProgram({len(self.events)} events from "
                f"{self.n_source_events}, n_mc={self.n_mc}, "
                f"M={self.micro_batches}, [{ops}...])")


def schedule_occupancy(streams) -> List[Dict[str, Any]]:
    """Per-physical-stage bubble/occupancy accounting from the canonical
    per-stage tick streams (`engine._pipe_streams()` output — the same
    object `compile_schedule` lowers).  A tick is `compute` when it
    carries a Forward/BackwardPass; the bubble fraction is the idle-tick
    share of the stage's stream — the schedule-theoretic pipeline bubble
    ((P-1)/(M+P-1) for plain 1F1B), independent of hardware timing.
    Emitted into every step event by the pipeline engine so a run's
    JSONL records how much of its step is schedule-structural."""
    out = []
    for stage, stream in enumerate(streams):
        ticks = len(stream)
        compute = 0
        for tick in stream:
            cmds = tick if isinstance(tick, (list, tuple)) else (tick,)
            if any(isinstance(c, (ForwardPass, BackwardPass))
                   for c in cmds):
                compute += 1
        out.append({"stage": stage, "ticks": ticks,
                    "compute_ticks": compute,
                    "bubble_frac": round(1.0 - compute / max(1, ticks), 4)})
    return out


class PipeInstrument:
    """Measured per-op dispatch-time accounting for the bound executor.

    Wraps every bound closure in a perf_counter pair, accumulating
    seconds by opcode and by model chunk.  This measures HOST dispatch
    time (dispatch is async); the engine closes the whole batch on a
    block_until_ready marker, so batch wall minus dispatch total bounds
    the device-side remainder — both land in the step event.  Only
    attached when a
    RunMonitor is active: the unmonitored executor keeps its bare
    `for f in steps: f()` loop."""

    __slots__ = ("op_s", "stage_s")

    def __init__(self):
        self.op_s: Dict[str, float] = {}
        self.stage_s: Dict[int, float] = {}

    def wrap(self, opname: str, mc: int, fn: Callable[[], None]):
        op_s, stage_s, clock = self.op_s, self.stage_s, time.perf_counter

        def timed():
            t0 = clock()
            fn()
            dt = clock() - t0
            op_s[opname] = op_s.get(opname, 0.0) + dt
            if mc >= 0:
                stage_s[mc] = stage_s.get(mc, 0.0) + dt
        return timed

    def drain(self) -> Dict[str, Any]:
        out = {
            "op_ms": {k: round(v * 1000.0, 3)
                      for k, v in sorted(self.op_s.items())},
            "stage_ms": {str(k): round(v * 1000.0, 3)
                         for k, v in sorted(self.stage_s.items())},
        }
        self.op_s.clear()
        self.stage_s.clear()
        return out


def compile_schedule(events, mc_of: Callable[[int, Any], int], n_mc: int,
                     micro_batches: int) -> PipeProgram:
    """Lower a canonical (stage, instruction) event list to a PipeProgram.

    `events` is engine._simulate_order's output; `mc_of` maps
    (stage, cmd) to the model-chunk index (engine._mc).  Pure structural
    lowering — no engine state is touched, so the result is reusable for
    every train_batch with the same (M, stages, interleave).
    """
    # -- pass 1: assign micro ids with the same counters the interpreted
    # dispatch uses, and drop bookkeeping-only instructions --------------
    events = list(events)
    fwd_cnt = [0] * n_mc
    bwd_cnt = [0] * n_mc
    sent_act = [0] * n_mc
    sent_grad = [0] * n_mc
    recv_act = [0] * n_mc
    recv_grad = [0] * n_mc
    load_cnt = 0
    mid: List[Tuple[int, int, int]] = []   # (kind, mc, mb)
    # one OP_TIED / OP_STEP per batch, placed at the LAST canonical
    # occurrence: every stage's stream carries one of each, and only at
    # the last one (stage 0's, after the globally final backward) are all
    # gradients complete.  Emitting at the first occurrence would apply
    # the optimizer while earlier stages' cooldown backwards are still
    # accumulating — dropped gradients this step, leakage into the next.
    tied_left = sum(isinstance(c, ReduceTiedGrads) for _, c in events)
    step_left = sum(isinstance(c, OptimizerStep) for _, c in events)
    n_source = 0
    for s, cmd in events:
        n_source += 1
        mc = mc_of(s, cmd)
        if isinstance(cmd, LoadMicroBatch):
            mid.append((OP_LOAD, mc, load_cnt))
            load_cnt += 1
        elif isinstance(cmd, ForwardPass):
            mid.append((OP_FWD, mc, fwd_cnt[mc]))
            fwd_cnt[mc] += 1
        elif isinstance(cmd, SendActivation):
            mid.append((OP_XFER_ACT, mc, sent_act[mc]))
            sent_act[mc] += 1
        elif isinstance(cmd, RecvActivation):
            # fused into the matching send (the transfer happens at the
            # send position in the interpreted walk too); assert the
            # canonical order really delivered before consumption
            mb = recv_act[mc]
            recv_act[mc] += 1
            if sent_act[mc - 1] < mb + 1:
                raise AssertionError(
                    f"recv_act before send for chunk {mc} micro {mb}")
        elif isinstance(cmd, BackwardPass):
            mid.append((OP_BWD, mc, bwd_cnt[mc]))
            bwd_cnt[mc] += 1
        elif isinstance(cmd, SendGrad):
            mid.append((OP_XFER_GRAD, mc, sent_grad[mc]))
            sent_grad[mc] += 1
        elif isinstance(cmd, RecvGrad):
            mb = recv_grad[mc]
            recv_grad[mc] += 1
            if sent_grad[mc + 1] < mb + 1:
                raise AssertionError(
                    f"recv_grad before send for chunk {mc} micro {mb}")
        elif isinstance(cmd, ReduceTiedGrads):
            tied_left -= 1
            if tied_left == 0:
                mid.append((OP_TIED, -1, -1))
        elif isinstance(cmd, OptimizerStep):
            step_left -= 1
            if step_left == 0:
                mid.append((OP_STEP, -1, -1))
        elif isinstance(cmd, ReduceGrads):
            pass  # within-stage dp reduction is implicit in the jitted loss
        else:
            raise NotImplementedError(f"instruction {cmd!r}")

    # -- pass 2: find each value's last reader (liveness) ----------------
    # value keys: ("x"|"y"|"dy"|"dx", mc, mb)
    last_read: Dict[Tuple[str, int, int], int] = {}
    for i, (kind, mc, mb) in enumerate(mid):
        if kind == OP_FWD:
            last_read[("x", mc, mb)] = i          # read again by BWD below
        elif kind == OP_XFER_ACT:
            last_read[("y", mc, mb)] = i
        elif kind == OP_BWD:
            last_read[("x", mc, mb)] = i
            last_read[("dy", mc, mb)] = i
        elif kind == OP_XFER_GRAD:
            last_read[("dx", mc, mb)] = i

    # -- pass 3: slot allocation + final event emission ------------------
    free: Dict[Tuple[int, str], List[int]] = {}
    high: Dict[Tuple[int, str], int] = {}
    slot_of: Dict[Tuple[str, int, int], int] = {}

    def alloc(kind, mc, mb):
        pool = free.setdefault((mc, kind), [])
        if pool:
            s = pool.pop()
        else:
            s = high.get((mc, kind), 0)
            high[(mc, kind)] = s + 1
        slot_of[(kind, mc, mb)] = s
        return s

    def read(kind, mc, mb, i):
        s = slot_of[(kind, mc, mb)]
        if last_read.get((kind, mc, mb)) == i:
            free.setdefault((mc, kind), []).append(s)
        return s

    out: List[Tuple[int, int, int, int, int]] = []
    for i, (kind, mc, mb) in enumerate(mid):
        if kind == OP_LOAD:
            out.append((OP_LOAD, mc, mb, alloc("x", mc, mb), -1, -1))
        elif kind == OP_FWD:
            x = read("x", mc, mb, i)
            y = -1
            if ("y", mc, mb) in last_read:      # someone will send it
                y = alloc("y", mc, mb)
            out.append((OP_FWD, mc, mb, x, y, -1))
        elif kind == OP_XFER_ACT:
            y = read("y", mc, mb, i)
            x = alloc("x", mc + 1, mb)
            out.append((OP_XFER_ACT, mc, mb, y, x, -1))
        elif kind == OP_BWD:
            x = read("x", mc, mb, i)
            dy = (read("dy", mc, mb, i)
                  if ("dy", mc, mb) in slot_of else -1)
            dx = (alloc("dx", mc, mb)
                  if ("dx", mc, mb) in last_read else -1)
            out.append((OP_BWD, mc, mb, x, dy, dx))
        elif kind == OP_XFER_GRAD:
            dx = read("dx", mc, mb, i)
            dy = alloc("dy", mc - 1, mb)
            out.append((OP_XFER_GRAD, mc, mb, dx, dy, -1))
        else:
            out.append((kind, -1, -1, -1, -1, -1))

    pool_sizes = {k: v for k, v in high.items()}
    return PipeProgram(out, pool_sizes, n_mc, micro_batches, n_source)


# ---------------------------------------------------------------------------
# binding: flat program -> list of zero-arg closures
# ---------------------------------------------------------------------------

def _leaf_shardings(rt, avals):
    """Per-leaf placement tree for a payload landing on stage rt — the
    SAME batch_shardable rule the interpreted path applies per event,
    resolved once here."""
    G = len(rt.devices)
    return jax.tree_util.tree_map(
        lambda a: rt.batch_sharding if batch_shardable(a.shape, G)
        else rt.replicated, avals)


def bind_program(engine, prog: PipeProgram, out_avals,
                 instrument: Optional[PipeInstrument] = None
                 ) -> List[Callable]:
    """Lower a PipeProgram to executable closures against `engine`.

    out_avals[mc] is the output aval tree of model chunk mc (from
    engine._chunk_out_avals).  Every static decision — stage runtime,
    slot index, rng fold constant, device_put sharding or channel
    transfer plan — is resolved here; the returned closures only index
    pools and call the already-jitted stage programs.  Closures read
    mutable engine/runtime state (params, scaler, micro-batch cache)
    through attribute access so checkpoint reloads keep working.

    Multi-host: events with no local role on this process are pruned
    (channel ops keep their collective entry order — both endpoints bind
    them at the same program positions).

    instrument: optional PipeInstrument — wraps every bound closure in
    per-op dispatch timing (attached by the engine when a RunMonitor is
    active; None keeps the closures bare).
    """
    mh = engine._mh
    n_mc = prog.n_mc
    fold_in = jax.random.fold_in

    def rt_of(mc):
        if mh:
            return engine._local.get(mc)
        return engine.stages[mc]

    # preallocated double-buffered pools (the x pool rides rng + x)
    pools: Dict[Tuple[int, str], List[Any]] = {
        k: [None] * n for k, n in prog.pool_sizes.items()}
    rngs: Dict[int, List[Any]] = {
        mc: [None] * n for (mc, kind), n in prog.pool_sizes.items()
        if kind == "x"}
    labels_pool: List[Any] = [None] * prog.micro_batches

    steps: List[Callable[[], None]] = []

    def push(f, opname, mc):
        steps.append(f if instrument is None
                     else instrument.wrap(opname, mc, f))

    for op, mc, mb, a, b, c in prog.events:
        if op == OP_LOAD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, slot = pools[(mc, "x")], a
            place = rt.place_batch

            def f_load(eng=engine, xp=xp, slot=slot, mb=mb, place=place):
                xp[slot] = place(eng._mb_cache[mb][0])
            push(f_load, "load", mc)
        elif op == OP_FWD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, rp = pools[(mc, "x")], rngs[mc]
            fold_const = mb * n_mc + mc
            if rt.is_last:
                def f_fwd_last(eng=engine, rt=rt, xp=xp, rp=rp, slot=a,
                               mb=mb, fc=fold_const, fold_in=fold_in,
                               labels_pool=labels_pool):
                    rng = fold_in(eng._batch_key, fc)
                    rp[slot] = rng
                    labels = rt.place_batch(
                        np.asarray(eng._mb_cache[mb][1]))
                    labels_pool[mb] = labels
                    rt.losses.append(rt.loss_j(rt.own, rt.ro_tied,
                                               xp[slot], labels, rng))
                push(f_fwd_last, "fwd", mc)
            else:
                yp = pools.get((mc, "y"))
                def f_fwd(eng=engine, rt=rt, xp=xp, rp=rp, yp=yp,
                          xs=a, ys=b, fc=fold_const, fold_in=fold_in):
                    rng = fold_in(eng._batch_key, fc)
                    rp[xs] = rng
                    y = rt.fwd_j(rt.own, rt.ro_tied, xp[xs], rng)
                    if ys >= 0:
                        yp[ys] = y
                push(f_fwd, "fwd", mc)
        elif op == OP_BWD:
            rt = rt_of(mc)
            if rt is None:
                continue
            xp, rp = pools[(mc, "x")], rngs[mc]
            dxp = pools.get((mc, "dx"))
            if rt.is_last:
                def f_bwd_last(eng=engine, rt=rt, xp=xp, rp=rp, dxp=dxp,
                               xs=a, dxs=c, mb=mb, labels_pool=labels_pool):
                    x = xp[xs]
                    xp[xs] = None
                    rng = rp[xs]
                    rp[xs] = None
                    labels = labels_pool[mb]
                    labels_pool[mb] = None
                    scale = eng._scaler_state["cur_scale"]
                    dx, rt.acc, rt.acc_ro = rt.bwd_j(
                        rt.own, rt.ro_tied, x, labels, rng, scale,
                        rt.acc, rt.acc_ro)
                    if dxs >= 0:
                        dxp[dxs] = dx
                push(f_bwd_last, "bwd", mc)
            else:
                dyp = pools[(mc, "dy")]
                def f_bwd(rt=rt, xp=xp, rp=rp, dyp=dyp, dxp=dxp,
                          xs=a, dys=b, dxs=c):
                    x = xp[xs]
                    xp[xs] = None
                    rng = rp[xs]
                    rp[xs] = None
                    dy = dyp[dys]
                    dyp[dys] = None
                    dx, rt.acc, rt.acc_ro = rt.bwd_j(
                        rt.own, rt.ro_tied, x, rng, dy, rt.acc, rt.acc_ro)
                    if dxs >= 0:
                        dxp[dxs] = dx
                push(f_bwd, "bwd", mc)
        elif op == OP_XFER_ACT:
            f = _bind_xfer(engine, mh, src_mc=mc, dst_mc=mc + 1,
                           avals=out_avals[mc],
                           src_pool=pools.get((mc, "y")), src_slot=a,
                           dst_pool=pools[(mc + 1, "x")], dst_slot=b,
                           chan=(engine._chan_act.get(mc) if mh else None),
                           rt_of=rt_of, kind="act")
            if f is not None:
                push(f, "xfer_act", mc)
        elif op == OP_XFER_GRAD:
            f = _bind_xfer(engine, mh, src_mc=mc, dst_mc=mc - 1,
                           avals=out_avals[mc - 1],
                           src_pool=pools.get((mc, "dx")), src_slot=a,
                           dst_pool=pools[(mc - 1, "dy")], dst_slot=b,
                           chan=(engine._chan_grad.get(mc) if mh else None),
                           rt_of=rt_of, kind="grad")
            if f is not None:
                push(f, "xfer_grad", mc)
        elif op == OP_TIED:
            push(engine._reduce_tied_grads_mh if mh
                 else engine._reduce_tied_grads, "tied", -1)
        elif op == OP_STEP:
            push(engine._pipe_optimizer_step_mh if mh
                 else engine._pipe_optimizer_step, "step", -1)
        else:
            raise NotImplementedError(f"opcode {op}")
    return steps


def _bind_xfer(engine, mh, src_mc, dst_mc, avals, src_pool, src_slot,
               dst_pool, dst_slot, chan, rt_of, kind="act"):
    """One fused send+recv: returns a closure or None (no local role).
    Payload bytes are resolved from the avals ONCE here and counted per
    dispatch (`pipe.xfer_{kind}`); the channel (mh) paths count inside
    ChannelPlan instead."""
    if not mh:
        # single-controller: a device_put resharding, target layout
        # resolved once from the aval (the interpreted path re-derives it
        # per event from the runtime value's shape)
        rt_dst = rt_of(dst_mc)
        sh = _leaf_shardings(rt_dst, avals)
        device_put = jax.device_put
        nbytes = tree_bytes(avals)
        cname = f"pipe.xfer_{kind}"

        def f_put(sp=src_pool, ss=src_slot, dp=dst_pool, ds=dst_slot,
                  sh=sh, device_put=device_put, nbytes=nbytes, cname=cname):
            COUNTERS.add(cname, nbytes)
            y = sp[ss]
            sp[ss] = None
            dp[ds] = device_put(y, sh)
        return f_put
    if chan is None:
        return None  # this process is not an endpoint: prune
    plan = chan.plan(avals)
    src_local = rt_of(src_mc) is not None
    dst_local = rt_of(dst_mc) is not None
    if src_local and dst_local:
        def f_both(sp=src_pool, ss=src_slot, dp=dst_pool, ds=dst_slot,
                   plan=plan):
            y = sp[ss]
            sp[ss] = None
            dp[ds] = plan(y)
        return f_both
    if src_local:
        def f_src(sp=src_pool, ss=src_slot, plan=plan):
            y = sp[ss]
            sp[ss] = None
            plan(y)
        return f_src

    def f_dst(dp=dst_pool, ds=dst_slot, plan=plan):
        dp[ds] = plan(None)
    return f_dst
