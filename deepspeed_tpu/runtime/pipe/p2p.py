"""Cross-process pipeline point-to-point over XLA collectives.

Reference capability: deepspeed/runtime/pipe/p2p.py:31-75 — NCCL
send/recv between adjacent pipeline ranks across nodes.  JAX has no raw
p2p between processes, but any computation on a mesh spanning exactly the
two endpoint processes is executed only by them; a transfer is therefore
a tiny jitted reduction on a 2-row pair mesh:

    row 0 = payload (sender's devices)     row 1 = zeros (receiver's)
    out   = sum over rows, replicated over the row axis

XLA lowers the row-sum to a pairwise exchange riding ICI/DCN — the
collective IS the send/recv.  The sum is exact (payload + 0).  Non-
endpoint processes never construct or enter the program, so independent
stage pairs need no global ordering — the NCCL-p2p property that makes
pipeline schedules composable.

The same construction works single-process (all devices addressable),
which is how the driver's virtual multichip dryrun executes the
multi-host code path verbatim.

Endpoint ordering contract: both endpoint processes must enter a
channel's transfers in the same relative order, and any two processes
must order their COMMON collectives identically.  The pipeline engine
guarantees this by deriving one canonical global event order from the
schedule (engine._simulate_order) and having every process walk it.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...monitor.counters import COUNTERS, tree_bytes


def batch_shardable(shape, group_size: int) -> bool:
    """THE shard-vs-replicate rule for pipeline payloads: batch-shard over
    a device group iff the leading dim divides evenly.  Sender and
    receiver of a Channel, and the single-controller placements, must all
    derive the layout from the aval alone — one rule, one place."""
    return bool(len(shape)) and shape[0] % group_size == 0


class Channel:
    """One-directional transfer: src device group -> dst device group.

    Both endpoint processes call transfer() at matched times (receiver
    passes values=None); the return value is the tree placed on the dst
    group (None on a pure-sender process).  Group sizes must match —
    uniform devices-per-process, the same contract the rest of the
    runtime assumes."""

    def __init__(self, src_devices, dst_devices, replicate: bool = False):
        """replicate=True forces every transfer to land replicated over
        the dst group (parameter/grad channels — tied weights are placed
        replicated on their stage, and a batch-sharded copy would force
        stage-program recompiles + re-gathers)."""
        if len(src_devices) != len(dst_devices):
            raise ValueError(
                f"channel endpoints need equal device counts, got "
                f"{len(src_devices)} -> {len(dst_devices)}")
        self.replicate = replicate
        self.G = len(src_devices)
        self.src = list(src_devices)
        self.dst = list(dst_devices)
        me = jax.process_index()
        self.is_src = any(d.process_index == me for d in self.src)
        self.is_dst = any(d.process_index == me for d in self.dst)
        self.mesh = Mesh(np.array([self.src, self.dst]), ("side", "dev"))
        self.src_mesh = Mesh(np.array(self.src), ("data",))
        self.dst_mesh = Mesh(np.array(self.dst), ("data",))
        self._progs: Dict[Any, Any] = {}
        self._zeros: Dict[Any, Any] = {}
        self._plans: Dict[Any, "ChannelPlan"] = {}

    def plan(self, avals) -> "ChannelPlan":
        """Precompiled transfer for a fixed aval tree (cached).  All
        leaves ride ONE jitted collective — the fused channel operation
        the compiled pipeline executor dispatches per schedule event."""
        leaves, treedef = jax.tree_util.tree_flatten(avals)
        key = (treedef, tuple((tuple(a.shape), str(a.dtype))
                              for a in leaves))
        p = self._plans.get(key)
        if p is None:
            p = ChannelPlan(self, avals)
            self._plans[key] = p
        return p

    def _plan(self, aval):
        """Layout from the aval alone (mirrors _StageRuntime.place_batch
        via batch_shardable); always replicated on parameter channels."""
        if self.replicate:
            return False
        return batch_shardable(aval.shape, self.G)

    def _zero_shard(self, shape, dtype, device):
        key = (shape, str(dtype), device.id)
        z = self._zeros.get(key)
        if z is None:
            z = jax.device_put(jnp.zeros(shape, dtype), device)
            self._zeros[key] = z
        return z

    def _leaf(self, aval, val) -> Optional[jax.Array]:
        shard = self._plan(aval)
        gshape = (2, *aval.shape)
        in_spec = P("side", "dev") if shard else P("side")
        in_sh = NamedSharding(self.mesh, in_spec)
        shards = []
        if self.is_src:
            if val is None:
                raise ValueError("sender process got no value to transfer")
            local_spec = P("data") if shard else P()
            val = jax.device_put(
                jnp.asarray(val),
                NamedSharding(self.src_mesh, local_spec))
            # [B/G, ...] (or full) per-device blocks -> [1, B/G, ...] rows
            shards += [s.data[None] for s in val.addressable_shards]
        if self.is_dst:
            row = ((aval.shape[0] // self.G, *aval.shape[1:])
                   if shard else tuple(aval.shape))
            shards += [self._zero_shard((1, *row), aval.dtype, d)
                       for d in self.dst if d.process_index ==
                       jax.process_index()]
        garr = jax.make_array_from_single_device_arrays(gshape, in_sh,
                                                        shards)
        key = (gshape, str(aval.dtype), shard)
        prog = self._progs.get(key)
        if prog is None:
            out_spec = P("dev") if shard else P()
            dt = aval.dtype
            prog = jax.jit(
                lambda a: jnp.sum(a, axis=0).astype(dt),
                out_shardings=NamedSharding(self.mesh, out_spec))
            self._progs[key] = prog
        out = prog(garr)
        if not self.is_dst:
            return None
        # rebuild as a dst-group-local array so the receiver's stage jits
        # (compiled over the local mesh) consume it without resharding
        local_spec = P("data") if shard else P()
        dst_set = {d.id for d in self.dst}
        mine = [s.data for s in out.addressable_shards
                if s.device.id in dst_set]
        return jax.make_array_from_single_device_arrays(
            tuple(aval.shape), NamedSharding(self.dst_mesh, local_spec),
            mine)

    def transfer(self, avals, values=None):
        """avals: pytree of ShapeDtypeStructs (both endpoints know it);
        values: matching pytree of arrays on the sender, None on the
        receiver.  Returns the tree on the dst group, or None if this
        process is not a receiver."""
        if not (self.is_src or self.is_dst):
            return None
        nbytes = tree_bytes(avals)
        if self.is_src:
            COUNTERS.add("p2p.send", nbytes)
        if self.is_dst:
            COUNTERS.add("p2p.recv", nbytes)
        leaves, treedef = jax.tree_util.tree_flatten(avals)
        vleaves = (treedef.flatten_up_to(values)
                   if self.is_src else [None] * len(leaves))
        out = [self._leaf(a, v) for a, v in zip(leaves, vleaves)]
        if not self.is_dst:
            return None
        return jax.tree_util.tree_unflatten(treedef, out)


class ChannelPlan:
    """Precompiled fused transfer for one Channel and one fixed aval tree.

    The interpreted `Channel.transfer` pays, per event and per leaf: a
    tree flatten, a layout re-derivation, two cache-dict lookups, a
    device_put, and ONE JIT DISPATCH PER LEAF.  A plan resolves all of
    that once at construction — layouts, pair-mesh shardings, zero rows,
    receiver rebuild metadata — and fuses every leaf's row-sum into a
    SINGLE jitted program, so a schedule event costs one dispatch no
    matter how many leaves the payload tree has (the "coalesced p2p"
    operation the compiled pipeline executor emits per fused send+recv).

    Numerics are identical to transfer(): the same payload + zero-row
    sum per leaf, just batched into one executable.  Call with the
    value tree on a sender (returns the dst-group tree, or None on a
    pure sender); call with None on a pure receiver.
    """

    __slots__ = ("treedef", "n", "is_src", "is_dst", "gshapes",
                 "in_shardings", "src_shardings", "zero_rows", "dst_ids",
                 "out_shapes", "out_shardings", "fused", "payload_bytes")

    def __init__(self, chan: "Channel", avals):
        leaves, self.treedef = jax.tree_util.tree_flatten(avals)
        self.n = len(leaves)
        self.payload_bytes = tree_bytes(avals)
        self.is_src = chan.is_src
        self.is_dst = chan.is_dst
        me = jax.process_index()
        self.gshapes = []
        self.in_shardings = []
        self.src_shardings = []
        self.zero_rows = []
        self.out_shapes = []
        self.out_shardings = []
        flags, dts = [], []
        for a in leaves:
            shard = chan._plan(a)
            flags.append(shard)
            dts.append(a.dtype)
            self.gshapes.append((2, *a.shape))
            in_spec = P("side", "dev") if shard else P("side")
            self.in_shardings.append(NamedSharding(chan.mesh, in_spec))
            local_spec = P("data") if shard else P()
            self.src_shardings.append(
                NamedSharding(chan.src_mesh, local_spec))
            if self.is_dst:
                row = ((a.shape[0] // chan.G, *a.shape[1:])
                       if shard else tuple(a.shape))
                self.zero_rows.append(
                    [chan._zero_shard((1, *row), a.dtype, d)
                     for d in chan.dst if d.process_index == me])
            else:
                self.zero_rows.append(None)
            self.out_shapes.append(tuple(a.shape))
            self.out_shardings.append(
                NamedSharding(chan.dst_mesh, local_spec))
        self.dst_ids = frozenset(d.id for d in chan.dst)

        def row_sum(*xs, _dts=tuple(dts)):
            return tuple(jnp.sum(x, axis=0).astype(dt)
                         for x, dt in zip(xs, _dts))

        self.fused = jax.jit(
            row_sum,
            out_shardings=tuple(
                NamedSharding(chan.mesh, P("dev") if sh else P())
                for sh in flags))

    def __call__(self, values=None):
        if self.is_src:
            COUNTERS.add("p2p.plan.send", self.payload_bytes)
        if self.is_dst:
            COUNTERS.add("p2p.plan.recv", self.payload_bytes)
        from_rows = jax.make_array_from_single_device_arrays
        garrs = []
        if self.is_src:
            vleaves = self.treedef.flatten_up_to(values)
        for i in range(self.n):
            shards = []
            if self.is_src:
                v = jax.device_put(jnp.asarray(vleaves[i]),
                                   self.src_shardings[i])
                shards += [s.data[None] for s in v.addressable_shards]
            if self.is_dst:
                shards += self.zero_rows[i]
            garrs.append(from_rows(self.gshapes[i], self.in_shardings[i],
                                   shards))
        outs = self.fused(*garrs)
        if not self.is_dst:
            return None
        res = []
        for i, out in enumerate(outs):
            mine = [s.data for s in out.addressable_shards
                    if s.device.id in self.dst_ids]
            res.append(from_rows(self.out_shapes[i],
                                 self.out_shardings[i], mine))
        return jax.tree_util.tree_unflatten(self.treedef, res)


class GlobalScalars:
    """Sum-reduce a small fp32 vector across ALL processes (pipeline step
    bookkeeping: loss, global grad-norm, overflow count).  Single global
    collective per call; every process must call in the same order.
    Identity when process_count == 1."""

    def __init__(self):
        self.nprocs = jax.process_count()
        if self.nprocs == 1:
            return
        devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        per = len(devs) // self.nprocs
        self.mesh = Mesh(np.array(devs).reshape(self.nprocs, per),
                         ("proc", "dev"))
        self._row = NamedSharding(self.mesh, P("proc"))
        self._sum = jax.jit(lambda x: jnp.sum(x, axis=0),
                            out_shardings=NamedSharding(self.mesh, P()))

    def sum(self, vec) -> np.ndarray:
        vec = np.asarray(vec, np.float32)
        COUNTERS.add("p2p.global_scalars", vec.nbytes)
        if self.nprocs == 1:
            return vec
        garr = jax.make_array_from_process_local_data(
            self._row, vec[None, :], (self.nprocs, vec.size))
        return np.asarray(self._sum(garr).addressable_data(0))
