"""PipelineModule / LayerSpec — layer-list model for pipeline parallelism.

Reference: deepspeed/runtime/pipe/module.py:23,86. A PipelineModule is a
sequence of layer constructors (LayerSpec) partitioned over pipeline stages.
The full pipeline runtime (schedules, ppermute p2p) lives in
runtime/pipe/engine.py; this module carries the model description and the
stage partitioner.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax

from ...utils.logging import logger
from ..utils import partition_balanced, partition_uniform


class LayerSpec:
    """Deferred layer constructor (reference pipe/module.py:23): holds the
    callable + args so stages only materialize their own layers."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages (reference :44), e.g.
    embedding/unembedding weight tying."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Sequence-of-layers model partitioned over the `pipe` mesh axis
    (reference pipe/module.py:86).

    Each built layer must be a TrainModule-like object exposing
    `init(rng) -> params` and `apply(params, x, rng=None, train=True) -> x`.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False, partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 interleave: int = 1):
        """interleave > 1 enables Megatron-style interleaved (virtual-
        stage) scheduling: the layer stack is cut into
        num_stages * interleave model chunks and each physical stage owns
        every num_stages-th chunk, shrinking the 1F1B bubble by ~1/
        interleave at the cost of more boundary traffic. (Beyond the
        reference, whose schedule.py:182 interleaves micro batches only.)"""
        self.layer_specs = list(layers)
        self.num_stages = num_stages or 1
        self.interleave = int(interleave)
        if self.interleave < 1:
            raise ValueError(f"interleave must be >= 1, got {interleave}")
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self._topology = topology
        self._layers = [spec.build() if isinstance(spec, LayerSpec) else spec
                        for spec in self.layer_specs]
        self.parts = self._partition_layers()

    def mpu(self):
        return self._topology

    def num_layers(self):
        return len(self._layers)

    def _count_layer_params(self):
        """Estimate per-layer parameter counts by abstract-evaluating init."""
        counts = []
        rng = jax.random.PRNGKey(0)
        for layer in self._layers:
            try:
                shapes = jax.eval_shape(layer.init, rng)
                counts.append(sum(int(jax.numpy.prod(jax.numpy.asarray(l.shape)))
                                  if l.shape else 1
                                  for l in jax.tree_util.tree_leaves(shapes)))
            except Exception:
                counts.append(1)
        return counts

    def _partition_layers(self):
        """Stage boundaries (reference pipe/module.py:358-413; methods
        `uniform` and `parameters`). With interleave > 1 the boundaries
        cut num_stages * interleave MODEL CHUNKS (parts has
        num_stages*interleave + 1 entries); chunk c lives on physical
        stage c % num_stages."""
        n_parts = self.num_stages * self.interleave
        method = self.partition_method.lower()
        if method == "uniform":
            parts = partition_uniform(len(self._layers), n_parts)
        elif method == "parameters":
            weights = self._count_layer_params()
            parts = partition_balanced([float(w) for w in weights],
                                       n_parts)
        elif method.startswith("type:"):
            # balance the count of layers whose class name matches the
            # regex (reference pipe/module.py:102,378-385)
            import re

            pattern = self.partition_method[len("type:"):]
            weights = [1.0 if re.search(pattern, type(l).__name__,
                                        re.IGNORECASE) else 0.0
                       for l in self._layers]
            if not any(weights):
                raise ValueError(
                    f"partition_method {self.partition_method!r} matched no "
                    f"layers (classes: "
                    f"{sorted({type(l).__name__ for l in self._layers})})")
            parts = partition_balanced(weights, n_parts)
        else:
            raise NotImplementedError(
                f"partition_method {self.partition_method!r}")
        logger.debug(f"pipeline partition: {parts}")
        return parts

    def stage_layers(self, stage_id: int) -> List[Any]:
        return self._layers[self.parts[stage_id]:self.parts[stage_id + 1]]

    # whole-model init/apply (used for single-stage and reference parity)
    def init(self, rng):
        """Params pytree: {"layers": [per-layer params or None], "tied":
        {key: shared params}}. Tied layers (TiedLayerSpec, reference
        pipe/module.py:415-428) share ONE param entry, so gradients
        accumulate into the single tied copy through autodiff — the
        functional equivalent of the reference's tied-grad allreduce."""
        tied = {}
        layer_params = []
        for layer, spec in zip(self._layers, self.layer_specs):
            rng, sub = jax.random.split(rng)
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = layer.init(sub)
                # {} (empty subtree) not None: None breaks strict pytree
                # zips against spec/sharding trees in the engine
                layer_params.append({})
            else:
                layer_params.append(layer.init(sub))
        return {"layers": layer_params, "tied": tied}

    def apply(self, params, x, rng=None, train=True):
        if isinstance(params, (list, tuple)):  # pre-tying flat format
            if any(isinstance(s, TiedLayerSpec) for s in self.layer_specs):
                raise ValueError(
                    "flat params list cannot express tied layers; use the "
                    "{'layers': ..., 'tied': ...} pytree from init()")
            layer_params, tied = list(params), {}
        else:
            layer_params, tied = params["layers"], params["tied"]
        for layer, spec, p in zip(self._layers, self.layer_specs,
                                  layer_params):
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if isinstance(spec, TiedLayerSpec):
                p = tied[spec.key]
                if spec.forward_fn is not None:
                    x = spec.forward_fn(layer, p, x)
                    continue
            x = layer.apply(p, x, rng=sub, train=train)
        return x

    def loss(self, params, batch, rng=None, train=True):
        inputs, labels = batch
        out = self.apply(params, inputs, rng=rng, train=train)
        if self.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        return self.loss_fn(out, labels)
