"""PipelineEngine — scheduled 1F1B pipeline-parallel training.

Reference: deepspeed/runtime/pipe/engine.py:52 (train_batch :264,
eval_batch :351, the instruction dispatch table :1280-1306 executing
pipe/schedule.py's TrainSchedule). This engine executes the same ISA
(runtime/pipe/schedule.py) over heterogeneous LayerSpec stacks:

* each pipeline stage owns a contiguous slice of the PipelineModule's
  layers, placed on its own device group (a slice of `jax.devices()`),
  with the micro batch data-sharded inside the group;
* the TrainSchedule instruction streams of ALL stages are executed from
  the single controller in dependency order (a Recv is runnable once the
  matching Send has been issued). Dispatch is asynchronous, so stage
  programs overlap on-device exactly as the eager NCCL interpreter's do —
  the 1F1B warmup/steady/cooldown order and per-stage buffer counts
  (TrainSchedule.num_pipe_buffers) are preserved;
* BackwardPass recomputes the stage forward under jax.vjp from the saved
  buffer input (per-stage activation checkpointing — only the buffer
  inputs are held, the reference's activation_checkpoint_interval
  behaviour with interval = stage length);
* TiedLayerSpec params (reference pipe/module.py:415-428) are owned by
  their first stage; ReduceTiedGrads ships the other stages' tied grads
  to the owner and OptimizerStep re-broadcasts the updated copy;
* SendActivation/SendGrad are `jax.device_put` reshards onto the next
  stage's device group (the single-controller analogue of p2p.py:31-75);
  on real multi-chip topologies XLA rides ICI for these transfers.

The SPMD GPipe executor (parallel/pipeline.py) remains the
compile-everything alternative for homogeneous stacked blocks; this engine
is the general one: heterogeneous layers, tied weights, 1F1B buffering.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec, Mesh

from ...utils.logging import log_dist, logger
from .. import checkpointing as ckpt_io
from ..engine import DeepSpeedEngine
from ..utils import has_overflow
from .compiler import (PipeInstrument, bind_program, compile_schedule,
                       schedule_occupancy)
from .module import PipelineModule, TiedLayerSpec
from .p2p import Channel, GlobalScalars, batch_shardable
from .schedule import (BackwardPass, ForwardPass, InterleavedTrainSchedule,
                       LoadMicroBatch, OptimizerStep, RecvActivation,
                       RecvGrad, ReduceGrads, ReduceTiedGrads,
                       SendActivation, SendGrad, TrainSchedule)


def _fsync_dir(path: str) -> None:
    """fsync a directory so a completed rename is durable — without this
    the file's rename can sit in the page cache after the data fsync,
    and a crash can publish `latest` over missing chunk files."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _StageRuntime:
    """Per-stage state: params, device placement, jitted programs, buffers."""

    def __init__(self, stage_id: int, layers, specs, devices,
                 is_last: bool, loss_fn, compute_dtype):
        self.stage_id = stage_id
        self.layers = layers
        self.specs = specs
        self.devices = devices
        self.is_last = is_last
        self.loss_fn = loss_fn
        self.compute_dtype = compute_dtype
        self.mesh = Mesh(np.asarray(devices), ("data",))
        self.replicated = NamedSharding(self.mesh, PartitionSpec())
        self.batch_sharding = NamedSharding(self.mesh, PartitionSpec("data"))

        # owned params: {"layers": [...], "tied": {key: ...}} — set by engine
        self.own: Any = None
        self.ro_tied: Dict[str, Any] = {}   # read-only copies of tied params
        self.opt_state: Any = None
        self.acc: Any = None                # fp32 grad acc, same struct as own
        self.acc_ro: Dict[str, Any] = {}    # grads for non-owned tied params

        # pipeline buffers
        self.x_in: Dict[int, Any] = {}      # buffer -> stage input
        self.rng_in: Dict[int, Any] = {}    # buffer -> rng key used in fwd
        self.y_out: Dict[int, Any] = {}     # buffer -> stage output
        self.dx_out: Dict[int, Any] = {}    # buffer -> grad wrt stage input
        self.labels: Dict[int, Any] = {}    # micro-batch id -> labels (last)
        self.losses: List[Any] = []
        self.fwd_count = 0
        self.bwd_count = 0

        self._build_programs()

    # -- pure stage functions ------------------------------------------

    def _forward_fn(self, own, ro_tied, x, rng, train):
        dtype = self.compute_dtype
        cast = lambda t: jax.tree_util.tree_map(
            lambda a: a.astype(dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, t)
        own = cast(own)
        ro_tied = cast(ro_tied)
        tied = dict(own["tied"])
        tied.update(ro_tied)
        for layer, spec, p in zip(self.layers, self.specs, own["layers"]):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            if isinstance(spec, TiedLayerSpec):
                p = tied[spec.key]
                if spec.forward_fn is not None:
                    x = spec.forward_fn(layer, p, x)
                    continue
            x = layer.apply(p, x, rng=sub, train=train)
        return x

    def _build_programs(self):
        fwd = self._forward_fn

        def fwd_train(own, ro, x, rng):
            return fwd(own, ro, x, rng, True)

        def fwd_eval(own, ro, x, rng):
            return fwd(own, ro, x, rng, False)

        self.fwd_j = jax.jit(fwd_train)
        self.fwd_eval_j = jax.jit(fwd_eval)

        if self.is_last:
            loss_fn = self.loss_fn

            def loss_of(own, ro, x, labels, rng):
                out = fwd(own, ro, x, rng, True)
                return loss_fn(out, labels)

            def loss_j(own, ro, x, labels, rng):
                return loss_of(own, ro, x, labels, rng)

            def bwd_last(own, ro, x, labels, rng, scale, acc, acc_ro):
                def scaled(o, r, xx):
                    return loss_of(o, r, xx, labels, rng) * scale

                _, pull = jax.vjp(scaled, own, ro, x)
                d_own, d_ro, dx = pull(jnp.ones((), jnp.float32))
                f32 = lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), t)
                new_acc = jax.tree_util.tree_map(jnp.add, acc, f32(d_own))
                new_ro = jax.tree_util.tree_map(jnp.add, acc_ro, f32(d_ro))
                return dx, new_acc, new_ro

            self.loss_j = jax.jit(loss_j)
            self.bwd_j = jax.jit(bwd_last, donate_argnums=(6, 7))

            def eval_loss(own, ro, x, labels, rng):
                out = fwd(own, ro, x, rng, False)
                return loss_fn(out, labels)

            self.eval_loss_j = jax.jit(eval_loss)
        else:
            def bwd_mid(own, ro, x, rng, dy, acc, acc_ro):
                def f(o, r, xx):
                    return fwd(o, r, xx, rng, True)

                _, pull = jax.vjp(f, own, ro, x)
                d_own, d_ro, dx = pull(dy)
                f32 = lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), t)
                new_acc = jax.tree_util.tree_map(jnp.add, acc, f32(d_own))
                new_ro = jax.tree_util.tree_map(jnp.add, acc_ro, f32(d_ro))
                return dx, new_acc, new_ro

            self.bwd_j = jax.jit(bwd_mid, donate_argnums=(5, 6))

    def build_apply(self, optimizer, clip):
        def detect(acc, denom):
            sq = sum(jnp.sum(jnp.square(g / denom))
                     for g in jax.tree_util.tree_leaves(acc))
            return sq, has_overflow(acc)

        # one fused pass: squared grad norm (for global clipping) + local
        # overflow flag. The engine ORs the flags across stages BEFORE
        # apply, so an overflow anywhere skips the step everywhere —
        # per-stage skipping would desynchronize the stages' parameters
        # from the non-pipelined run (reference fp16 semantics: the whole
        # step is skipped)
        self.detect_j = jax.jit(detect)

        def apply_step(own, opt_state, acc, lr, denom, clip_coef, overflow):
            # clip_coef carries the GLOBAL-norm clipping factor (computed
            # across all stages by the engine) — per-stage local clipping
            # would change the update direction vs the non-pipelined run
            grads = jax.tree_util.tree_map(
                lambda g: g * (clip_coef / denom), acc)
            new_own, new_opt = optimizer.update(grads, opt_state, own, lr=lr)
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
            new_own = sel(new_own, own)
            new_opt = sel(new_opt, opt_state)
            zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return new_own, new_opt, zero

        self.apply_j = jax.jit(apply_step, donate_argnums=(0, 1, 2))

    # -- placement helpers ---------------------------------------------

    def place_replicated(self, tree):
        return jax.device_put(tree, self.replicated)

    def place_batch(self, x):
        x = jnp.asarray(x)
        if batch_shardable(x.shape, len(self.devices)):
            return jax.device_put(x, self.batch_sharding)
        return jax.device_put(x, self.replicated)

    def zero_acc(self):
        f32z = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), t)
        self.acc = self.place_replicated(f32z(self.own))
        self.acc_ro = self.place_replicated(f32z(self.ro_tied))


class PipelineEngine(DeepSpeedEngine):
    """Executes the TrainSchedule ISA over a staged PipelineModule.

    Public API matches the reference PipelineEngine: train_batch pulls
    gradient_accumulation_steps micro batches from the iterator and runs
    the full 1F1B schedule + optimizer step; eval_batch runs the
    InferenceSchedule.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.micro_batches = self.gradient_accumulation_steps()
        module = self.module
        self._staged = (isinstance(module, PipelineModule)
                        and module.num_stages > 1
                        and len(jax.devices()) >= module.num_stages)
        if isinstance(module, PipelineModule) and module.num_stages > 1 \
                and not self._staged:
            logger.warning(
                f"PipelineModule wants {module.num_stages} stages but only "
                f"{len(jax.devices())} devices are visible; running "
                f"single-stage through the base engine")
        # multi-host: each process owns one physical stage and executes
        # only its own chunks; handoffs ride p2p.Channel collectives
        # (reference pipe/p2p.py:31-75). Also selectable single-process
        # via pipeline.use_p2p_channels for the driver's virtual-multichip
        # dryrun, which then exercises the multi-host code path verbatim.
        self._mh = bool(self._staged and (
            jax.process_count() > 1
            or self._config.pipe_use_p2p_channels))
        # the interpreted per-event walk is the parity oracle and the
        # bring-up executor; the compiled flat program is the default
        # (BENCH.md round-5: ~300 us of serialized Python per event)
        self._debug_schedule = bool(self._config.pipe_debug_schedule)
        self._pipe_prog = None
        self._bound_cache: Dict[Any, Any] = {}
        # telemetry: dispatch-time instrument (attached at bind time when
        # a RunMonitor is active) + cached schedule-bubble accounting
        self._pipe_instrument = None
        self._pipe_occupancy = None
        if self._staged:
            if self._mh:
                self._build_stages_mh()
            else:
                self._build_stages()

    # ------------------------------------------------------------------
    # staged construction
    # ------------------------------------------------------------------

    def _build_stages(self):
        module: PipelineModule = self.module
        P = module.num_stages
        v = getattr(module, "interleave", 1)
        self._n_phys = P
        self._v = v
        n_mc = P * v  # model chunks; chunk index mc = chunk_id * P + stage
        self._n_mc = n_mc
        devices = jax.devices()
        G = len(devices) // P
        clip = float(self._config.gradient_clipping or 0.0)

        # tied ownership: first MODEL CHUNK containing each tied key
        self._tied_owner, self._tied_users = self._tied_maps(module, n_mc)
        tied_owner, tied_users = self._tied_owner, self._tied_users

        # whole-model params were built by the base engine; redistribute.
        # self.stages is in MODEL-CHUNK order (= model order), so every
        # walk over it — eval, checkpointing, the params property — sees
        # the layers in sequence; interleaving only changes which device
        # group hosts each chunk (chunk mc -> physical stage mc % P).
        full = jax.tree_util.tree_map(np.asarray, self._params)
        # abstract param trees: _chunk_out_avals derives every chunk's
        # output aval from these (shared with the mh build; the compiled
        # executor resolves transfer layouts from avals at bind time)
        abst = lambda t: jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t)
        self._abs_layers = [abst(lp) for lp in full["layers"]]
        self._abs_tied = {k: abst(t) for k, t in full["tied"].items()}
        self._aval_cache: Dict[Any, Any] = {}
        self.stages: List[_StageRuntime] = []
        for mc in range(n_mc):
            s_phys = mc % P
            lo, hi = module.parts[mc], module.parts[mc + 1]
            rt = _StageRuntime(
                stage_id=mc,
                layers=module._layers[lo:hi],
                specs=module.layer_specs[lo:hi],
                devices=devices[s_phys * G:(s_phys + 1) * G],
                is_last=(mc == n_mc - 1),
                loss_fn=module.loss_fn,
                compute_dtype=self.compute_dtype)
            own_tied = {k: full["tied"][k] for k, o in tied_owner.items()
                        if o == mc}
            ro_tied = {k: full["tied"][k] for k, users in tied_users.items()
                       if mc in users and tied_owner[k] != mc}
            rt.own = rt.place_replicated(
                {"layers": full["layers"][lo:hi], "tied": own_tied})
            rt.ro_tied = rt.place_replicated(ro_tied)
            rt.opt_state = rt.place_replicated(
                self.optimizer.init(rt.own))
            rt.build_apply(self.optimizer, clip)
            rt.zero_acc()
            self.stages.append(rt)

        # the base engine's whole-tree placements are no longer the source
        # of truth; drop them so device memory holds one copy of the model
        self._params = None
        self._opt_state = None
        self._grad_acc = None
        log_dist(
            f"pipeline: {P} stages x {G} device(s)/stage"
            + (f" x {v} interleaved chunks" if v > 1 else "")
            + f", partitions {module.parts}, "
            f"tied={ {k: sorted(u) for k, u in tied_users.items()} }",
            ranks=[0])

    # ------------------------------------------------------------------
    # multi-host construction (one physical stage per process)
    # ------------------------------------------------------------------

    def _tied_maps(self, module, n_mc):
        def chunk_of_layer(i):
            for mc in range(n_mc):
                if module.parts[mc] <= i < module.parts[mc + 1]:
                    return mc
            return n_mc - 1

        tied_owner: Dict[str, int] = {}
        tied_users: Dict[str, set] = {}
        for i, spec in enumerate(module.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                mc = chunk_of_layer(i)
                tied_owner.setdefault(spec.key, mc)
                tied_users.setdefault(spec.key, set()).add(mc)
        return tied_owner, tied_users

    def _build_stages_mh(self):
        """Per-process stage build: this process materializes ONLY its own
        model chunks; adjacent chunks on other processes are reached
        through p2p.Channel collectives. Single-process (the dryrun), all
        chunks are local and the channels are purely local collectives —
        the code path is identical.

        Deliberate duplication note: the *_mh methods mirror the
        single-controller executor with channel transfers in place of
        direct device_put reshards. The channel path functionally
        subsumes the local one, but device_put is the cheaper transport
        within one process (no collective, no zero-row add), so both are
        kept; test_pipe_multihost.py pins them to identical losses, which
        is the guard against semantic drift between the copies."""
        module: PipelineModule = self.module
        P = module.num_stages
        v = getattr(module, "interleave", 1)
        self._n_phys = P
        self._v = v
        n_mc = P * v
        self._n_mc = n_mc
        nprocs = jax.process_count()
        me = jax.process_index()
        if nprocs > 1 and P != nprocs:
            raise ValueError(
                f"multi-host pipeline runs one physical stage per process: "
                f"num_stages={P} but process_count={nprocs}")
        clip = float(self._config.gradient_clipping or 0.0)

        # device group of each physical stage: the owning process's local
        # devices multi-host; equal slices of the local devices otherwise
        groups: Dict[int, list] = {}
        if nprocs > 1:
            for d in jax.devices():
                groups.setdefault(d.process_index, []).append(d)
            for q in groups:
                groups[q] = sorted(groups[q], key=lambda d: d.id)
            sizes = {len(g) for g in groups.values()}
            if len(sizes) != 1:
                raise ValueError(
                    f"uniform devices-per-process required, got "
                    f"{ {q: len(g) for q, g in groups.items()} }")
        else:
            devs = jax.devices()
            G = len(devs) // P
            groups = {q: devs[q * G:(q + 1) * G] for q in range(P)}
        self._groups = groups

        self._tied_owner, self._tied_users = self._tied_maps(module, n_mc)

        full = jax.tree_util.tree_map(np.asarray, self._params)
        abst = lambda t: jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t)
        self._abs_layers = [abst(lp) for lp in full["layers"]]
        self._abs_tied = {k: abst(t) for k, t in full["tied"].items()}

        def mine(mc):
            return nprocs == 1 or mc % P == me

        self._local: Dict[int, _StageRuntime] = {}
        for mc in range(n_mc):
            if not mine(mc):
                continue
            lo, hi = module.parts[mc], module.parts[mc + 1]
            rt = _StageRuntime(
                stage_id=mc,
                layers=module._layers[lo:hi],
                specs=module.layer_specs[lo:hi],
                devices=groups[mc % P],
                is_last=(mc == n_mc - 1),
                loss_fn=module.loss_fn,
                compute_dtype=self.compute_dtype)
            own_tied = {k: full["tied"][k]
                        for k, o in self._tied_owner.items() if o == mc}
            ro_tied = {k: full["tied"][k]
                       for k, users in self._tied_users.items()
                       if mc in users and self._tied_owner[k] != mc}
            rt.own = rt.place_replicated(
                {"layers": full["layers"][lo:hi], "tied": own_tied})
            rt.ro_tied = rt.place_replicated(ro_tied)
            rt.opt_state = rt.place_replicated(self.optimizer.init(rt.own))
            rt.build_apply(self.optimizer, clip)
            rt.zero_acc()
            self._local[mc] = rt

        self._params = None
        self._opt_state = None
        self._grad_acc = None

        # channels this process participates in (all of them when
        # single-process). Keyed by the SENDING chunk.
        def endpoint(a, b):
            return nprocs == 1 or me in (a % P, b % P)

        self._chan_act: Dict[int, Channel] = {}
        self._chan_grad: Dict[int, Channel] = {}
        for mc in range(n_mc - 1):
            if endpoint(mc, mc + 1):
                self._chan_act[mc] = Channel(groups[mc % P],
                                             groups[(mc + 1) % P])
        for mc in range(1, n_mc):
            if endpoint(mc, mc - 1):
                self._chan_grad[mc] = Channel(groups[mc % P],
                                              groups[(mc - 1) % P])
        self._chan_tied_grad: Dict[Any, Channel] = {}
        self._chan_tied_param: Dict[Any, Channel] = {}
        for key, users in self._tied_users.items():
            o = self._tied_owner[key]
            for u in sorted(users):
                if u == o or u % P == o % P:
                    continue
                if endpoint(u, o):
                    self._chan_tied_grad[(key, u)] = Channel(
                        groups[u % P], groups[o % P], replicate=True)
                    self._chan_tied_param[(key, u)] = Channel(
                        groups[o % P], groups[u % P], replicate=True)
        # checkpoint-save gather channels (tied owner -> process 0),
        # built once so periodic saves don't re-jit transfer programs.
        # Only needed multi-process (mh save is guarded on it), and an
        # existing owner->user param channel with the user on process 0
        # is reused rather than duplicated.
        self._chan_tied_save: Dict[str, Channel] = {}
        if nprocs > 1:
            for key in sorted(self._tied_owner):
                o = self._tied_owner[key]
                if o % P == 0 or not endpoint(o, 0):
                    continue
                reuse = next(
                    (self._chan_tied_param[(key, u)]
                     for u in sorted(self._tied_users[key])
                     if u % P == 0 and (key, u) in self._chan_tied_param),
                    None)
                self._chan_tied_save[key] = reuse or Channel(
                    groups[o % P], groups[0], replicate=True)
        self._gscal = GlobalScalars()
        self._aval_cache: Dict[Any, Any] = {}
        log_dist(
            f"pipeline (p2p channels): {P} stages over {nprocs} "
            f"process(es), local chunks {sorted(self._local)}, "
            f"partitions {module.parts}", ranks=[0])

    def _chunk_out_avals(self, x_aval):
        """Output aval of every model chunk, derived locally by abstract
        evaluation over the full layer stack — every process has the
        module description and the init-param shapes, so no shape
        handshake is needed (the reference sends meta tensors first,
        p2p.py:88-120)."""
        key = (tuple(x_aval.shape), str(x_aval.dtype))
        if key in self._aval_cache:
            return self._aval_cache[key]
        module: PipelineModule = self.module
        dtype = self.compute_dtype
        outs = []
        x = x_aval
        for mc in range(self._n_mc):
            lo, hi = module.parts[mc], module.parts[mc + 1]
            layers = module._layers[lo:hi]
            specs = module.layer_specs[lo:hi]

            def fwd(lparams, tied, xx, layers=layers, specs=specs):
                cast = lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, t)
                lparams = cast(lparams)
                tied = cast(tied)
                for layer, spec, p in zip(layers, specs, lparams):
                    if isinstance(spec, TiedLayerSpec):
                        p = tied[spec.key]
                        if spec.forward_fn is not None:
                            xx = spec.forward_fn(layer, p, xx)
                            continue
                    xx = layer.apply(p, xx, rng=None, train=False)
                return xx

            x = jax.eval_shape(fwd, self._abs_layers[lo:hi],
                               self._abs_tied, x)
            outs.append(x)
        self._aval_cache[key] = outs
        return outs

    def _simulate_order(self, streams):
        """Canonical global event order: replay the dependency-driven
        executor symbolically. Every process derives the SAME list, so all
        processes enter their common collectives in one global total
        order — the property that makes the channel handoffs deadlock-free
        regardless of how the 1F1B streams interleave."""
        P = len(streams)
        n = self._n_mc
        sent_act = [0] * n
        sent_grad = [0] * n
        recv_act = [0] * n
        recv_grad = [0] * n
        mail_act, mail_grad = set(), set()
        events, pos = [], [0] * P

        def ready(s, tick):
            for cmd in tick:
                if isinstance(cmd, RecvActivation):
                    mc = self._mc(s, cmd)
                    if (mc, recv_act[mc]) not in mail_act:
                        return False
                if isinstance(cmd, RecvGrad):
                    mc = self._mc(s, cmd)
                    if (mc, recv_grad[mc]) not in mail_grad:
                        return False
            return True

        while True:
            progressed = False
            done = True
            for s in range(P):
                while pos[s] < len(streams[s]):
                    tick = streams[s][pos[s]]
                    if not ready(s, tick):
                        break
                    for cmd in tick:
                        mc = self._mc(s, cmd)
                        if isinstance(cmd, SendActivation):
                            mail_act.add((mc + 1, sent_act[mc]))
                            sent_act[mc] += 1
                        elif isinstance(cmd, RecvActivation):
                            recv_act[mc] += 1
                        elif isinstance(cmd, SendGrad):
                            mail_grad.add((mc - 1, sent_grad[mc]))
                            sent_grad[mc] += 1
                        elif isinstance(cmd, RecvGrad):
                            recv_grad[mc] += 1
                        events.append((s, cmd))
                    pos[s] += 1
                    progressed = True
                if pos[s] < len(streams[s]):
                    done = False
            if done:
                return events
            if not progressed:
                raise RuntimeError(
                    f"pipeline schedule deadlock in simulation at {pos}")

    def _train_batch_mh(self, data_iter):
        if self.run_monitor is not None:
            self.run_monitor.step_start(self.global_steps)
        self.tput_timer.start()
        M = self.micro_batches
        # the multi-host data contract (same as the DP engines'): every
        # process's iterator yields the identical micro-batch stream; the
        # first chunk consumes inputs, the last consumes labels
        self._mb_cache = [self._next_micro_batch_from(data_iter)
                          for _ in range(M)]
        x0 = np.asarray(self._mb_cache[0][0])
        self._aval_out = self._chunk_out_avals(
            jax.ShapeDtypeStruct(x0.shape, x0.dtype))
        n = self._n_mc
        self._mail_act = {}
        self._mail_grad = {}
        self._sent_act_cnt = [0] * n
        self._sent_grad_cnt = [0] * n
        self._recv_act_cnt = [0] * n
        self._recv_grad_cnt = [0] * n
        self._load_cnt = 0
        self._batch_key = self._next_rng()
        streams = self._pipe_streams()
        self._arm_step_guards(streams)
        for rt in self._local.values():
            rt.losses = []
            rt.fwd_count = 0
            rt.bwd_count = 0
        for s, cmd in self._simulate_order(streams):
            self._dispatch_mh(s, cmd)
        self.micro_steps += M
        self.global_samples += self.train_batch_size()
        self.tput_timer.stop(report_speed=False)
        if self.steps_per_print() and \
                self.global_steps % self.steps_per_print() == 0:
            log_dist(f"pipe step={self.global_steps} "
                     f"loss={float(self._last_loss):.4f}", ranks=[0])
        self._emit_pipe_run_event()
        return self._last_loss

    def _dispatch_mh(self, s: int, cmd):
        mc = self._mc(s, cmd)
        rt = self._local.get(mc)
        b = getattr(cmd, "buffer_id", None)
        if isinstance(cmd, LoadMicroBatch):
            mb = self._load_cnt
            self._load_cnt += 1
            if rt is not None:
                rt.x_in[b] = rt.place_batch(self._mb_cache[mb][0])
        elif isinstance(cmd, RecvActivation):
            mb = self._recv_act_cnt[mc]
            self._recv_act_cnt[mc] += 1
            if rt is not None:
                rt.x_in[b] = self._mail_act.pop((mc, mb))
        elif isinstance(cmd, ForwardPass):
            if rt is None:
                return
            mb = rt.fwd_count
            rt.fwd_count += 1
            rng = jax.random.fold_in(self._batch_key, mb * self._n_mc + mc)
            rt.rng_in[b] = rng
            if rt.is_last:
                labels = rt.place_batch(np.asarray(self._mb_cache[mb][1]))
                rt.labels[mb] = labels
                rt.y_out[b] = None
                rt.losses.append(rt.loss_j(rt.own, rt.ro_tied, rt.x_in[b],
                                           labels, rng))
            else:
                rt.y_out[b] = rt.fwd_j(rt.own, rt.ro_tied, rt.x_in[b], rng)
        elif isinstance(cmd, SendActivation):
            mb = self._sent_act_cnt[mc]
            self._sent_act_cnt[mc] += 1
            chan = self._chan_act.get(mc)
            if chan is None:
                return
            y = rt.y_out.pop(b) if rt is not None else None
            res = chan.transfer(self._aval_out[mc], y)
            if res is not None:
                self._mail_act[(mc + 1, mb)] = res
        elif isinstance(cmd, RecvGrad):
            mb = self._recv_grad_cnt[mc]
            self._recv_grad_cnt[mc] += 1
            if rt is not None:
                rt.dy_in = getattr(rt, "dy_in", {})
                rt.dy_in[b] = self._mail_grad.pop((mc, mb))
        elif isinstance(cmd, BackwardPass):
            if rt is None:
                return
            mb = rt.bwd_count
            rt.bwd_count += 1
            x = rt.x_in.pop(b)
            rng = rt.rng_in.pop(b)
            if rt.is_last:
                scale = self._scaler_state["cur_scale"]
                labels = rt.labels.pop(mb)
                dx, rt.acc, rt.acc_ro = rt.bwd_j(
                    rt.own, rt.ro_tied, x, labels, rng, scale,
                    rt.acc, rt.acc_ro)
            else:
                dy = rt.dy_in.pop(b)
                dx, rt.acc, rt.acc_ro = rt.bwd_j(
                    rt.own, rt.ro_tied, x, rng, dy, rt.acc, rt.acc_ro)
            rt.dx_out[b] = dx
        elif isinstance(cmd, SendGrad):
            mb = self._sent_grad_cnt[mc]
            self._sent_grad_cnt[mc] += 1
            chan = self._chan_grad.get(mc)
            if chan is None:
                return
            dx = rt.dx_out.pop(b) if rt is not None else None
            # dx has the aval of this chunk's INPUT = previous chunk's out
            res = chan.transfer(self._aval_out[mc - 1], dx)
            if res is not None:
                self._mail_grad[(mc - 1, mb)] = res
        elif isinstance(cmd, ReduceTiedGrads):
            self._reduce_tied_grads_mh()
        elif isinstance(cmd, ReduceGrads):
            pass  # within-stage dp reduction is implicit in the jitted loss
        elif isinstance(cmd, OptimizerStep):
            self._pipe_optimizer_step_mh()
        else:
            raise NotImplementedError(f"instruction {cmd!r}")

    def _next_micro_batch_from(self, data_iter):
        batch = next(data_iter)
        if isinstance(batch, dict):
            return batch["input_ids"], batch.get("labels")
        return batch[0], batch[1]

    def _reduce_tied_grads_mh(self):
        """Ship tied grads to the owner chunk: local pairs by direct add,
        cross-process pairs through their dedicated channel, all walked in
        the same sorted order on every process.  Runs at the LAST
        canonical ReduceTiedGrads (see _arm_step_guards)."""
        self._tied_pending -= 1
        if self._tied_pending > 0:
            return
        f32 = lambda t: jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, jnp.float32), t)
        for key in sorted(self._tied_users):
            users = self._tied_users[key]
            o = self._tied_owner[key]
            ort = self._local.get(o)
            for u in sorted(users):
                if u == o:
                    continue
                if u % self._n_phys == o % self._n_phys:
                    # same process (interleave): direct add
                    if ort is not None:
                        urt = self._local[u]
                        g = jax.device_put(urt.acc_ro[key], ort.replicated)
                        ort.acc["tied"][key] = jax.tree_util.tree_map(
                            jnp.add, ort.acc["tied"][key], g)
                    continue
                chan = self._chan_tied_grad.get((key, u))
                if chan is None:
                    continue
                val = (self._local[u].acc_ro[key]
                       if chan.is_src and u in self._local else None)
                res = chan.transfer(f32(self._abs_tied[key]), val)
                if res is not None and ort is not None:
                    ort.acc["tied"][key] = jax.tree_util.tree_map(
                        jnp.add, ort.acc["tied"][key], res)

    def _pipe_optimizer_step_mh(self):
        self._step_pending -= 1
        if self._step_pending > 0:
            return
        M = self.micro_batches
        denom = jnp.asarray(self._scaler_state["cur_scale"] * M,
                            jnp.float32)
        cur_lr = self._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        clip = float(self._config.gradient_clipping or 0.0)
        loss_sum = total_sq = ov = 0.0
        for mc in sorted(self._local):
            rt = self._local[mc]
            sq, o = rt.detect_j(rt.acc, denom)
            total_sq += float(sq)
            ov += float(np.asarray(o))
            if rt.is_last and rt.losses:
                loss_sum = float(jnp.sum(jnp.stack(rt.losses)))
        red = self._gscal.sum([loss_sum, total_sq, ov])
        loss = red[0] / M
        overflow = red[2] > 0
        clip_coef = 1.0
        if clip > 0.0:
            norm = float(np.sqrt(red[1]))
            if np.isfinite(norm) and norm > clip:
                clip_coef = clip / (norm + 1e-6)
        ovf = jnp.asarray(bool(overflow))
        for mc in sorted(self._local):
            rt = self._local[mc]
            rt.own, rt.opt_state, rt.acc = rt.apply_j(
                rt.own, rt.opt_state, rt.acc,
                lr, denom, jnp.asarray(clip_coef, jnp.float32), ovf)
            rt.acc_ro = jax.tree_util.tree_map(jnp.zeros_like, rt.acc_ro)
        self._scaler_state = self.loss_scaler.jit_update(
            self._scaler_state, jnp.asarray(bool(overflow)))
        self.global_steps += 1
        if overflow:
            self._skipped_steps += 1
            log_dist(f"pipeline overflow: skipped step, new loss scale "
                     f"{float(self._scaler_state['cur_scale'])}", ranks=[0])
        else:
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self._refresh_tied_copies_mh()
        self._last_loss = jnp.asarray(loss, jnp.float32)
        self._emit_monitor_scalars()

    def _refresh_tied_copies_mh(self):
        for key in sorted(self._tied_users):
            users = self._tied_users[key]
            o = self._tied_owner[key]
            ort = self._local.get(o)
            for u in sorted(users):
                if u == o:
                    continue
                if u % self._n_phys == o % self._n_phys:
                    if ort is not None:
                        self._local[u].ro_tied[key] = jax.device_put(
                            ort.own["tied"][key],
                            self._local[u].replicated)
                    continue
                chan = self._chan_tied_param.get((key, u))
                if chan is None:
                    continue
                val = (ort.own["tied"][key]
                       if chan.is_src and ort is not None else None)
                res = chan.transfer(self._abs_tied[key], val)
                if res is not None and u in self._local:
                    self._local[u].ro_tied[key] = res

    # ------------------------------------------------------------------
    # schedule execution
    # ------------------------------------------------------------------

    def _mc(self, s: int, cmd) -> int:
        """Model-chunk index a command targets: interleaved instructions
        carry chunk_id (chunk c of physical stage s is model chunk
        c * n_phys + s); plain 1F1B instructions default to chunk 0."""
        return getattr(cmd, "chunk_id", 0) * self._n_phys + s

    def _pipe_streams(self):
        """Per-stage instruction streams for one train_batch — the ONE
        place both executors (and the schedule compiler) get them."""
        M = self.micro_batches
        P = self._n_phys
        if self._v > 1:
            return [list(InterleavedTrainSchedule(M, P, s, self._v).steps())
                    for s in range(P)]
        return [list(TrainSchedule(M, P, s).steps()) for s in range(P)]

    def _arm_step_guards(self, streams):
        """Per-batch countdowns for the interpreted walk: tied-grad
        reduction and the optimizer step must run at their LAST canonical
        occurrence (each stage's stream carries one of each; only at the
        last one — stage 0's, whose cooldown backward is the globally
        final backward — are every stage's gradients complete).  Acting
        at the first occurrence, as earlier rounds did, applied the
        optimizer while later events were still accumulating: those
        gradients were dropped from the step and leaked into the next
        batch's accumulators."""
        cmds = [c for st in streams for tick in st
                for c in (tick if isinstance(tick, (list, tuple))
                          else (tick,))]
        self._tied_pending = sum(isinstance(c, ReduceTiedGrads)
                                 for c in cmds)
        self._step_pending = sum(isinstance(c, OptimizerStep)
                                 for c in cmds)

    def _compiled_steps(self, x_aval):
        """Bound flat-program executor for this engine's schedule and the
        given input aval (cached — lowering runs once per engine, binding
        once per input shape)."""
        key = (tuple(x_aval.shape), str(x_aval.dtype))
        steps = self._bound_cache.get(key)
        if steps is None:
            if self._pipe_prog is None:
                events = self._simulate_order(self._pipe_streams())
                self._pipe_prog = compile_schedule(
                    events, self._mc, self._n_mc, self.micro_batches)
            if self.run_monitor is not None and \
                    self._pipe_instrument is None:
                self._pipe_instrument = PipeInstrument()
            steps = bind_program(self, self._pipe_prog,
                                 self._chunk_out_avals(x_aval),
                                 instrument=self._pipe_instrument)
            self._bound_cache[key] = steps
        return steps

    def _pipe_occupancy_stats(self):
        """Schedule-tick bubble/occupancy per physical stage (cached —
        pure function of (M, stages, interleave))."""
        if self._pipe_occupancy is None:
            self._pipe_occupancy = schedule_occupancy(self._pipe_streams())
        return self._pipe_occupancy

    def _emit_pipe_run_event(self):
        """Per-batch telemetry event for the pipeline executors: step
        bookkeeping (loss/lr/scale via the base emitter) + pipeline
        bubble accounting + measured per-op dispatch time + the comm
        counter deltas picked up by step_end."""
        rm = self.run_monitor
        if rm is None:
            return
        if rm.sync_timing and self._last_loss is not None:
            jax.block_until_ready(self._last_loss)
        pipe: Dict[str, Any] = {"occupancy": self._pipe_occupancy_stats()}
        if self._pipe_prog is not None:
            pipe["events"] = len(self._pipe_prog.events)
            pipe["source_events"] = self._pipe_prog.n_source_events
        if self._pipe_instrument is not None:
            pipe.update(self._pipe_instrument.drain())
        self._emit_run_event(pipe=pipe)

    def _train_batch_compiled(self, data_iter):
        """Default train_batch executor: an index walk over the bound
        flat program (compiler.py) — no schedule regeneration, no
        dependency re-simulation, no isinstance dispatch, no counter or
        mail-dict bookkeeping per event.  `pipeline.debug_schedule: true`
        selects the interpreted per-event oracle instead; the two are
        pinned bit-identical by tests/test_pipe_compiler.py."""
        if self.run_monitor is not None:
            self.run_monitor.step_start(self.global_steps)
        self.tput_timer.start()
        M = self.micro_batches
        self._mb_cache = [self._next_micro_batch_from(data_iter)
                          for _ in range(M)]
        x0 = np.asarray(self._mb_cache[0][0])
        steps = self._compiled_steps(
            jax.ShapeDtypeStruct(x0.shape, x0.dtype))
        self._batch_key = self._next_rng()
        # the flat program emits exactly one OP_TIED and one OP_STEP (at
        # the canonical LAST occurrence — all backwards precede them)
        self._tied_pending = 1
        self._step_pending = 1
        for rt in (self._local.values() if self._mh else self.stages):
            rt.losses = []
        for f in steps:
            f()
        if not self._mh:
            # mh sets _last_loss inside _pipe_optimizer_step_mh (global
            # reduction); single-controller averages the local losses the
            # same way the interpreted walk does
            last = self.stages[-1]
            self._last_loss = (jnp.mean(jnp.stack(last.losses))
                               if last.losses else None)
        self.micro_steps += M
        self.global_samples += self.train_batch_size()
        self.tput_timer.stop(report_speed=False)
        if self.steps_per_print() and \
                self.global_steps % self.steps_per_print() == 0:
            log_dist(f"pipe step={self.global_steps} "
                     f"loss={float(self._last_loss):.4f}", ranks=[0])
        self._emit_pipe_run_event()
        return self._last_loss

    def train_batch(self, data_iter=None):
        if not self._staged:
            return super().train_batch(data_iter)
        if data_iter is None:
            if self.training_dataloader is None:
                raise ValueError("train_batch needs data_iter or training_data")
            if not hasattr(self, "_train_iter"):
                from ..dataloader import RepeatingLoader
                self._train_iter = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._train_iter
        if not self._debug_schedule:
            return self._train_batch_compiled(data_iter)
        if self._mh:
            return self._train_batch_mh(data_iter)

        if self.run_monitor is not None:
            self.run_monitor.step_start(self.global_steps)
        self.tput_timer.start()
        M = self.micro_batches
        n_rt = len(self.stages)
        self._mail_act: Dict[Any, Any] = {}
        self._mail_grad: Dict[Any, Any] = {}
        self._data_iter = data_iter
        self._batch_key = self._next_rng()
        self._recv_act_cnt = [0] * n_rt
        self._recv_grad_cnt = [0] * n_rt
        self._sent_act_cnt = [0] * n_rt
        self._sent_grad_cnt = [0] * n_rt
        for rt in self.stages:
            rt.losses = []
            rt.fwd_count = 0
            rt.bwd_count = 0

        # the single-controller executor consumes the same canonical
        # event order the multi-host executor derives — one dependency
        # resolver for both (see _simulate_order)
        streams = self._pipe_streams()
        self._arm_step_guards(streams)
        for s, cmd in self._simulate_order(streams):
            self._dispatch_train(s, cmd)

        last = self.stages[-1]
        loss = jnp.mean(jnp.stack(last.losses)) if last.losses else None
        self.micro_steps += M
        self.global_samples += self.train_batch_size()
        self._last_loss = loss
        self.tput_timer.stop(report_speed=False)
        if self.steps_per_print() and \
                self.global_steps % self.steps_per_print() == 0:
            log_dist(f"pipe step={self.global_steps} "
                     f"loss={float(loss):.4f}", ranks=[0])
        self._emit_pipe_run_event()
        return loss

    # -- instruction handlers ------------------------------------------

    def _dispatch_train(self, s: int, cmd):
        mc = self._mc(s, cmd)
        rt = self.stages[mc]
        b = getattr(cmd, "buffer_id", None)
        if isinstance(cmd, LoadMicroBatch):
            inputs, labels = self._next_micro_batch()
            mb = rt.fwd_count
            rt.x_in[b] = rt.place_batch(inputs)
            self.stages[-1].labels[mb] = labels
        elif isinstance(cmd, RecvActivation):
            mb = self._recv_act_cnt[mc]
            self._recv_act_cnt[mc] += 1
            rt.x_in[b] = self._mail_act.pop((mc, mb))
        elif isinstance(cmd, ForwardPass):
            mb = rt.fwd_count
            rt.fwd_count += 1
            rng = jax.random.fold_in(self._batch_key,
                                     mb * len(self.stages) + mc)
            rt.rng_in[b] = rng
            if rt.is_last:
                labels = rt.place_batch(rt.labels[mb])
                rt.labels[mb] = labels
                rt.y_out[b] = None
                rt.losses.append(rt.loss_j(rt.own, rt.ro_tied, rt.x_in[b],
                                           labels, rng))
            else:
                rt.y_out[b] = rt.fwd_j(rt.own, rt.ro_tied, rt.x_in[b], rng)
        elif isinstance(cmd, SendActivation):
            # consecutive model chunks are adjacent in self.stages, so the
            # interleaved wrap (last stage chunk c -> stage 0 chunk c+1)
            # and the plain next-stage hop are both mc + 1
            nxt = self.stages[mc + 1]
            mb = self._sent_act_cnt[mc]
            self._sent_act_cnt[mc] += 1
            y = rt.y_out.pop(b)
            self._mail_act[(mc + 1, mb)] = jax.device_put(
                y, nxt.batch_sharding
                if batch_shardable(y.shape, len(nxt.devices))
                else nxt.replicated)
        elif isinstance(cmd, RecvGrad):
            mb = self._recv_grad_cnt[mc]
            self._recv_grad_cnt[mc] += 1
            rt.dy_in = getattr(rt, "dy_in", {})
            rt.dy_in[b] = self._mail_grad.pop((mc, mb))
        elif isinstance(cmd, BackwardPass):
            mb = rt.bwd_count
            rt.bwd_count += 1
            x = rt.x_in.pop(b)
            rng = rt.rng_in.pop(b)
            if rt.is_last:
                scale = self._scaler_state["cur_scale"]
                labels = rt.labels.pop(mb)
                dx, rt.acc, rt.acc_ro = rt.bwd_j(
                    rt.own, rt.ro_tied, x, labels, rng, scale,
                    rt.acc, rt.acc_ro)
            else:
                dy = rt.dy_in.pop(b)
                dx, rt.acc, rt.acc_ro = rt.bwd_j(
                    rt.own, rt.ro_tied, x, rng, dy, rt.acc, rt.acc_ro)
            rt.dx_out[b] = dx
        elif isinstance(cmd, SendGrad):
            prev = self.stages[mc - 1]
            mb = self._sent_grad_cnt[mc]
            self._sent_grad_cnt[mc] += 1
            dx = rt.dx_out.pop(b)
            self._mail_grad[(mc - 1, mb)] = jax.device_put(
                dx, prev.batch_sharding
                if batch_shardable(dx.shape, len(prev.devices))
                else prev.replicated)
        elif isinstance(cmd, ReduceTiedGrads):
            self._reduce_tied_grads()
        elif isinstance(cmd, ReduceGrads):
            pass  # within-stage dp reduction is implicit in the jitted loss
        elif isinstance(cmd, OptimizerStep):
            self._pipe_optimizer_step()
        else:
            raise NotImplementedError(f"instruction {cmd!r}")

    def _next_micro_batch(self):
        return self._next_micro_batch_from(self._data_iter)

    def _reduce_tied_grads(self):
        """Ship non-owner tied grads to the owner stage and sum (the
        single-controller form of reference pipe/engine.py's
        _all_reduce_tied_weight_gradients).  Runs at the LAST canonical
        ReduceTiedGrads (see _arm_step_guards)."""
        self._tied_pending -= 1
        if self._tied_pending > 0:
            return
        for key, users in self._tied_users.items():
            owner = self.stages[self._tied_owner[key]]
            total = owner.acc["tied"][key]
            for s in sorted(users):
                rt = self.stages[s]
                if rt.stage_id == owner.stage_id:
                    continue
                g = jax.device_put(rt.acc_ro[key], owner.replicated)
                total = jax.tree_util.tree_map(jnp.add, total, g)
            owner.acc["tied"][key] = total

    def _pipe_optimizer_step(self):
        self._step_pending -= 1
        if self._step_pending > 0:
            return
        denom = jnp.asarray(
            self._scaler_state["cur_scale"] * self.micro_batches,
            jnp.float32)
        cur_lr = self._current_lr()
        lr = None if cur_lr is None else jnp.asarray(cur_lr, jnp.float32)
        clip = float(self._config.gradient_clipping or 0.0)
        # detect BEFORE apply: global norm for clipping + global overflow,
        # so every stage applies (or skips) the step together (reference
        # pipe engine all-reduces both over pipeline ranks)
        detects = [rt.detect_j(rt.acc, denom) for rt in self.stages]
        total_sq = sum(float(sq) for sq, _ in detects)
        overflow = bool(np.any([np.asarray(ov) for _, ov in detects]))
        clip_coef = 1.0
        if clip > 0.0:
            norm = float(np.sqrt(total_sq))
            if np.isfinite(norm) and norm > clip:
                clip_coef = clip / (norm + 1e-6)
        ovf = jnp.asarray(overflow)
        for rt in self.stages:
            rt.own, rt.opt_state, rt.acc = rt.apply_j(
                rt.own, rt.opt_state, rt.acc,
                lr, denom, jnp.asarray(clip_coef, jnp.float32), ovf)
            rt.acc_ro = jax.tree_util.tree_map(
                jnp.zeros_like, rt.acc_ro)
        self._scaler_state = self.loss_scaler.jit_update(
            self._scaler_state, jnp.asarray(overflow))
        self.global_steps += 1
        if overflow:
            # all stages selected their old params in-jit; undo bookkeeping
            self._skipped_steps += 1
            log_dist(f"pipeline overflow: skipped step, new loss scale "
                     f"{float(self._scaler_state['cur_scale'])}", ranks=[0])
        else:
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self._refresh_tied_copies()
        self._emit_monitor_scalars()

    def _refresh_tied_copies(self):
        for key, users in self._tied_users.items():
            owner = self.stages[self._tied_owner[key]]
            for s in sorted(users):
                rt = self.stages[s]
                if rt.stage_id == owner.stage_id:
                    continue
                rt.ro_tied[key] = jax.device_put(
                    owner.own["tied"][key], rt.replicated)

    @property
    def params(self):
        """Full {'layers': ..., 'tied': ...} pytree reassembled from the
        per-stage placements (the base property would return the nulled
        whole-tree placement in staged mode — exports/params access must
        see the live stage weights)."""
        if not self._staged:
            return DeepSpeedEngine.params.fget(self)
        module: PipelineModule = self.module
        layers = [None] * module.num_layers()
        tied = {}
        if self._mh:
            # process-local view: layers this process does not own stay
            # None (multi-host processes cannot address remote params)
            for mc, rt in self._local.items():
                lo = module.parts[mc]
                for j, lp in enumerate(rt.own["layers"]):
                    layers[lo + j] = lp
                tied.update(rt.own["tied"])
            return {"layers": layers, "tied": tied}
        for s, rt in enumerate(self.stages):
            lo = module.parts[s]
            for j, lp in enumerate(rt.own["layers"]):
                layers[lo + j] = lp
            tied.update(rt.own["tied"])
        return {"layers": layers, "tied": tied}

    # ------------------------------------------------------------------
    # multi-host checkpointing: reference-layout per-layer files, one
    # writer per owned piece (the sharded-checkpoint rule, engine.py
    # one-writer-per-piece), reassembled into the SAME on-disk format the
    # single-process engine writes, so checkpoints are portable between
    # multi-host and single-host runs
    # ------------------------------------------------------------------

    def _mh_write(self, path, payload):
        from flax import serialization

        # write-tmp + fsync + rename: the pre-`latest` barrier only orders
        # processes, not the page cache — a host crash after the barrier
        # must not leave `latest` pointing at torn chunk files
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialization.msgpack_serialize(
                jax.tree_util.tree_map(np.asarray, payload)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # the containing dir is fsynced ONCE per save (before the
        # pre-`latest` barrier), not here — one barrier, not one per file

    def _mh_read(self, path):
        from flax import serialization

        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def _chunk_optim_name(self, ckpt_dir, mc):
        return os.path.join(ckpt_dir, f"pipe_optim_chunk{mc:02d}.msgpack")

    def _read_local_chunks(self, ckpt_dir, tied, load_optimizer_states):
        """Read every local chunk's layer files, owned tied params AND
        optimizer chunk states in one pass BEFORE mutating any runtime
        state, so any unreadable file leaves the engine untouched."""
        module: PipelineModule = self.module
        staged = {}
        single_optim = None  # single-host-written optimizer fallback
        for mc in sorted(self._local):
            lo, hi = module.parts[mc], module.parts[mc + 1]
            layers = [jax.tree_util.tree_map(
                jnp.asarray,
                self._mh_read(ckpt_io.layer_ckpt_name(ckpt_dir, i)))
                for i in range(lo, hi)]
            own_tied = {k: jax.tree_util.tree_map(jnp.asarray, tied[k])
                        for k, o in self._tied_owner.items() if o == mc}
            restored = None
            if load_optimizer_states:
                cpath = self._chunk_optim_name(ckpt_dir, mc)
                if os.path.isfile(cpath):
                    restored = self._mh_read(cpath)
                else:  # single-host-written checkpoint: list layout
                    if single_optim is None:
                        opath = ckpt_io.optim_ckpt_name(ckpt_dir)
                        if os.path.isfile(opath):
                            so = self._mh_read(opath)
                            if isinstance(so, dict) and \
                                    so.get("__dstpu_ckpt_v2__"):
                                # v2 wrapper: payload under "state",
                                # sharded leaves in rank piece files
                                pieces = ckpt_io._load_rank_pieces(
                                    ckpt_dir, 0)
                                so = so.get("state")
                                if pieces:
                                    so = ckpt_io._reassemble(so, pieces)
                            single_optim = so or {}
                    if single_optim and single_optim.get(
                            "pipeline_parts") == list(module.parts):
                        restored = single_optim["optimizer_state"][mc]
                if restored is None:
                    # loud, not silent: resuming with fresh Adam moments
                    # is a numerics regression the caller must know about
                    logger.warning(
                        f"load_checkpoint: no optimizer state for model "
                        f"chunk {mc} in {ckpt_dir}; its optimizer "
                        f"re-initializes from scratch")
            staged[mc] = (layers, own_tied, restored)
        return staged

    def _save_checkpoint_mh(self, save_dir, tag=None, client_state=None,
                            save_latest=True):
        if tag is None:
            tag = f"global_step{self.global_steps}"
        module: PipelineModule = self.module
        me = jax.process_index()
        ckpt_dir = os.path.join(save_dir, str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)

        for mc in sorted(self._local):
            rt = self._local[mc]
            lo = module.parts[mc]
            # layers only: tied params are gathered separately below, so
            # a whole-tree D2H would copy the (large) tied tables twice
            layers_np = jax.tree_util.tree_map(np.asarray,
                                               rt.own["layers"])
            for j, lp in enumerate(layers_np):
                self._mh_write(ckpt_io.layer_ckpt_name(ckpt_dir, lo + j),
                               lp)
            state = rt.opt_state
            if hasattr(self.optimizer, "serialize_state"):
                state = self.optimizer.serialize_state(state)
            self._mh_write(self._chunk_optim_name(ckpt_dir, mc), state)

        # tied params: ship each owner's copy to process 0 so the module
        # skeleton carries the full tied dict (single-host-loadable);
        # every process constructs/enters the channels in sorted order
        tied_full = {}
        for key in sorted(self._tied_owner):
            o = self._tied_owner[key]
            if o % self._n_phys == 0:
                if me == 0:
                    tied_full[key] = jax.tree_util.tree_map(
                        np.asarray, self._local[o].own["tied"][key])
                continue
            chan = self._chan_tied_save.get(key)
            if chan is not None:
                val = (self._local[o].own["tied"][key]
                       if o in self._local else None)
                res = chan.transfer(self._abs_tied[key], val)
                if me == 0:
                    tied_full[key] = jax.tree_util.tree_map(np.asarray,
                                                            res)

        if me == 0:
            L = module.num_layers()
            model_state = {
                "module": {"layers": [None] * L, "tied": tied_full,
                           "num_layers": L},
                "lr_scheduler": (self.lr_scheduler.state_dict()
                                 if self.lr_scheduler is not None else None),
                "loss_scaler": {k: np.asarray(v)
                                for k, v in self._scaler_state.items()},
                "rng_key": np.asarray(self._rng_key),
                "pipeline_parts": list(module.parts),
                **self._client_state(client_state),
            }
            self._mh_write(ckpt_io.model_ckpt_name(ckpt_dir), model_state)
        # make this process's renames durable (single directory barrier
        # for all files written above) AND the <tag> dirent itself (lives
        # in save_dir — per-host filesystems each need it), then the
        # collective barrier: every process's files are on disk before
        # rank 0 publishes `latest`
        _fsync_dir(ckpt_dir)
        _fsync_dir(save_dir)
        self._gscal.sum(np.zeros(1, np.float32))
        if me == 0:
            # the collective barrier above IS this writer's commit
            # rendezvous: every process's files are durable, so publish
            # the commit marker (keeps mh tags first-class for
            # read_latest_tag's committed-tag resolution — a marker-less
            # tag in a marker-bearing dir would be skipped as torn)
            ckpt_io.write_commit_marker(
                save_dir, tag,
                meta={"world_size": jax.process_count(),
                      "pipeline_parts": list(module.parts),
                      "zero_stage": self.zero_optimization_stage()},
                world_size=jax.process_count())
        if save_latest and me == 0:
            # atomic publish: write-tmp-then-rename so a crash mid-write
            # can't leave a truncated `latest`
            latest = os.path.join(save_dir, "latest")
            tmp = latest + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(tag))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, latest)
            _fsync_dir(save_dir)
        log_dist(f"saved multi-host pipeline checkpoint {tag} to "
                 f"{ckpt_dir}", ranks=[0])
        return True

    def _load_checkpoint_mh(self, load_dir, tag=None,
                            load_optimizer_states=True,
                            load_lr_scheduler_states=True):
        module: PipelineModule = self.module
        if tag is None:
            tag = ckpt_io.read_latest_tag(load_dir)
            if tag is None:
                logger.warning(f"load_checkpoint: no latest in {load_dir}")
                return None, {}
        ckpt_dir = os.path.join(load_dir, str(tag))
        mpath = ckpt_io.model_ckpt_name(ckpt_dir)
        if not os.path.isfile(mpath):
            logger.warning(f"load_checkpoint: {mpath} not found")
            return None, {}
        model_state = self._mh_read(mpath)
        tied = (model_state.get("module") or {}).get("tied", {})
        if model_state.get("pipeline_parts") not in (None,
                                                     list(module.parts)):
            raise ValueError(
                f"checkpoint pipeline_parts "
                f"{model_state.get('pipeline_parts')} != current "
                f"{list(module.parts)}; repartitioned multi-host reload "
                f"is unsupported")
        try:
            staged = self._read_local_chunks(ckpt_dir, tied,
                                             load_optimizer_states)
        except Exception as e:
            # partial/torn checkpoint (a writer died before the barrier:
            # missing files raise FileNotFoundError, truncated msgpack
            # raises unpack errors) or layer/tied mismatch — keep the
            # warn-and-return contract, don't crash training scripts;
            # NOTHING was mutated (the staging pass reads everything
            # before the loop below touches runtime state)
            logger.warning(f"load_checkpoint: unreadable/incomplete "
                           f"checkpoint in {ckpt_dir}: {e!r}")
            return None, {}
        for mc in sorted(self._local):
            rt = self._local[mc]
            layers, own_tied, restored = staged[mc]
            rt.own = rt.place_replicated({"layers": layers,
                                          "tied": own_tied})
            if restored is not None:
                if hasattr(self.optimizer, "deserialize_state"):
                    restored = self.optimizer.deserialize_state(
                        restored, rt.own)
                rt.opt_state = rt.place_replicated(
                    jax.tree_util.tree_map(jnp.asarray, restored))
            rt.zero_acc()
        self._refresh_tied_copies_mh()
        return self._finish_pipe_load(model_state, ckpt_dir,
                                      load_lr_scheduler_states)

    def _finish_pipe_load(self, model_state, ckpt_dir,
                          load_lr_scheduler_states):
        """Shared tail of both pipeline loaders: scaler/scheduler/rng/
        counter restore + client-state extraction (one copy, no drift)."""
        if model_state.get("loss_scaler") is not None:
            self._scaler_state = {k: jnp.asarray(v) for k, v in
                                  model_state["loss_scaler"].items()}
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                model_state.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(model_state["lr_scheduler"])
        if model_state.get("rng_key") is not None:
            self._rng_key = jnp.asarray(model_state["rng_key"])
        self.global_steps = int(model_state.get("global_steps", 0))
        self.global_samples = int(model_state.get("global_samples", 0))
        self.micro_steps = int(model_state.get("micro_steps", 0))
        self.loaded_checkpoint_tag = os.path.basename(ckpt_dir)
        client_state = {k: v for k, v in model_state.items()
                        if k not in ("module", "lr_scheduler",
                                     "loss_scaler", "pipeline_parts")}
        return ckpt_dir, client_state

    def _runtimes(self) -> List[_StageRuntime]:
        """Stage runtimes in model-chunk order. In channel (mh) mode this
        is only valid when every chunk is local (single process)."""
        if not self._mh:
            return self.stages
        return [self._local[mc] for mc in sorted(self._local)]

    def memory_status(self, tag: str = ""):
        """Per-stage device-memory report (reference pipe/engine.py:
        1195-1243 memory_status): bytes in use / peak per stage's device
        group, plus live pipeline-buffer counts."""
        if not self._staged:
            from ...utils.timer import SynchronizedWallClockTimer

            log_dist(f"MEMSTATS {tag} "
                     f"{SynchronizedWallClockTimer.memory_usage()}",
                     ranks=[0])
            return
        for rt in (self._local.values() if self._mh else self.stages):
            used = peak = 0
            for d in rt.devices:
                stats = (d.memory_stats() or {}) \
                    if hasattr(d, "memory_stats") else {}
                used += stats.get("bytes_in_use", 0) or 0
                peak += stats.get("peak_bytes_in_use", 0) or 0
            log_dist(
                f"MEMSTATS {tag} stage {rt.stage_id}: "
                f"in_use {used / 2**30:.2f} GB | peak {peak / 2**30:.2f} GB"
                f" | buffers: x_in={len(rt.x_in)} y_out={len(rt.y_out)} "
                f"dx_out={len(rt.dx_out)}", ranks=[0])

    # ------------------------------------------------------------------
    # eval / inference
    # ------------------------------------------------------------------

    def eval_batch(self, data_iter):
        if not self._staged:
            batch = next(data_iter) if hasattr(data_iter, "__next__") \
                else data_iter
            return super().eval_batch(batch)
        if not hasattr(data_iter, "__next__"):
            data_iter = iter([data_iter])
        if self._mh:
            return self._eval_batch_mh(data_iter)
        self._mail_act = {}
        self._mail_grad = {}
        self._data_iter = data_iter
        self._batch_key = self._next_rng()
        M = self.micro_batches
        P = len(self.stages)
        for rt in self.stages:
            rt.losses = []
            rt.fwd_count = 0
        # forward-only streams; consume as many micro batches as available
        losses = []
        for mb in range(M):
            try:
                inputs, labels = self._next_micro_batch()
            except StopIteration:
                break
            x = self.stages[0].place_batch(inputs)
            for rt in self.stages[:-1]:
                x = rt.fwd_eval_j(rt.own, rt.ro_tied, x, None)
                nxt = self.stages[rt.stage_id + 1]
                x = jax.device_put(
                    x, nxt.batch_sharding
                    if batch_shardable(x.shape, len(nxt.devices))
                    else nxt.replicated)
            last = self.stages[-1]
            losses.append(last.eval_loss_j(
                last.own, last.ro_tied, x, last.place_batch(labels), None))
        return jnp.mean(jnp.stack(losses)) if losses else None

    def _eval_batch_mh(self, data_iter):
        """Forward-only walk in model-chunk order; every process enters
        the activation channels in the same (mc, mb) order, the loss is
        summed globally at the end."""
        M = self.micro_batches
        loss_sum = 0.0
        count = 0
        for _ in range(M):
            try:
                inputs, labels = self._next_micro_batch_from(data_iter)
                got = 1.0
            except StopIteration:
                got = 0.0
            # Contract check BEFORE the chunk walk: every process must see
            # the identical data stream.  If iterators diverge, the process
            # that got data would enter channel collectives its peer never
            # joins and the job would hang — sum a got-data flag and raise
            # on mismatch instead (cheap: one tiny collective per mb).
            total_got = float(self._gscal.sum([got])[0])
            if total_got == 0.0:
                break
            if total_got != float(self._gscal.nprocs):
                raise RuntimeError(
                    f"eval data iterators diverged across processes: "
                    f"{int(total_got)}/{self._gscal.nprocs} processes had a "
                    f"micro batch at index {count} — every process must be "
                    f"given an identical data stream")
            count += 1
            avals = self._chunk_out_avals(jax.ShapeDtypeStruct(
                np.asarray(inputs).shape, np.asarray(inputs).dtype))
            x = None
            first = self._local.get(0)
            if first is not None:
                x = first.place_batch(inputs)
            for mc in range(self._n_mc):
                rt = self._local.get(mc)
                if rt is not None:
                    if rt.is_last:
                        loss_sum += float(rt.eval_loss_j(
                            rt.own, rt.ro_tied, x,
                            rt.place_batch(np.asarray(labels)), None))
                        continue
                    x = rt.fwd_eval_j(rt.own, rt.ro_tied, x, None)
                if mc < self._n_mc - 1:
                    chan = self._chan_act.get(mc)
                    if chan is not None:
                        res = chan.transfer(
                            avals[mc], x if rt is not None else None)
                        if res is not None:
                            x = res
        red = self._gscal.sum([loss_sum])
        return (jnp.asarray(red[0] / count, jnp.float32)
                if count else None)

    def inference_batch(self, data_iter):
        """One-shot forward over the pipeline stages (EleutherAI
        addition, reference pipe/engine.py:422).

        This is the reference-era SINGLE-BATCH path: one fixed batch,
        full forward, no KV cache, no admission — every token of every
        sequence recomputes the whole prefix.  For actual serving
        (autoregressive decode, continuous batching, paged KV,
        latency/throughput accounting) use `deepspeed_tpu.serving`
        (docs/tutorials/serving.md): `ServeEngine.submit()` /
        `generate()` is the supported inference path, pinned
        token-identical to `models/generation.generate`.  This method
        stays for batch-scoring workloads (perplexity eval over a
        fixed set) where recompute is acceptable and the pipeline
        stages are already resident — the two paths must not silently
        diverge, hence the one-time pointer logged below."""
        from ...utils.logging import warning_once

        warning_once(
            "pipe.engine.inference_batch is the reference-era one-shot "
            "forward (full prefix recompute, no batching across "
            "requests); for serving use deepspeed_tpu.serving "
            "(ServeEngine — continuous batching over a paged KV cache, "
            "docs/tutorials/serving.md)")
        batch = next(data_iter) if hasattr(data_iter, "__next__") else data_iter
        inputs = batch[0] if isinstance(batch, (tuple, list)) else batch
        if not self._staged:
            return self.module.apply(self._params, inputs, train=False)
        if self._mh:
            avals = self._chunk_out_avals(jax.ShapeDtypeStruct(
                np.asarray(inputs).shape, np.asarray(inputs).dtype))
            x = None
            if 0 in self._local:
                x = self._local[0].place_batch(inputs)
            for mc in range(self._n_mc):
                rt = self._local.get(mc)
                if rt is not None:
                    x = rt.fwd_eval_j(rt.own, rt.ro_tied, x, None)
                if mc < self._n_mc - 1:
                    chan = self._chan_act.get(mc)
                    if chan is not None:
                        res = chan.transfer(
                            avals[mc], x if rt is not None else None)
                        if res is not None:
                            x = res
            # the final output lives on the last chunk's owner; other
            # processes return None (the reference's last-rank-only output)
            return x if (self._n_mc - 1) in self._local else None
        x = self.stages[0].place_batch(inputs)
        for rt in self.stages:
            x = rt.fwd_eval_j(rt.own, rt.ro_tied, rt.place_batch(x), None)
        return x

    # ------------------------------------------------------------------
    # checkpointing: per-layer files (reference pipe/module.py:520-578)
    # ------------------------------------------------------------------

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        if not self._staged:
            return super().save_checkpoint(save_dir, tag, client_state,
                                           save_latest)
        if self._mh and jax.process_count() > 1:
            return self._save_checkpoint_mh(save_dir, tag, client_state,
                                            save_latest)
        if tag is None:
            tag = f"global_step{self.global_steps}"
        module: PipelineModule = self.module
        layer_states = {}
        tied_states = {}
        for s, rt in enumerate(self._runtimes()):
            lo = module.parts[s]
            own_np = jax.tree_util.tree_map(np.asarray, rt.own)
            for j, lp in enumerate(own_np["layers"]):
                layer_states[lo + j] = lp
            tied_states.update(own_np["tied"])
        model_state = {
            "module": {"layers": [layer_states.get(i)
                                  for i in range(module.num_layers())],
                       "tied": tied_states},
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None else None),
            "loss_scaler": {k: np.asarray(v)
                            for k, v in self._scaler_state.items()},
            "rng_key": np.asarray(self._rng_key),
            **self._client_state(client_state),
        }
        def pack_opt(rt):
            state = rt.opt_state
            if hasattr(self.optimizer, "serialize_state"):
                # namedtuple optimizer states (optax) can't ride msgpack
                state = self.optimizer.serialize_state(state)
            return jax.tree_util.tree_map(np.asarray, state)

        optim_state = {
            "optimizer_state": [pack_opt(rt) for rt in self._runtimes()],
            "pipeline_parts": list(module.parts),
            "zero_stage": self.zero_optimization_stage(),
            "offload": False,
        }
        ckpt_io.save_checkpoint_state(
            save_dir, tag, model_state, optim_state, save_latest=save_latest,
            layer_states=layer_states, tied_states=tied_states)
        return True

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        if not self._staged:
            return super().load_checkpoint(load_dir, tag, load_module_strict,
                                           load_optimizer_states,
                                           load_lr_scheduler_states)
        if self._mh and jax.process_count() > 1:
            return self._load_checkpoint_mh(load_dir, tag,
                                            load_optimizer_states,
                                            load_lr_scheduler_states)
        try:
            ckpt_dir, model_state, optim_state = \
                ckpt_io.load_checkpoint_state(load_dir, tag)
        except FileNotFoundError as e:
            logger.warning(f"load_checkpoint: {e}")
            return None, {}
        module: PipelineModule = self.module
        if optim_state is None:
            # multi-host-written checkpoint: per-chunk optim files instead
            # of the single zero_pp_rank file — reassemble the list layout
            chunk_files = [self._chunk_optim_name(ckpt_dir, mc)
                           for mc in range(len(module.parts) - 1)]
            if all(os.path.isfile(p) for p in chunk_files):
                optim_state = {
                    "optimizer_state": [self._mh_read(p)
                                        for p in chunk_files],
                    "pipeline_parts": model_state.get(
                        "pipeline_parts", list(module.parts)),
                }
        layers = model_state["module"]["layers"]
        tied = model_state["module"]["tied"]
        for s, rt in enumerate(self._runtimes()):
            lo, hi = module.parts[s], module.parts[s + 1]
            own_tied = {k: tied[k] for k, o in self._tied_owner.items()
                        if o == s}
            rt.own = rt.place_replicated(
                {"layers": [jax.tree_util.tree_map(jnp.asarray, l)
                            for l in layers[lo:hi]],
                 "tied": own_tied})
            if load_optimizer_states and optim_state is not None and \
                    optim_state.get("pipeline_parts") == list(module.parts):
                restored = optim_state["optimizer_state"][s]
                if hasattr(self.optimizer, "deserialize_state"):
                    restored = self.optimizer.deserialize_state(
                        restored, rt.own)
                rt.opt_state = rt.place_replicated(
                    jax.tree_util.tree_map(jnp.asarray, restored))
            rt.zero_acc()
        if self._mh:
            self._refresh_tied_copies_mh()
        else:
            self._refresh_tied_copies()
        return self._finish_pipe_load(model_state, ckpt_dir,
                                      load_lr_scheduler_states)
