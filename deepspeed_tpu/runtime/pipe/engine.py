"""PipelineEngine — scheduled pipeline-parallel training.

Reference: deepspeed/runtime/pipe/engine.py:52 (train_batch :264,
eval_batch :351, instruction dispatch :1280-1306).

Current state: executes the PipelineModule end-to-end through the base
engine (correct for pipe=1 meshes); the instruction-schedule executor over
the `pipe` mesh axis (1F1B via ppermute handoffs) builds on
pipe/schedule.py and lands with the pipeline milestone.
"""

from __future__ import annotations

from ..engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.micro_batches = self.gradient_accumulation_steps()

    def train_batch(self, data_iter=None):
        return super().train_batch(data_iter)

    def eval_batch(self, data_iter):
        batch = next(data_iter) if hasattr(data_iter, "__next__") else data_iter
        return super().eval_batch(batch)

    def inference_batch(self, data_iter):
        """EleutherAI addition (reference pipe/engine.py:422)."""
        batch = next(data_iter) if hasattr(data_iter, "__next__") else data_iter
        inputs = batch[0] if isinstance(batch, (tuple, list)) else batch
        return self.module.apply(self._params, inputs, train=False)
