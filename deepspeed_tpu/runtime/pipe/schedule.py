"""Pipeline instruction schedules — the reference's clean ISA abstraction.

Reference: deepspeed/runtime/pipe/schedule.py (TrainSchedule :182,
InferenceSchedule :129, DataParallelSchedule :292; instruction classes
:336-474). Each schedule yields, per "clock step", a list of instructions
for one stage. The reference interprets these eagerly with NCCL p2p
(pipe/engine.py:1280-1306); here the SPMD executor
(deepspeed_tpu/parallel/pipeline.py) compiles the whole schedule into one
jitted scan-over-ticks program — the ISA remains the portable description
(and drives schedule-shape tests mirroring tests/unit/test_pipe_schedule.py).
"""

from __future__ import annotations

from abc import ABC, abstractmethod


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule(ABC):
    """Generates stage-local instruction streams (reference schedule.py:12)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list of PipeInstructions per clock step."""

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def _buffer_idx(self, micro_batch_id) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only stream (reference schedule.py:129)."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        for mb in range(self.micro_batches):
            b = self._buffer_idx(mb)
            cmds = []
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(b))
            else:
                cmds.append(RecvActivation(b))
            cmds.append(ForwardPass(b))
            if not self.is_last_stage:
                cmds.append(SendActivation(b))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady one-forward-one-backward, cooldown
    backwards, then grad reduction + optimizer step (reference
    schedule.py:182-289's interleaved even/odd schedule has the same
    steady-state occupancy; this is the canonical 1F1B formulation)."""

    def num_pipe_buffers(self):
        # in-flight activations per stage: distance to the last stage + 1
        return min(self.stages - self.stage_id, self.micro_batches) or 1

    def _fwd_cmds(self, mb):
        b = self._buffer_idx(mb)
        cmds = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(b))
        else:
            cmds.append(RecvActivation(b))
        cmds.append(ForwardPass(b))
        if not self.is_last_stage:
            cmds.append(SendActivation(b))
        return cmds

    def _bwd_cmds(self, mb):
        b = self._buffer_idx(mb)
        cmds = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(b))
        cmds.append(BackwardPass(b))
        if not self.is_first_stage:
            cmds.append(SendGrad(b))
        return cmds

    def steps(self):
        warmup = min(self.stages - self.stage_id - 1, self.micro_batches)
        steady = self.micro_batches - warmup
        fwd = bwd = 0
        for _ in range(warmup):
            yield self._fwd_cmds(fwd)
            fwd += 1
        for _ in range(steady):
            yield self._fwd_cmds(fwd)
            fwd += 1
            yield self._bwd_cmds(bwd)
            bwd += 1
        for _ in range(warmup):
            yield self._bwd_cmds(bwd)
            bwd += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference schedule.py:292)."""

    def num_pipe_buffers(self):
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
        yield [ReduceGrads(), OptimizerStep()]
