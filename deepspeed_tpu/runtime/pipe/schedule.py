"""Pipeline instruction schedules — the reference's clean ISA abstraction.

Reference: deepspeed/runtime/pipe/schedule.py (TrainSchedule :182,
InferenceSchedule :129, DataParallelSchedule :292; instruction classes
:336-474). Each schedule yields, per "clock step", a list of instructions
for one stage. The reference interprets these eagerly with NCCL p2p
(pipe/engine.py:1280-1306); here the SPMD executor
(deepspeed_tpu/parallel/pipeline.py) compiles the whole schedule into one
jitted scan-over-ticks program — the ISA remains the portable description
(and drives schedule-shape tests mirroring tests/unit/test_pipe_schedule.py).
"""

from __future__ import annotations

from abc import ABC, abstractmethod


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule(ABC):
    """Generates stage-local instruction streams (reference schedule.py:12)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list of PipeInstructions per clock step."""

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def _buffer_idx(self, micro_batch_id) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only stream (reference schedule.py:129)."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        for mb in range(self.micro_batches):
            b = self._buffer_idx(mb)
            cmds = []
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(b))
            else:
                cmds.append(RecvActivation(b))
            cmds.append(ForwardPass(b))
            if not self.is_last_stage:
                cmds.append(SendActivation(b))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady one-forward-one-backward, cooldown
    backwards, then grad reduction + optimizer step (reference
    schedule.py:182-289's interleaved even/odd schedule has the same
    steady-state occupancy; this is the canonical 1F1B formulation)."""

    def num_pipe_buffers(self):
        # in-flight activations per stage: distance to the last stage + 1
        return min(self.stages - self.stage_id, self.micro_batches) or 1

    def _fwd_cmds(self, mb):
        b = self._buffer_idx(mb)
        cmds = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(b))
        else:
            cmds.append(RecvActivation(b))
        cmds.append(ForwardPass(b))
        if not self.is_last_stage:
            cmds.append(SendActivation(b))
        return cmds

    def _bwd_cmds(self, mb):
        b = self._buffer_idx(mb)
        cmds = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(b))
        cmds.append(BackwardPass(b))
        if not self.is_first_stage:
            cmds.append(SendGrad(b))
        return cmds

    def steps(self):
        warmup = min(self.stages - self.stage_id - 1, self.micro_batches)
        steady = self.micro_batches - warmup
        fwd = bwd = 0
        for _ in range(warmup):
            yield self._fwd_cmds(fwd)
            fwd += 1
        for _ in range(steady):
            yield self._fwd_cmds(fwd)
            fwd += 1
            yield self._bwd_cmds(bwd)
            bwd += 1
        for _ in range(warmup):
            yield self._bwd_cmds(bwd)
            bwd += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class InterleavedTrainSchedule(PipeSchedule):
    """Megatron-style interleaved (virtual-stage) 1F1B: each physical
    stage owns `chunks` model chunks (chunk c = model chunk c*stages +
    stage_id), shrinking the pipeline bubble by ~1/chunks. Beyond the
    reference (its schedule.py:182 has no virtual stages); the ordering
    follows the public interleaved-1F1B formulation: virtual micro-batch
    index k maps to model chunk (k // stages) % chunks (reversed for
    backward) and micro batch stages*(k // (stages*chunks)) + k % stages,
    with warmup min((stages - stage_id - 1)*2 + (chunks - 1)*stages,
    total) forwards before the 1F1B steady state.

    Instructions carry chunk_id; micro_batches must divide by stages."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int,
                 chunks: int):
        super().__init__(micro_batches, stages, stage_id)
        if micro_batches % stages != 0:
            raise ValueError(
                f"interleaved schedule requires micro_batches "
                f"({micro_batches}) divisible by stages ({stages})")
        assert chunks >= 1
        self.chunks = chunks

    # note: buffer_ids here are raw micro-batch ids (the engine keys its
    # buffer dicts per model chunk, so no wrap is needed); the in-flight
    # count per chunk is still bounded by the warmup depth

    def _chunk_of(self, k: int, forward: bool) -> int:
        cid = (k // self.stages) % self.chunks
        return cid if forward else self.chunks - 1 - cid

    def _mb_of(self, k: int) -> int:
        group = self.stages * self.chunks
        return self.stages * (k // group) + k % self.stages

    def _is_first_model_chunk(self, c: int) -> bool:
        return self.stage_id == 0 and c == 0

    def _is_last_model_chunk(self, c: int) -> bool:
        return self.stage_id == self.stages - 1 and c == self.chunks - 1

    def _fwd_cmds(self, c: int, mb: int):
        cmds = []
        if self._is_first_model_chunk(c):
            cmds.append(LoadMicroBatch(mb, chunk_id=c))
        else:
            cmds.append(RecvActivation(mb, chunk_id=c))
        cmds.append(ForwardPass(mb, chunk_id=c))
        if not self._is_last_model_chunk(c):
            cmds.append(SendActivation(mb, chunk_id=c))
        return cmds

    def _bwd_cmds(self, c: int, mb: int):
        cmds = []
        if not self._is_last_model_chunk(c):
            cmds.append(RecvGrad(mb, chunk_id=c))
        cmds.append(BackwardPass(mb, chunk_id=c))
        if not self._is_first_model_chunk(c):
            cmds.append(SendGrad(mb, chunk_id=c))
        return cmds

    def steps(self):
        total = self.micro_batches * self.chunks
        warmup = min((self.stages - self.stage_id - 1) * 2
                     + (self.chunks - 1) * self.stages, total)
        fwd_k = bwd_k = 0
        for _ in range(warmup):
            yield self._fwd_cmds(self._chunk_of(fwd_k, True),
                                 self._mb_of(fwd_k))
            fwd_k += 1
        for _ in range(total - warmup):
            yield self._fwd_cmds(self._chunk_of(fwd_k, True),
                                 self._mb_of(fwd_k))
            fwd_k += 1
            yield self._bwd_cmds(self._chunk_of(bwd_k, False),
                                 self._mb_of(bwd_k))
            bwd_k += 1
        for _ in range(warmup):
            yield self._bwd_cmds(self._chunk_of(bwd_k, False),
                                 self._mb_of(bwd_k))
            bwd_k += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference schedule.py:292)."""

    def num_pipe_buffers(self):
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
        yield [ReduceGrads(), OptimizerStep()]
