"""ZeRO-Offload runtime — optimizer state in host RAM or on NVMe.

Reference: ZeRO-Offload keeps fp32 master params + Adam moments on the host
and runs the native CPU-Adam there (stage2.py:1450-1461 grad offload +
DeepSpeedCPUAdam; NVMe swapping via swap_tensor/* state machines + the aio
engine). TPU-native equivalent:

* device keeps only working weights (bf16/fp32) — NO optimizer state in HBM;
* at each boundary the fp32 grad shards transfer host-side, the vectorized
  C++ Adam (csrc/adam/cpu_adam.cpp, OpenMP+SIMD) updates the host masters,
  and the refreshed weights upload back to HBM;
* with offload device "nvme", the Adam moments additionally page through
  the native aio engine (csrc/aio/ds_aio.cpp) to local SSD, so host RAM
  holds only one leaf's moments at a time — the ZeRO-Infinity pattern
  (reference swap_tensor/optimizer_utils.py) without its hook machinery.

The step overlaps three phases (reference overlap analogue:
stage2.py:680-745 grad D2H tiling + cpu_adam.h:23 param-copy overlap):

1. D2H: `copy_to_host_async` is issued for EVERY grad leaf up front, so
   all transfers are in flight before the first host read blocks;
2. compute: the native Adam (csrc/adam/cpu_adam.cpp) updates leaf i while
   leaf i+1's transfer completes;
3. H2D: updated weights are emitted directly in the bf16 wire format
   (`ds_adam_step_bf16` round-to-nearest-even) and `jax.device_put` is
   dispatched asynchronously — the upload of leaf i rides alongside the
   Adam compute of leaf i+1, at half the fp32 wire size.

The overflow check requires all grads host-side before the first update
(a later-leaf inf must skip the WHOLE step, reference loss-scaler
semantics), so phase 1 is a barrier — but a concurrent one.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import log_dist
from ..utils import clip_grad_norm  # noqa: F401 (device-path counterpart)


class NvmeStateStore:
    """Pages per-leaf Adam moments to local SSD via the native aio engine."""

    def __init__(self, nvme_path: str, n_threads: int = 4):
        import shutil
        import uuid
        import weakref

        from ...ops.aio import AsyncIOHandle

        # instance-unique, not just pid-scoped: two runtimes in one
        # process (checkpoint save + fresh reload) must not clobber each
        # other's moment files; removed when the store is collected
        self.dir = os.path.join(
            nvme_path,
            f"dstpu_offload_{os.getpid()}_{uuid.uuid4().hex[:8]}")
        os.makedirs(self.dir, exist_ok=True)
        self.handle = AsyncIOHandle(n_threads=n_threads)
        self._initialized = set()
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, self.dir, True)

    def _path(self, key: int, name: str) -> str:
        return os.path.join(self.dir, f"leaf{key}_{name}.bin")

    def has(self, key: int) -> bool:
        """True iff moments for this leaf have ever been stored (load()
        fabricates zeros for unknown keys — callers that must distinguish
        'fresh' from 'zero' ask first)."""
        return key in self._initialized

    def load(self, key: int, n: int):
        m = np.zeros(n, np.float32)
        v = np.zeros(n, np.float32)
        if key in self._initialized:
            self.handle.async_pread(m, self._path(key, "m"))
            self.handle.async_pread(v, self._path(key, "v"))
            self.handle.wait()
        return {"m": m, "v": v}

    def store(self, key: int, state):
        self.handle.async_pwrite(state["m"], self._path(key, "m"))
        self.handle.async_pwrite(state["v"], self._path(key, "v"))
        self.handle.wait()  # buffers freed after this returns
        self._initialized.add(key)


class CPUOffloadRuntime:
    """Host-side optimizer step for the engine's offload path."""

    def __init__(self, params, hparams: dict, adam_w_mode: bool = True,
                 nvme_path: Optional[str] = None, param_dtype=jnp.float32,
                 param_shardings=None):
        from ...ops.adam.cpu_adam import HostAdam

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [l.shape for l in leaves]
        self.param_dtype = param_dtype
        self.param_shardings = (jax.tree_util.tree_leaves(param_shardings)
                                if param_shardings is not None else None)
        # fp32 host masters
        self.masters: List[np.ndarray] = [
            np.asarray(l, np.float32).ravel().copy() for l in leaves]
        self.adam = HostAdam(
            lr=hparams.get("lr", 1e-3),
            betas=tuple(hparams.get("betas", (0.9, 0.999))),
            eps=hparams.get("eps", 1e-8),
            weight_decay=hparams.get("weight_decay", 0.0),
            adam_w_mode=adam_w_mode)
        self.nvme: Optional[NvmeStateStore] = None
        if nvme_path is not None:
            self.nvme = NvmeStateStore(nvme_path)
            log_dist(f"ZeRO-Offload: Adam moments paging to {nvme_path}",
                     ranks=[0])
        else:
            log_dist("ZeRO-Offload: optimizer state in host RAM", ranks=[0])

    def num_elements(self) -> int:
        return sum(m.size for m in self.masters)

    def step(self, grad_leaves, denom: float, lr: Optional[float],
             clip: float = 0.0):
        """grad_leaves: device fp32 grad accumulators (unscaled by denom
        here on host). Returns (new device param leaves, overflow, norm)."""
        # issue ALL D2H copies before the first blocking read — transfers
        # run concurrently, np.asarray then only waits for its own leaf
        for g in grad_leaves:
            try:
                g.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # non-jax input (e.g. tests passing numpy)
        host_grads = [np.asarray(g, np.float32).ravel()
                      for g in grad_leaves]
        inv = 1.0 / denom
        overflow = not all(np.isfinite(g).all() for g in host_grads)
        if overflow:
            return None, True, 0.0

        sq = sum(float(np.dot(g, g)) for g in host_grads) * inv * inv
        norm = float(np.sqrt(sq))
        scale = inv
        if clip > 0.0 and norm > clip:
            scale = inv * (clip / (norm + 1e-6))

        import ml_dtypes
        emit_bf16 = self.param_dtype == jnp.bfloat16
        self.adam.begin_step()
        new_leaves = []
        for i, (master, g) in enumerate(zip(self.masters, host_grads)):
            # jax host views are read-only; one writable scaled copy
            g = np.multiply(g, np.float32(scale), dtype=np.float32)
            g = np.ascontiguousarray(g)
            if self.nvme is not None:
                self.adam._state[i] = self.nvme.load(i, master.size)
            if emit_bf16:
                # native kernel emits the bf16 wire directly — half the
                # upload bytes, no separate fp32->bf16 host pass
                wire = np.empty(master.size, np.uint16)
                self.adam.update_flat(i, master, g, lr=lr, out_bf16=wire)
                host_out = wire.view(ml_dtypes.bfloat16).reshape(
                    self.shapes[i])
            else:
                self.adam.update_flat(i, master, g, lr=lr)
                host_out = master.reshape(self.shapes[i])
                target = np.dtype(self.param_dtype)
                if host_out.dtype != target:  # e.g. fp16 working weights
                    host_out = host_out.astype(target)
            if self.nvme is not None:
                self.nvme.store(i, self.adam._state.pop(i))
            # async dispatch: leaf i uploads while leaf i+1 computes
            if self.param_shardings is not None:
                dev = jax.device_put(host_out, self.param_shardings[i])
            else:
                dev = jnp.asarray(host_out, dtype=self.param_dtype)
            new_leaves.append(dev)
        params = jax.tree_util.tree_unflatten(self.treedef, new_leaves)
        return params, False, norm

    # checkpoint parity ------------------------------------------------
    def state_dict(self):
        sd = self.adam.state_dict()
        if self.nvme is not None:
            # moments live on SSD between steps — page them back for
            # serialization (step() pops each leaf into the NvmeStateStore)
            sd["state"] = {
                str(i): {k: v.copy()
                         for k, v in self.nvme.load(i, m.size).items()}
                for i, m in enumerate(self.masters)}
        sd["masters"] = [m.copy() for m in self.masters]
        return sd

    def load_state_dict(self, sd):
        self.adam.load_state_dict({k: sd[k] for k in ("step", "state")})
        self.masters = [np.asarray(m, np.float32) for m in sd["masters"]]
        if self.nvme is not None:
            # write through to the fresh (pid-scoped) store so step()'s
            # nvme.load sees the restored moments, not zeros
            for key, st in list(self.adam._state.items()):
                self.nvme.store(int(key), st)
            self.adam._state = {}
