"""ZeRO config keys (reference: deepspeed/runtime/zero/constants.py).

Stages keep their reference meaning; on TPU they resolve to sharding specs
over the data axis rather than bucketed NCCL machinery:
  0 = disabled, 1 = optimizer states, 2 = + gradients, 3 = + parameters.
"""

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

# Bucket/overlap knobs (reference zero/constants.py). overlap_comm /
# contiguous_gradients stay accepted-for-parity (XLA latency-hides and
# lays out buffers itself); reduce_bucket_size and reduce_scatter are
# HONORED since the bucketed gradient wire landed: with
# "comm": {"gradient_reduction": "bucketed"} the BucketPlan
# (runtime/comm/bucketing.py) caps fused buckets at reduce_bucket_size
# elements, and reduce_scatter selects the ZeRO>=2 psum_scatter lowering.
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True
ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True
ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = False
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500000000  # elements, not bytes
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500000000
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED = "allgather_size"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

# Offload
ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS = "cpu_offload_params"
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT = False
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY = "cpu_offload_use_pin_memory"
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT = False
ZERO_OPTIMIZATION_OFFLOAD_PARAM = "offload_param"
ZERO_OPTIMIZATION_OFFLOAD_PARAM_DEFAULT = None
ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER = "offload_optimizer"
ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER_DEFAULT = None

# Stage-3 knobs
ZERO_OPTIMIZATION_SUB_GROUP_SIZE = "sub_group_size"
ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT = 1000000000000
ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT = 1000000000
ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT = 1000000000
ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT = 50000000
ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 100000
ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = \
    "stage3_gather_fp16_weights_on_model_save"
ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT = False
# qwZ (ZeRO++ arXiv:2306.10209): the stage-3 parameter all-gather moves
# blockwise-quantized blocks + fp16 scales instead of full-width
# weights; the master copy stays full precision.  false | true (int8) |
# "int8" | "int4".  Block size rides comm.quant_block_size.
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS = "quantized_weights"
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT = False

ZERO_FORMAT = """
ZeRO optimization should be enabled as:
"zero_optimization": {
  "stage": [0|1|2|3],
  "offload_param": {...},
  "offload_optimizer": {...},
  ...
}
"""

# offload sub-dict keys (reference zero/offload_constants.py)
OFFLOAD_DEVICE = "device"
OFFLOAD_CPU_DEVICE = "cpu"
OFFLOAD_NVME_DEVICE = "nvme"
OFFLOAD_NONE_DEVICE = "none"
OFFLOAD_NVME_PATH = "nvme_path"
OFFLOAD_BUFFER_COUNT = "buffer_count"
OFFLOAD_BUFFER_SIZE = "buffer_size"
OFFLOAD_MAX_IN_CPU = "max_in_cpu"
OFFLOAD_PIN_MEMORY = "pin_memory"
OFFLOAD_PIPELINE = "pipeline"
OFFLOAD_PIPELINE_READ = "pipeline_read"
OFFLOAD_PIPELINE_WRITE = "pipeline_write"
OFFLOAD_FAST_INIT = "fast_init"
