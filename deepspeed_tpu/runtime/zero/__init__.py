from .config import DeepSpeedZeroConfig  # noqa: F401
