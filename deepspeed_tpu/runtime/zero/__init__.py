from .config import DeepSpeedZeroConfig  # noqa: F401
from .partition_parameters import GatheredParameters, Init  # noqa: F401
from .tiling import TiledLinear  # noqa: F401
