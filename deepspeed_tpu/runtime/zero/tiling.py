"""TiledLinear — split a big linear into tiles to cap working-set size.

Reference: deepspeed/runtime/zero/tiling.py:26-294 splits an nn.Linear
into in_splits x out_splits sub-Linears so ZeRO-3 gathers (and activation
memory) stay bounded; input is chunked, partial products summed.

TPU version: the tiles are separate param leaves (so a stage-3 plan
shards each tile independently and XLA's gather-on-use touches one tile
at a time); the forward is a sum over input tiles of per-output-tile
matmuls, optionally rematerialised per tile. Math is identical to a
single [in, out] matmul.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..utils import partition_uniform


class TiledLinear:
    """Functional tiled linear: init() -> params pytree of tiles;
    __call__(params, x) -> x @ W + b computed tile-wise."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 in_splits: int = 1, out_splits: int = 1,
                 input_is_already_split: bool = False, combine_out_splits: bool = True,
                 linear_cls=None, init_linear=None, remat_each_tile: bool = False,
                 **kwargs):
        if in_splits < 1 or out_splits < 1:
            raise RuntimeError("in and out splits must be >= 1")
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.input_is_already_split = input_is_already_split
        self.combine_out_splits = combine_out_splits
        self.remat_each_tile = remat_each_tile
        # row/col boundaries (reference uses partition_uniform too, :80-92)
        self.in_parts = partition_uniform(in_features, in_splits)
        self.out_parts = partition_uniform(out_features, out_splits)
        self._init_from = init_linear  # optional {'w': [in,out], 'b': [out]}

    def init(self, rng, param_dtype=jnp.float32) -> Dict[str, Any]:
        tiles = []
        if self._init_from is not None:
            w = jnp.asarray(self._init_from["w"])
            b = self._init_from.get("b")
            for o in range(self.out_splits):
                o0, o1 = self.out_parts[o], self.out_parts[o + 1]
                row = []
                for i in range(self.in_splits):
                    i0, i1 = self.in_parts[i], self.in_parts[i + 1]
                    row.append({"w": w[i0:i1, o0:o1].astype(param_dtype)})
                tiles.append(row)
            if self.use_bias:
                # bias=True with no 'b' supplied: zero-init (silently
                # dropping the requested bias would change the model)
                bsrc = (jnp.asarray(b) if b is not None
                        else jnp.zeros((self.out_features,)))
                biases = [bsrc[self.out_parts[o]:self.out_parts[o + 1]]
                          .astype(param_dtype)
                          for o in range(self.out_splits)]
            else:
                biases = None
        else:
            keys = jax.random.split(rng, self.in_splits * self.out_splits)
            scale = (1.0 / self.in_features) ** 0.5
            tiles = []
            k = 0
            for o in range(self.out_splits):
                row = []
                for i in range(self.in_splits):
                    shape = (self.in_parts[i + 1] - self.in_parts[i],
                             self.out_parts[o + 1] - self.out_parts[o])
                    row.append({"w": (scale * jax.random.normal(
                        keys[k], shape)).astype(param_dtype)})
                    k += 1
                tiles.append(row)
            biases = ([jnp.zeros((self.out_parts[o + 1] - self.out_parts[o],),
                                 param_dtype)
                       for o in range(self.out_splits)]
                      if self.use_bias else None)
        out = {"tiles": tiles}
        if biases is not None:
            out["bias"] = biases
        return out

    def _split_input(self, x):
        return [x[..., self.in_parts[i]:self.in_parts[i + 1]]
                for i in range(self.in_splits)]

    def __call__(self, params, x):
        xs = x if self.input_is_already_split else self._split_input(x)
        if len(xs) != self.in_splits:
            raise RuntimeError(
                f"expected {self.in_splits} input tiles, got {len(xs)}")
        outs = []
        for o in range(self.out_splits):
            def tile_row(row_params, xs_):
                acc = None
                for i in range(self.in_splits):
                    y = xs_[i] @ row_params[i]["w"]
                    acc = y if acc is None else acc + y
                return acc

            fn = (jax.checkpoint(tile_row, static_argnums=())
                  if self.remat_each_tile else tile_row)
            y = fn(params["tiles"][o], xs)
            if self.use_bias and "bias" in params:
                y = y + params["bias"][o]
            outs.append(y)
        if self.combine_out_splits:
            return jnp.concatenate(outs, axis=-1)
        return outs

    def full_weight(self, params):
        """Reassemble the dense [in, out] matrix (testing / export)."""
        cols = [jnp.concatenate([params["tiles"][o][i]["w"]
                                 for i in range(self.in_splits)], axis=0)
                for o in range(self.out_splits)]
        return jnp.concatenate(cols, axis=1)
