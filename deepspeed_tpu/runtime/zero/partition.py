"""ZeRO stages as sharding specs — the TPU-native core of ZeRO.

The reference implements ZeRO with ~7k LoC of flattening, bucketing, grad
hooks and hand-rolled collectives (zero/stage1.py, stage2.py, stage3.py +
partition_parameters.py). On TPU the same memory win is expressed as
sharding annotations and XLA inserts the collectives:

  stage 1  optimizer state sharded over the `data` axis
           (reference stage1.py:328-465 sub-partitions -> NamedSharding)
  stage 2  + gradients reduce-scattered to their owner shard
           (reference stage2.py:614-745 bucket machinery ->
            with_sharding_constraint on grads = psum_scatter; with
            "comm": {"gradient_reduction": "bucketed"} the scatter runs
            explicitly over the BucketPlan's fused flat buckets instead —
            runtime/comm/bucketing.py — and these grad specs describe the
            per-leaf layout the scattered buckets unflatten into)
  stage 3  + parameters sharded; XLA all-gathers on use and discards after
           (reference stage3.py fetch/release hooks + PrefetchCoordinator ->
            XLA scheduling)

Sharding rule per tensor: shard the largest dimension divisible by the dp
size that is not already occupied by a tensor-parallel axis; tensors too
small to shard (or with no divisible dim) stay replicated — the analogue of
the reference's `param_persistence_threshold` (stage3.py:1386).

Hierarchical data axis (hpZ secondary shards, ZeRO++ arXiv:2306.10209):
when the mesh factors `data` into `("data_outer", "data_inner")`, the
stage-1/2 optimizer-state and gradient partitions are placed over
`data_inner` ONLY — replicated across outer groups.  That costs
outer-factor x more partition memory than a full-dp shard (the hpZ
trade) but keeps every post-step parameter all-gather strictly on the
fast intra-group fabric, and it is exactly where the hierarchical
bucket wire's reduce-scatter already leaves the gradients
(runtime/comm/bucketing.py).  Stage-3 parameter sharding keeps the full
dp factor (both sub-axes) — the memory win is the point there.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...comm.mesh import (DATA_AXIS, DATA_INNER_AXIS, DATA_OUTER_AXIS,
                          MeshInfo)

_DATA_AXIS_NAMES = (DATA_AXIS, DATA_INNER_AXIS, DATA_OUTER_AXIS)


def _spec_to_list(spec: Optional[PartitionSpec], ndim: int):
    out = [None] * ndim
    if spec is not None:
        for i, s in enumerate(spec):
            if i < ndim:
                out[i] = s
    return out


def add_data_axis(spec: Optional[PartitionSpec], shape, dp_size: int,
                  min_size_to_shard: int = 1024,
                  axes: Sequence[str] = (DATA_AXIS,)) -> PartitionSpec:
    """Extend a (possibly TP-sharded) PartitionSpec with the data axis
    (`axes`: one mesh axis name, or the hierarchical sub-axis pair with
    `dp_size` their product) on the best free dimension. Returns the
    original spec if nothing divides."""
    dims = _spec_to_list(spec, len(shape))
    if dp_size <= 1 or int(np.prod(shape or (1,))) < min_size_to_shard:
        return PartitionSpec(*dims)
    flat = [a for d in dims if d is not None
            for a in (d if isinstance(d, tuple) else (d,))]
    if any(a in flat for a in _DATA_AXIS_NAMES):
        # already data-sharded (e.g. expert-parallel)
        return PartitionSpec(*dims)
    best, best_len = None, 0
    for i, d in enumerate(shape):
        if dims[i] is None and d % dp_size == 0 and d > best_len:
            best, best_len = i, d
    if best is None:
        return PartitionSpec(*dims)
    axes = tuple(axes)
    dims[best] = axes[0] if len(axes) == 1 else axes
    return PartitionSpec(*dims)


class ZeroShardingPlan:
    """Per-stage shardings for params / grads / optimizer state.

    Produced once at engine init; consumed as `in_shardings`/
    `with_sharding_constraint` targets of the jitted train step.
    """

    def __init__(self, stage: int, mesh_info: MeshInfo, params,
                 param_specs=None, min_size_to_shard: int = 1024):
        self.stage = int(stage)
        self.mesh_info = mesh_info
        self.min_size_to_shard = min_size_to_shard
        dp = mesh_info.axis_size(DATA_AXIS)
        # partition placement: flat meshes shard over the whole data
        # axis; hierarchical meshes place stage-1/2 partitions on the
        # inner sub-axis only (hpZ secondary shards — see module doc),
        # keeping stage-3 parameter shards at the full dp factor.
        if mesh_info.hierarchical:
            part_axes: Tuple[str, ...] = (DATA_INNER_AXIS,)
            part_size = mesh_info.data_inner_size
            full_axes: Tuple[str, ...] = (DATA_OUTER_AXIS, DATA_INNER_AXIS)
        else:
            part_axes = full_axes = (DATA_AXIS,)
            part_size = dp
        self.partition_axes = part_axes
        self.partition_size = part_size

        def base_spec(path_spec, leaf):
            # TP spec supplied by the model (or None -> replicated)
            return path_spec if path_spec is not None else PartitionSpec()

        if param_specs is None:
            param_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(),
                                                 params)
        else:
            # drop spec axes the current mesh can't honor (dim not
            # divisible by the axis size) — keeps model-supplied TP/EP
            # layouts elastic across mesh widths (e.g. 4 experts resumed
            # on an 8-wide data axis fall back to replication)
            param_specs = jax.tree_util.tree_map(
                lambda s, l: self._sanitize(s, getattr(l, "shape", ())),
                param_specs, params,
                is_leaf=lambda x: isinstance(x, PartitionSpec) or x is None)

        def with_partition(spec, leaf):
            return add_data_axis(spec, leaf.shape, part_size,
                                 min_size_to_shard, axes=part_axes)

        def with_full_dp(spec, leaf):
            return add_data_axis(spec, leaf.shape, dp, min_size_to_shard,
                                 axes=full_axes)

        is_spec = lambda x: isinstance(x, PartitionSpec) or x is None

        # parameter specs: replicated over data unless stage 3 (full dp
        # factor even hierarchical — param memory is the stage-3 win)
        if self.stage >= 3:
            self.param_spec = jax.tree_util.tree_map(with_full_dp,
                                                     param_specs,
                                                     params, is_leaf=is_spec)
        else:
            self.param_spec = jax.tree_util.tree_map(base_spec, param_specs,
                                                     params, is_leaf=is_spec)

        # gradient specs: sharded from stage 2 (reduce-scatter), else
        # follow params (mean over data handled by psum/jit)
        if self.stage >= 2:
            self.grad_spec = jax.tree_util.tree_map(with_partition,
                                                    param_specs,
                                                    params, is_leaf=is_spec)
        else:
            self.grad_spec = self.param_spec

        # optimizer-state specs: sharded from stage 1
        if self.stage >= 1:
            self.opt_spec = jax.tree_util.tree_map(with_partition,
                                                   param_specs,
                                                   params, is_leaf=is_spec)
        else:
            self.opt_spec = self.param_spec

    def _translate_data_axes(self, d):
        """One spec entry (axis name or tuple): on a hierarchical mesh
        the logical "data" name is not a mesh axis — a model-supplied
        spec using it (e.g. expert-parallel MoE params) expands to the
        ("data_outer", "data_inner") pair, same total size.  Under the
        explicit MoE a2a wire with INNER placement (comm.moe —
        moe/dispatch.resolve_placement) the translation narrows to
        `data_inner` only: experts replicate across outer groups so the
        expert exchange never leaves the fast fabric (their gradients
        pick up the outer psum from the replication, like any
        replicated parameter)."""
        if not self.mesh_info.hierarchical or d is None:
            return d
        target = (DATA_OUTER_AXIS, DATA_INNER_AXIS)
        # NOTE: this narrowing keys off the process-global MoE wire
        # config and applies to EVERY model-supplied DATA_AXIS param
        # spec.  Today only expert-parallel MoE params use one (the
        # engine's own data sharding never routes through model specs);
        # a future non-expert data-sharded param would need a scoped
        # marker here rather than inheriting the MoE placement.
        from ...moe import dispatch as _moe_dispatch

        wcfg = _moe_dispatch.get_wire_config()
        if wcfg.explicit and _moe_dispatch.resolve_placement(
                wcfg, self.mesh_info) == "inner":
            target = (DATA_INNER_AXIS,)
        out = []
        for a in (d if isinstance(d, tuple) else (d,)):
            out.extend(target if a == DATA_AXIS else (a,))
        return tuple(out) if len(out) > 1 else out[0]

    def _sanitize(self, spec: Optional[PartitionSpec], shape):
        if spec is None:
            return PartitionSpec()
        dims = [self._translate_data_axes(d)
                for d in _spec_to_list(spec, len(shape))]
        out = []
        for i, d in enumerate(dims):
            if d is None:
                out.append(None)
                continue
            axes = d if isinstance(d, tuple) else (d,)
            size = 1
            for a in axes:
                size *= self.mesh_info.axis_size(a)
            if size > 1 and (i >= len(shape) or shape[i] % size != 0):
                out.append(None)  # mesh can't honor this axis here
            else:
                out.append(d)
        return PartitionSpec(*out)

    # -- NamedSharding views ------------------------------------------

    def _named(self, spec_tree):
        mesh = self.mesh_info.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self):
        return self._named(self.param_spec)

    def grad_shardings(self):
        return self._named(self.grad_spec)

    def opt_state_shardings(self, opt_state):
        """Map moment pytrees (same structure as params, nested under state
        keys) to opt_spec; scalars (step counters) replicate."""
        mesh = self.mesh_info.mesh

        def for_leaf_path(state_leaf, spec):
            return NamedSharding(mesh, spec)

        def map_state(state):
            out = {}
            for k, v in state.items():
                if k in ("exp_avg", "exp_avg_sq", "worker_error",
                         "server_error"):
                    out[k] = jax.tree_util.tree_map(
                        lambda leaf, s: for_leaf_path(leaf, s), v,
                        self.opt_spec)
                else:
                    # scalars (step counters) and states of unknown shape
                    # (e.g. OptaxOptimizer's wrapped transform state):
                    # replicate every leaf — stage-1 moment sharding only
                    # applies to the moment trees it understands
                    out[k] = jax.tree_util.tree_map(
                        lambda leaf: NamedSharding(mesh, PartitionSpec()),
                        v)
            return out

        return map_state(opt_state)

    def constrain_grads(self, grads):
        """Apply stage>=2 gradient sharding inside jit: XLA turns the
        psum+constraint pattern into a reduce-scatter (+ later all-gather),
        the ZeRO-2 wire pattern (reference stage2.py average_tensor)."""
        if self.stage < 2:
            return grads
        return jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh_info.mesh, s)),
            grads, self.grad_spec)

    def constrain_opt_state(self, opt_state):
        if self.stage < 1:
            return opt_state
        shardings = self.opt_state_shardings(opt_state)
        return jax.tree_util.tree_map(jax.lax.with_sharding_constraint,
                                      opt_state, shardings)

    def constrain_params(self, params):
        if self.stage < 3:
            return params
        return jax.tree_util.tree_map(
            lambda p, s: jax.lax.with_sharding_constraint(
                p, NamedSharding(self.mesh_info.mesh, s)),
            params, self.param_spec)

    def partition_layout(self) -> dict:
        """The facts a checkpoint must record for resharding-on-restore:
        where stage-1/2 partitions live (full dp vs hpZ inner-only) is a
        function of all of these, so a restore at ANY different tuple
        re-partitions (runtime/checkpointing.py stores this in the
        commit marker; engine.load_checkpoint logs the transition)."""
        mi = self.mesh_info
        return {
            "zero_stage": self.stage,
            "dp_world_size": mi.axis_size(DATA_AXIS),
            "data_outer": mi.data_outer_size if mi.hierarchical else 1,
            "data_inner": (mi.data_inner_size if mi.hierarchical
                           else mi.axis_size(DATA_AXIS)),
            "partition_size": self.partition_size,
            "hierarchical": bool(mi.hierarchical),
        }

    def describe(self) -> str:
        n_shard = 0
        n_total = 0
        for s in jax.tree_util.tree_leaves(
                self.opt_spec, is_leaf=lambda x: isinstance(x, PartitionSpec)):
            n_total += 1
            flat = [a for d in tuple(s) if d is not None
                    for a in (d if isinstance(d, tuple) else (d,))]
            if any(a in flat for a in _DATA_AXIS_NAMES):
                n_shard += 1
        where = (f"{self.partition_size} intra-group shards "
                 f"(hpZ: replicated across "
                 f"{self.mesh_info.data_outer_size} outer groups)"
                 if self.mesh_info.hierarchical
                 else f"{self.partition_size} shards")
        return (f"ZeRO stage {self.stage}: {n_shard}/{n_total} tensors "
                f"dp-sharded over {where}")


class QuantizedWeightGather:
    """qwZ (ZeRO++ arXiv:2306.10209): the stage-3 parameter all-gather
    rides blockwise int8/int4 payloads + per-block fp16 scales instead
    of full-width weights; every rank dequantizes on device right after
    the gather.  The MASTER weights (and the optimizer update applied to
    them) stay full precision — only the compute-side replica that the
    forward/backward consumes is quantize-roundtripped, which is what
    bounds the error to one quantization per step rather than an
    accumulating drift.

    Built once at engine init from the ZeroShardingPlan: each leaf whose
    param spec carries the data axis gathers through the quantized wire
    (one jitted shard_map over the data axes; tensor/pipe axes stay
    auto, so TP layouts pass through untouched); leaves too small to
    shard are already replicated and pass through as-is.  Wire bytes
    are priced exactly (`wire_bytes_per_gather`) so the engine's
    `qwz.gather` counter proves the compression."""

    def __init__(self, plan: "ZeroShardingPlan", params, *,
                 wire: str = "int8", block: int = 256):
        from ..comm.quant import (payload_bytes, qmax,
                                  validate_block_size)

        qmax(wire)  # validates the wire name
        self.wire = wire
        self.block = validate_block_size(block)
        self.plan = plan
        mesh = plan.mesh_info.mesh

        leaves, treedef = jax.tree_util.tree_flatten(params)
        specs = jax.tree_util.tree_flatten(
            plan.param_spec,
            is_leaf=lambda x: isinstance(x, PartitionSpec))[0]

        def data_placement(spec, ndim):
            """(dim index, data-axis names) of the leaf's data sharding,
            or (None, ()) for replicated-over-data leaves."""
            for i, entry in enumerate(_spec_to_list(spec, ndim)):
                if entry is None:
                    continue
                axes = entry if isinstance(entry, tuple) else (entry,)
                if all(a in _DATA_AXIS_NAMES for a in axes):
                    return i, tuple(axes)
            return None, ()

        self._placements = []
        axis_names = set()
        in_specs, out_specs = [], []
        self.wire_bytes_per_gather = 0
        self.collectives_per_gather = 0
        self.n_quantized_leaves = 0
        for leaf, spec in zip(leaves, specs):
            shape = tuple(leaf.shape)
            dim, axes = data_placement(spec, len(shape))
            self._placements.append((dim, axes, shape))
            if dim is None:
                in_specs.append(PartitionSpec())
                out_specs.append(PartitionSpec())
                continue
            axis_names.update(axes)
            entries = [None] * len(shape)
            entries[dim] = axes if len(axes) > 1 else axes[0]
            in_specs.append(PartitionSpec(*entries))
            out_specs.append(PartitionSpec())
            world = 1
            for a in axes:
                world *= plan.mesh_info.axis_size(a)
            local = int(np.prod(shape, dtype=np.int64)) // world
            per_hop = payload_bytes(local, wire, self.block)
            # sequential gathers resend the accumulated payload: hop j
            # over axes[-1-j] carries per_hop x (product of the sizes
            # already gathered).  Flat data axes (the only layout the
            # stage-3 engine builds) have exactly one hop.
            gathered = 1
            for a in reversed(axes):
                self.wire_bytes_per_gather += per_hop * gathered
                # payload + scales fused into one buffer (pack_wire)
                self.collectives_per_gather += 1
                gathered *= plan.mesh_info.axis_size(a)
            self.n_quantized_leaves += 1

        self._treedef = treedef
        self._axis_names = axis_names
        if not self.n_quantized_leaves:
            self._fn = None
            return

        placements = tuple(self._placements)
        wire_name, blk = self.wire, self.block

        def gather_tree(*flat_leaves):
            from ..comm.bucketing import _record
            from ..comm.quant import quantized_all_gather

            out = []
            for x, (dim, axes, shape) in zip(flat_leaves, placements):
                if dim is None:
                    out.append(x)
                    continue
                deq = quantized_all_gather(
                    x, axes, blk, wire_name,
                    record=lambda nb: _record("qwz.all_gather", nb))
                world = deq.shape[0]
                deq = deq.reshape((world,) + tuple(x.shape))
                full = jnp.moveaxis(deq, 0, dim).reshape(shape)
                out.append(full.astype(x.dtype))
            return tuple(out)

        smapped = jax.shard_map(gather_tree, mesh=mesh,
                                in_specs=tuple(in_specs),
                                out_specs=tuple(out_specs),
                                axis_names=axis_names, check_vma=False)

        def run(tree):
            flat = jax.tree_util.tree_leaves(tree)
            return jax.tree_util.tree_unflatten(treedef,
                                                list(smapped(*flat)))

        self._fn = run

    @property
    def active(self) -> bool:
        return self._fn is not None

    def overlap_layout(self):
        """[(leaf_idx, offset, nbytes, local_elems, dim, axes, shape)]
        of each quantized leaf inside the fused per-rank exchange
        buffer, + the buffer's total size — the host-exchanged (qwZ
        prefetch) form of the gather."""
        from ..comm.quant import payload_bytes

        layout, off = [], 0
        for idx, (dim, axes, shape) in enumerate(self._placements):
            if dim is None:
                continue
            world = 1
            for a in axes:
                world *= self.plan.mesh_info.axis_size(a)
            local = int(np.prod(shape, dtype=np.int64)) // world
            nb = payload_bytes(local, self.wire, self.block)
            layout.append((idx, off, nb, local, dim, axes, shape))
            off += nb
        return layout, off

    def overlap_encode(self, qleaves):
        """Local stage-3 shards (the quantized leaves, in layout order)
        -> ONE fused uint8 exchange buffer for this rank (inside a
        shard_map over the data axes, same in_specs as the in-program
        gather).  Quantization math is byte-identical to
        `quantized_all_gather`'s encode half."""
        from ..comm.quant import pack_wire, quantize_blockwise

        parts = []
        for leaf in qleaves:
            payload, scales = quantize_blockwise(leaf, self.block,
                                                 self.wire)
            parts.append(pack_wire(payload, scales))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def overlap_decode(self, cleaves, matrix):
        """Gathered [world, total_nbytes] exchange matrix -> the full
        compute-param leaves (replicated), mirroring the in-program
        gather's dequantize/reassemble exactly.  Runs on global arrays
        — no shard_map, no collectives."""
        from ..comm.quant import dequantize_blockwise, unpack_wire

        layout, _ = self.overlap_layout()
        out = list(cleaves)
        for idx, off, nb, local, dim, axes, shape in layout:
            world = 1
            for a in axes:
                world *= self.plan.mesh_info.axis_size(a)
            rows = jax.lax.slice(matrix, (0, off),
                                 (matrix.shape[0], off + nb))
            p, s = unpack_wire(rows, self.wire, self.block, local)
            deq = dequantize_blockwise(p, s, self.wire, local)
            local_shape = list(shape)
            local_shape[dim] //= world
            deq = deq.reshape((world,) + tuple(local_shape))
            full = jnp.moveaxis(deq, 0, dim).reshape(shape)
            out[idx] = full.astype(cleaves[idx].dtype)
        return out

    def encode_in_specs(self):
        """in_specs of `overlap_encode`'s shard_map (quantized leaves
        only, layout order) — the same data shardings the in-program
        gather consumes."""
        specs = []
        for dim, axes, shape in self._placements:
            if dim is None:
                continue
            entries = [None] * len(shape)
            entries[dim] = axes if len(axes) > 1 else axes[0]
            specs.append(PartitionSpec(*entries))
        return tuple(specs)

    def encode_out_spec(self):
        """Out spec stacking each rank's exchange buffer rank-major."""
        axis_names = []
        for _dim, axes, _shape in self._placements:
            for a in axes:
                if a not in axis_names:
                    axis_names.append(a)
        # outer-major ordering matches the sequential-hop gather
        order = [a for a in (DATA_OUTER_AXIS, DATA_INNER_AXIS, DATA_AXIS)
                 if a in axis_names]
        return PartitionSpec(tuple(order) if len(order) > 1 else order[0])

    def build_overlap(self, cast_fn):
        """(encode, decode) jitted programs for the host-exchanged
        (prefetchable) form of the gather:

          encode(params) -> this mesh's fused uint8 exchange buffer,
                            stacked rank-major over the data axes
          decode(params, matrix[world, nbytes]) -> full compute params

        `cast_fn` is the engine's master->compute cast; encode
        quantizes the CAST shards (exactly what the in-program gather
        quantizes) and decode reassembles + casts the replicated
        passthrough leaves, so decode(params, exchange(encode(params)))
        is bitwise `prep_params` on the serial path."""
        mesh = self.plan.mesh_info.mesh
        layout, total = self.overlap_layout()
        qidx = [entry[0] for entry in layout]
        treedef = self._treedef

        smapped = jax.shard_map(
            lambda *qleaves: self.overlap_encode(qleaves),
            mesh=mesh, in_specs=self.encode_in_specs(),
            out_specs=self.encode_out_spec(),
            axis_names=self._axis_names, check_vma=False)

        def encode(params):
            cleaves = jax.tree_util.tree_leaves(cast_fn(params))
            return smapped(*[cleaves[i] for i in qidx])

        def decode(params, matrix):
            cleaves = jax.tree_util.tree_leaves(cast_fn(params))
            out = self.overlap_decode(cleaves, matrix)
            return jax.tree_util.tree_unflatten(treedef, out)

        return jax.jit(encode), jax.jit(decode)

    def gather(self, params):
        """Sharded (stage-3) compute params -> full gathered params,
        quantize-roundtripped through the wire.  Trace-safe (call inside
        the jitted step)."""
        if self._fn is None:
            return params
        return self._fn(params)

    def describe(self) -> str:
        return (f"qwZ quantized weight gather: {self.n_quantized_leaves} "
                f"stage-3 leaves ride {self.wire} blocks of {self.block} "
                f"(+fp16 scales), {self.wire_bytes_per_gather} wire bytes "
                f"/ {self.collectives_per_gather} collective(s) per "
                f"gather; master weights stay full precision")


def describe_reshard(saved: Optional[dict], current: dict,
                     reason: Optional[str] = None) -> Optional[str]:
    """Human-readable description of a checkpoint topology transition, or
    None when the saved and restoring layouts match (nothing to reshard
    beyond placement).  `saved` is a partition_layout() dict out of the
    checkpoint's commit marker; unknown/legacy checkpoints (None) return
    None — there is nothing trustworthy to compare against.  `reason`
    (an elastic trigger, e.g. "rank 3 died: heartbeat stall") is
    appended so the shrink/regrow log line names WHY the world changed,
    not just that it did."""
    if not saved:
        return None

    def fmt(lay: dict) -> str:
        dp = lay.get("dp_world_size", "?")
        outer = int(lay.get("data_outer", 1) or 1)
        hier = (f"hierarchy {outer}x{lay.get('data_inner', '?')}"
                if outer > 1 else "flat")
        return f"dp={dp} ({hier}), ZeRO stage {lay.get('zero_stage', '?')}"

    keys = ("zero_stage", "dp_world_size", "data_outer", "data_inner")
    if all(saved.get(k) == current.get(k) for k in keys):
        return None
    return (f"resharding checkpoint state: saved at {fmt(saved)} -> "
            f"restoring at {fmt(current)} (ZeRO-1/2 partitions, including "
            f"hpZ secondary shards, re-partition to the new layout on "
            f"device_put)"
            + (f" [elastic trigger: {reason}]" if reason else ""))
