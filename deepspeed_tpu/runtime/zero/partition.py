"""ZeRO stages as sharding specs — the TPU-native core of ZeRO.

The reference implements ZeRO with ~7k LoC of flattening, bucketing, grad
hooks and hand-rolled collectives (zero/stage1.py, stage2.py, stage3.py +
partition_parameters.py). On TPU the same memory win is expressed as
sharding annotations and XLA inserts the collectives:

  stage 1  optimizer state sharded over the `data` axis
           (reference stage1.py:328-465 sub-partitions -> NamedSharding)
  stage 2  + gradients reduce-scattered to their owner shard
           (reference stage2.py:614-745 bucket machinery ->
            with_sharding_constraint on grads = psum_scatter; with
            "comm": {"gradient_reduction": "bucketed"} the scatter runs
            explicitly over the BucketPlan's fused flat buckets instead —
            runtime/comm/bucketing.py — and these grad specs describe the
            per-leaf layout the scattered buckets unflatten into)
  stage 3  + parameters sharded; XLA all-gathers on use and discards after
           (reference stage3.py fetch/release hooks + PrefetchCoordinator ->
            XLA scheduling)

Sharding rule per tensor: shard the largest dimension divisible by the dp
size that is not already occupied by a tensor-parallel axis; tensors too
small to shard (or with no divisible dim) stay replicated — the analogue of
the reference's `param_persistence_threshold` (stage3.py:1386).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...comm.mesh import DATA_AXIS, MeshInfo


def _spec_to_list(spec: Optional[PartitionSpec], ndim: int):
    out = [None] * ndim
    if spec is not None:
        for i, s in enumerate(spec):
            if i < ndim:
                out[i] = s
    return out


def add_data_axis(spec: Optional[PartitionSpec], shape, dp_size: int,
                  min_size_to_shard: int = 1024) -> PartitionSpec:
    """Extend a (possibly TP-sharded) PartitionSpec with the `data` axis on
    the best free dimension. Returns the original spec if nothing divides."""
    dims = _spec_to_list(spec, len(shape))
    if dp_size <= 1 or int(np.prod(shape or (1,))) < min_size_to_shard:
        return PartitionSpec(*dims)
    flat = [a for d in dims if d is not None
            for a in (d if isinstance(d, tuple) else (d,))]
    if DATA_AXIS in flat:  # already data-sharded (e.g. expert-parallel)
        return PartitionSpec(*dims)
    best, best_len = None, 0
    for i, d in enumerate(shape):
        if dims[i] is None and d % dp_size == 0 and d > best_len:
            best, best_len = i, d
    if best is None:
        return PartitionSpec(*dims)
    dims[best] = DATA_AXIS
    return PartitionSpec(*dims)


class ZeroShardingPlan:
    """Per-stage shardings for params / grads / optimizer state.

    Produced once at engine init; consumed as `in_shardings`/
    `with_sharding_constraint` targets of the jitted train step.
    """

    def __init__(self, stage: int, mesh_info: MeshInfo, params,
                 param_specs=None, min_size_to_shard: int = 1024):
        self.stage = int(stage)
        self.mesh_info = mesh_info
        self.min_size_to_shard = min_size_to_shard
        dp = mesh_info.axis_size(DATA_AXIS)

        def base_spec(path_spec, leaf):
            # TP spec supplied by the model (or None -> replicated)
            return path_spec if path_spec is not None else PartitionSpec()

        if param_specs is None:
            param_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(),
                                                 params)
        else:
            # drop spec axes the current mesh can't honor (dim not
            # divisible by the axis size) — keeps model-supplied TP/EP
            # layouts elastic across mesh widths (e.g. 4 experts resumed
            # on an 8-wide data axis fall back to replication)
            param_specs = jax.tree_util.tree_map(
                lambda s, l: self._sanitize(s, getattr(l, "shape", ())),
                param_specs, params,
                is_leaf=lambda x: isinstance(x, PartitionSpec) or x is None)

        def with_dp(spec, leaf):
            return add_data_axis(spec, leaf.shape, dp, min_size_to_shard)

        is_spec = lambda x: isinstance(x, PartitionSpec) or x is None

        # parameter specs: replicated over data unless stage 3
        if self.stage >= 3:
            self.param_spec = jax.tree_util.tree_map(with_dp, param_specs,
                                                     params, is_leaf=is_spec)
        else:
            self.param_spec = jax.tree_util.tree_map(base_spec, param_specs,
                                                     params, is_leaf=is_spec)

        # gradient specs: sharded from stage 2 (reduce-scatter), else
        # follow params (mean over data handled by psum/jit)
        if self.stage >= 2:
            self.grad_spec = jax.tree_util.tree_map(with_dp, param_specs,
                                                    params, is_leaf=is_spec)
        else:
            self.grad_spec = self.param_spec

        # optimizer-state specs: sharded from stage 1
        if self.stage >= 1:
            self.opt_spec = jax.tree_util.tree_map(with_dp, param_specs,
                                                   params, is_leaf=is_spec)
        else:
            self.opt_spec = self.param_spec

    def _sanitize(self, spec: Optional[PartitionSpec], shape):
        if spec is None:
            return PartitionSpec()
        dims = _spec_to_list(spec, len(shape))
        out = []
        for i, d in enumerate(dims):
            if d is None:
                out.append(None)
                continue
            axes = d if isinstance(d, tuple) else (d,)
            size = 1
            for a in axes:
                size *= self.mesh_info.axis_size(a)
            if size > 1 and (i >= len(shape) or shape[i] % size != 0):
                out.append(None)  # mesh can't honor this axis here
            else:
                out.append(d)
        return PartitionSpec(*out)

    # -- NamedSharding views ------------------------------------------

    def _named(self, spec_tree):
        mesh = self.mesh_info.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self):
        return self._named(self.param_spec)

    def grad_shardings(self):
        return self._named(self.grad_spec)

    def opt_state_shardings(self, opt_state):
        """Map moment pytrees (same structure as params, nested under state
        keys) to opt_spec; scalars (step counters) replicate."""
        mesh = self.mesh_info.mesh

        def for_leaf_path(state_leaf, spec):
            return NamedSharding(mesh, spec)

        def map_state(state):
            out = {}
            for k, v in state.items():
                if k in ("exp_avg", "exp_avg_sq", "worker_error",
                         "server_error"):
                    out[k] = jax.tree_util.tree_map(
                        lambda leaf, s: for_leaf_path(leaf, s), v,
                        self.opt_spec)
                else:
                    # scalars (step counters) and states of unknown shape
                    # (e.g. OptaxOptimizer's wrapped transform state):
                    # replicate every leaf — stage-1 moment sharding only
                    # applies to the moment trees it understands
                    out[k] = jax.tree_util.tree_map(
                        lambda leaf: NamedSharding(mesh, PartitionSpec()),
                        v)
            return out

        return map_state(opt_state)

    def constrain_grads(self, grads):
        """Apply stage>=2 gradient sharding inside jit: XLA turns the
        psum+constraint pattern into a reduce-scatter (+ later all-gather),
        the ZeRO-2 wire pattern (reference stage2.py average_tensor)."""
        if self.stage < 2:
            return grads
        return jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh_info.mesh, s)),
            grads, self.grad_spec)

    def constrain_opt_state(self, opt_state):
        if self.stage < 1:
            return opt_state
        shardings = self.opt_state_shardings(opt_state)
        return jax.tree_util.tree_map(jax.lax.with_sharding_constraint,
                                      opt_state, shardings)

    def constrain_params(self, params):
        if self.stage < 3:
            return params
        return jax.tree_util.tree_map(
            lambda p, s: jax.lax.with_sharding_constraint(
                p, NamedSharding(self.mesh_info.mesh, s)),
            params, self.param_spec)

    def describe(self) -> str:
        n_shard = 0
        n_total = 0
        for s in jax.tree_util.tree_leaves(
                self.opt_spec, is_leaf=lambda x: isinstance(x, PartitionSpec)):
            n_total += 1
            if DATA_AXIS in tuple(s):
                n_shard += 1
        return (f"ZeRO stage {self.stage}: {n_shard}/{n_total} tensors "
                f"dp-sharded over {self.mesh_info.axis_size(DATA_AXIS)} shards")
