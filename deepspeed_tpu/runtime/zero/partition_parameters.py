"""ZeRO-3 parameter-partitioning API surface.

Reference: deepspeed/runtime/zero/partition_parameters.py — `zero.Init`
(:265) monkey-patches nn.Module.__init__ so every parameter is partitioned
at construction (1/world per rank, optionally on cpu/nvme), and
`GatheredParameters` (:1002) temporarily all-gathers partitioned params for
host-side surgery.

TPU redesign: XLA materializes ARRAYS, not modules, so `Init` wraps the
model's init function: the init runs under jit with `out_shardings` set to
the ZeRO-3 plan, meaning every parameter is CREATED already sharded across
the data axis — no single-device full copy ever exists (the same guarantee
zero.Init's patching buys, without patching). `GatheredParameters`
device_puts to replicated for the body and re-shards on exit.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...comm.mesh import MeshInfo, get_current_mesh
from ...utils.logging import log_dist
from .partition import ZeroShardingPlan


class Init:
    """Materialize parameters directly sharded (reference zero.Init :265).

    Usage:
        with zero.Init(mesh_info=info) as zinit:
            params = zinit.materialize(model.init, rng)
        # params leaves are sharded over the data axis; no device ever
        # held the full tree

    `remote_device` / `pin_memory` / `config` keywords are accepted for
    API parity; "cpu" remote_device materializes on host instead.
    """

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device: Optional[str] = None,
                 pin_memory: bool = False, deepspeed_config=None,
                 param_dict=None, enabled: bool = True,
                 mesh_info: Optional[MeshInfo] = None,
                 param_specs=None):
        self.enabled = enabled
        self.mesh_info = mesh_info or get_current_mesh()
        self.remote_device = remote_device
        self.param_specs = param_specs
        self._plan = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def materialize(self, init_fn: Callable, *init_args):
        """Run `init_fn(*init_args)` with ZeRO-3 output shardings."""
        if not self.enabled:
            return init_fn(*init_args)
        abstract = jax.eval_shape(init_fn, *init_args)
        plan = ZeroShardingPlan(3, self.mesh_info, abstract,
                                param_specs=self.param_specs)
        self._plan = plan
        if self.remote_device == "cpu":
            # host materialization (reference remote_device='cpu')
            params = jax.jit(init_fn, backend="cpu")(*init_args) \
                if jax.default_backend() != "cpu" else init_fn(*init_args)
            return jax.device_put(params, plan.param_shardings())
        sharded_init = jax.jit(init_fn,
                               out_shardings=plan.param_shardings())
        params = sharded_init(*init_args)
        log_dist("zero.Init: materialized parameters sharded over the data "
                 "axis (stage-3 plan)", ranks=[0])
        return params

    @property
    def plan(self) -> Optional[ZeroShardingPlan]:
        return self._plan


class GatheredParameters:
    """reference partition_parameters.py:1002 — temporarily gather
    partitioned params for host-side reads/writes.

    with GatheredParameters(params) as g:
        g.params = mutate(g.params)     # full (replicated) values
    params = g.params                    # re-sharded on exit

    `modifier_rank` is accepted for parity; in single-controller JAX every
    process sees the same values, so rank-0 broadcast is implicit.
    """

    def __init__(self, params, modifier_rank: Optional[int] = None,
                 fwd_module=None, enabled: bool = True,
                 shardings=None, mesh_info: Optional[MeshInfo] = None):
        self.enabled = enabled
        self._orig_shardings = shardings
        self.mesh_info = mesh_info or get_current_mesh()
        self.params = params

    def __enter__(self):
        if not self.enabled:
            return self
        if self._orig_shardings is None:
            self._orig_shardings = jax.tree_util.tree_map(
                lambda l: l.sharding if hasattr(l, "sharding") else None,
                self.params)
        mesh = self.mesh_info.mesh
        replicated = NamedSharding(mesh, PartitionSpec())
        self.params = jax.tree_util.tree_map(
            lambda l: jax.device_put(l, replicated)
            if hasattr(l, "sharding") else l, self.params)
        return self

    def __exit__(self, *exc):
        if not self.enabled:
            return False
        self.params = jax.tree_util.tree_map(
            lambda l, s: jax.device_put(l, s) if s is not None else l,
            self.params, self._orig_shardings)
        return False
