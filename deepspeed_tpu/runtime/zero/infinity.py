"""ZeRO-Infinity — train models larger than device HBM by streaming
parameters from host RAM (optionally paging optimizer moments to NVMe).

Reference: deepspeed/runtime/zero/stage3.py:1332,2742 (param fetch/release
around each submodule) + swap_tensor/partitioned_param_swapper.py:223-277
(NVMe paging). The reference interposes autograd hooks on a resident
module graph; the TPU-native design drives the layer stream explicitly:

* fp32 master parameters live on the HOST, grouped per model stage
  (embed / block:i / head — the model's `stream_groups` protocol);
* forward walks the blocks with ONE cached jit per block shape: the next
  block's working weights upload (H2D, compute dtype) while the current
  block computes — device HBM holds ~2 blocks of params + the saved
  block inputs, never the whole model;
* backward re-streams blocks in reverse, recomputing each block's
  forward under jax.vjp from the saved input (per-block activation
  checkpointing), and overlaps each block's fp32 grad D2H with the next
  block's compute;
* the native CPU-Adam (csrc/adam/cpu_adam.cpp) updates the host masters
  after an all-groups-finite check (a later-block inf must skip the
  whole step), with moments optionally paged through the aio engine
  (csrc/aio/ds_aio.cpp) to NVMe;
* next step's forward streams the UPDATED masters — no separate param
  re-upload pass exists.

Per-step wire traffic: 2x params H2D (fwd + bwd re-stream) + 1x grads
D2H — the same fetch pattern as reference stage3 without its hook
machinery.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import log_dist


def _tokens_labels(batch):
    if isinstance(batch, dict):
        tokens, labels = batch["input_ids"], batch.get("labels")
    else:
        tokens, labels = batch
    if labels is None:
        tokens, labels = tokens[:, :-1], tokens[:, 1:]
    return tokens, labels


class CrossProcessGradReducer:
    """Mean host fp32 gradient vectors across jax.distributed processes.

    The streamed step computes LOCAL grads per process (each process
    trains on its shard of the global batch); the fp32 masters are
    updated on every host identically, so the grads must be averaged
    across processes first. Host data can't ride a collective directly —
    chunks are staged through the devices: a [P, chunk] global array
    (one row per process, via make_array_from_process_local_data) is
    mean-reduced by a tiny jitted program whose replicated output every
    process can read. Chunking bounds the device working set, so this
    works even when total grads far exceed HBM (the Infinity regime).

    Reference capability: stage-3's dp grad reduce-scatter
    (zero/stage3.py:1119-1170) ahead of the partitioned host update."""

    def __init__(self, chunk_elems: int = 32 * 1024 * 1024):
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        self.nprocs = jax.process_count()
        devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        counts = {}
        for d in devs:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
        if len(set(counts.values())) > 1 or len(counts) != self.nprocs:
            raise ValueError(
                f"CrossProcessGradReducer needs a uniform device count per "
                f"process; got per-process counts {counts}. Heterogeneous "
                f"hosts are unsupported — exclude the uneven host or pin "
                f"JAX to an equal device subset.")
        per_proc = len(devs) // self.nprocs
        grid = np.array(devs).reshape(self.nprocs, per_proc)
        self.mesh = Mesh(grid, ("proc", "dev"))
        self._row_sharding = NamedSharding(self.mesh, P("proc"))
        self._out_sharding = NamedSharding(self.mesh, P())
        self.chunk = int(chunk_elems)
        self._buf = None  # lazily-allocated reusable staging buffer
        self._mean = jax.jit(lambda x: jnp.mean(x, axis=0),
                             out_shardings=self._out_sharding)

    def _reduce_chunk(self, local: np.ndarray) -> np.ndarray:
        """local [n] fp32 -> mean over processes [n] fp32 (n <= chunk)."""
        from jax import make_array_from_process_local_data

        garr = make_array_from_process_local_data(
            self._row_sharding, local[None, :], (self.nprocs, local.size))
        out = self._mean(garr)
        return np.asarray(out.addressable_data(0))

    def mean_inplace(self, sink: dict) -> None:
        """Average every vector in {key: fp32 1-D ndarray} across
        processes, packing keys (deterministic order — identical trees on
        every process) into chunk-sized staging buffers."""
        keys = sorted(sink)
        if self._buf is None:
            self._buf = np.empty((self.chunk,), np.float32)
        buf = self._buf
        pending: list = []  # (key, start, end) spans inside buf
        used = 0

        def flush():
            nonlocal used
            if not pending:
                return
            reduced = self._reduce_chunk(buf[:used])
            for key, s, e in pending:
                sink[key] = reduced[s:e]
            pending.clear()
            used = 0

        for key in keys:
            g = sink[key]
            if g.size > self.chunk:
                # reduce into a FRESH array: g may be a read-only zero-copy
                # view of a device buffer (CPU backend np.asarray)
                flush()
                out = np.empty(g.size, np.float32)
                for s in range(0, g.size, self.chunk):
                    e = min(s + self.chunk, g.size)
                    out[s:e] = self._reduce_chunk(
                        np.ascontiguousarray(g[s:e]))
                sink[key] = out
                continue
            if used + g.size > self.chunk:
                flush()
            buf[used:used + g.size] = g
            pending.append((key, used, used + g.size))
            used += g.size
        flush()

    def mean_scalar(self, value) -> jnp.ndarray:
        return jnp.asarray(
            self._reduce_chunk(
                np.asarray([value], np.float32))[0], jnp.float32)


class NvmeMasterPager:
    """fp32 master parameters on NVMe — one file per leaf, group-granular
    load/store through the native aio engine with one-group read-ahead.

    Reference: swap_tensor/partitioned_param_swapper.py:223-277 (param
    swap-in/swap-out around each submodule). Masters are read for the
    H2D upload of each streamed group and written back after the host
    Adam update; host RAM holds only the group in flight plus one
    prefetched group, so max model size is bounded by NVMe, not RAM."""

    def __init__(self, nvme_path: str, n_threads: int = 4):
        import os
        import shutil
        import uuid
        import weakref

        from ...ops.aio import AsyncIOHandle

        # instance-unique (not just pid-scoped): two runtimes in one
        # process (e.g. checkpoint save + fresh reload) must not clobber
        # each other's master files. The directory holds a full fp32
        # model image, so it is removed when the pager is collected.
        self.dir = os.path.join(
            nvme_path,
            f"dstpu_masters_{os.getpid()}_{uuid.uuid4().hex[:8]}")
        os.makedirs(self.dir, exist_ok=True)
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, self.dir, True)
        self._h_main = AsyncIOHandle(n_threads=n_threads)
        self._h_pre = AsyncIOHandle(n_threads=2)
        self._pending: Dict[str, List[np.ndarray]] = {}

    def _path(self, name: str, j: int) -> str:
        import os

        safe = name.replace(":", "_").replace("/", "_")
        return os.path.join(self.dir, f"{safe}.leaf{j}.f32")

    def write_group(self, name: str, flat: List[np.ndarray]) -> None:
        for j, arr in enumerate(flat):
            self._h_main.async_pwrite(np.ascontiguousarray(arr),
                                      self._path(name, j))
        self._h_main.wait()

    def prefetch(self, name: str, sizes: List[int]) -> None:
        """Issue async reads for a group; read_group() collects them.
        One prefetch in flight at a time (the handle waits all)."""
        if name in self._pending:
            return
        bufs = [np.empty(n, np.float32) for n in sizes]
        for j, buf in enumerate(bufs):
            self._h_pre.async_pread(buf, self._path(name, j))
        self._pending[name] = bufs

    def read_group(self, name: str, sizes: List[int]) -> List[np.ndarray]:
        bufs = self._pending.pop(name, None)
        if bufs is not None:
            self._h_pre.wait()
            return bufs
        bufs = [np.empty(n, np.float32) for n in sizes]
        for j, buf in enumerate(bufs):
            self._h_main.async_pread(buf, self._path(name, j))
        self._h_main.wait()
        return bufs


class InfinityRuntime:
    def __init__(self, model, rng, hparams: dict, adam_w_mode: bool = True,
                 compute_dtype=jnp.bfloat16, nvme_path: Optional[str] = None,
                 params_on_nvme: bool = False):
        from ...ops.adam.cpu_adam import HostAdam
        from .offload import NvmeStateStore

        if not model.stream_supported():
            raise ValueError(
                "model does not support parameter streaming (needs "
                "homogeneous blocks, no MoE/pipeline, dropout=0)")
        self.model = model
        self.compute_dtype = compute_dtype
        import ml_dtypes  # noqa: F401  (jax dependency; host bf16 cast)

        self._wire_dtype = np.dtype(compute_dtype)

        # host fp32 masters, one group at a time on device during init.
        # params_on_nvme: the flat arrays page through NvmeMasterPager and
        # the in-RAM slot holds None — only the group in flight (plus one
        # prefetched) is resident, so capacity is NVMe-bounded.
        if params_on_nvme and not nvme_path:
            raise ValueError("params_on_nvme requires an nvme_path")
        self.pager = NvmeMasterPager(nvme_path) if params_on_nvme else None
        self.masters: Dict[str, Tuple[Any, Any, List]] = {}
        self.group_order: List[str] = []
        n_elem = 0
        for name, host_tree in model.stream_init(rng):
            leaves, treedef = jax.tree_util.tree_flatten(host_tree)
            flat = [np.asarray(l, np.float32).ravel() for l in leaves]
            shapes = [l.shape for l in leaves]
            if self.pager is not None:
                self.pager.write_group(name, flat)
                self.masters[name] = (None, treedef, shapes)
            else:
                self.masters[name] = (flat, treedef, shapes)
            self.group_order.append(name)
            n_elem += sum(int(np.prod(s)) if s else 1 for s in shapes)
        self.n_elements = n_elem

        self.adam = HostAdam(
            lr=hparams.get("lr", 1e-3),
            betas=tuple(hparams.get("betas", (0.9, 0.999))),
            eps=hparams.get("eps", 1e-8),
            weight_decay=hparams.get("weight_decay", 0.0),
            adam_w_mode=adam_w_mode)
        self.nvme = NvmeStateStore(nvme_path) if nvme_path else None
        self._leaf_base = {}
        base = 0
        for name in self.group_order:
            self._leaf_base[name] = base
            base += len(self.masters[name][2])  # leaf count = len(shapes)
        self._jits: Dict[str, Any] = {}
        # multi-host DP: each process streams on its shard of the global
        # batch; grads are averaged across processes before the (replicated)
        # host master update
        self.reducer = (CrossProcessGradReducer()
                        if jax.process_count() > 1 else None)
        # gradient accumulation: micro_step() adds into this sink until
        # apply_accumulated() consumes it (lifts the old gas==1 limit)
        self._acc_sink: Dict[int, np.ndarray] = {}
        self._acc_count = 0
        # paged-master stash: the forward's last block read is kept in
        # RAM so the backward's first read costs no disk I/O
        self._kept: Dict[str, List[np.ndarray]] = {}
        log_dist(f"ZeRO-Infinity: {n_elem / 1e6:.1f}M params streamed from "
                 f"{'NVMe' if self.pager is not None else 'host RAM'} "
                 f"({'moments on NVMe' if nvme_path else 'moments in RAM'}"
                 f"{', dp=' + str(jax.process_count()) if self.reducer else ''})",
                 ranks=[0])

    # -- host <-> device / NVMe ----------------------------------------

    def _group_sizes(self, name: str) -> List[int]:
        _, _, shapes = self.masters[name]
        return [int(np.prod(s)) if s else 1 for s in shapes]

    def _masters_flat(self, name: str) -> List[np.ndarray]:
        flat, _, _ = self.masters[name]
        if flat is not None:
            return flat
        return self.pager.read_group(name, self._group_sizes(name))

    def _commit_masters(self, name: str, flat: List[np.ndarray]) -> None:
        if self.pager is not None:
            self.pager.write_group(name, flat)
        else:
            treedef, shapes = self.masters[name][1:]
            self.masters[name] = (flat, treedef, shapes)

    def _prefetch_masters(self, name: Optional[str]) -> None:
        if name is not None and self.pager is not None:
            self.pager.prefetch(name, self._group_sizes(name))

    def _to_device(self, name: str, prefetch: Optional[str] = None,
                   keep: bool = False):
        """Async H2D of a group's working weights in compute dtype; with
        NVMe-paged masters, also kick off the read-ahead of the NEXT group
        so disk latency hides behind this group's upload + compute.
        keep=True stashes the host buffers for the next read of the same
        group (fwd's last block == bwd's first — no redundant disk read)."""
        # collect this group's in-flight read FIRST (h_pre.wait() waits on
        # everything queued, so only one prefetch may be outstanding),
        # then kick off the next group's read-ahead to overlap with this
        # group's cast + H2D + compute
        flat, treedef, shapes = self.masters[name]
        if flat is None:
            flat = self._kept.pop(name, None)
            if flat is None:
                flat = self.pager.read_group(name, self._group_sizes(name))
        self._prefetch_masters(prefetch)
        if keep and self.pager is not None:
            self._kept[name] = flat
        leaves = [jax.device_put(m.reshape(s).astype(self._wire_dtype))
                  for m, s in zip(flat, shapes)]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _grads_to_host(self, name: str, grad_tree, sink: Dict[int, np.ndarray]):
        leaves = jax.tree_util.tree_leaves(grad_tree)
        for leaf in leaves:
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
        base = self._leaf_base[name]
        for j, leaf in enumerate(leaves):
            g = np.asarray(leaf, np.float32).ravel()
            if base + j in sink:
                sink[base + j] = sink[base + j] + g  # tied params (wte)
            else:
                sink[base + j] = g

    # -- jitted stage programs ------------------------------------------

    def _jit(self, key, fn):
        if key not in self._jits:
            self._jits[key] = jax.jit(fn)
        return self._jits[key]

    def _programs(self):
        model = self.model

        def block_fwd(p, x):
            return model.stream_block(p, x)

        def block_bwd(p, x, dy):
            _, pull = jax.vjp(model.stream_block, p, x)
            dp, dx = pull(dy)
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), dp), dx

        def head_fwd_bwd(head_p, w, x, labels, valid):
            loss, pull = jax.vjp(model.stream_head_loss, head_p, w, x,
                                 labels, valid)
            dhead, dw, dx, _, _ = pull(jnp.ones((), jnp.float32))
            f32 = lambda t: jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), t)
            return loss, f32(dhead), dw.astype(jnp.float32), dx

        def embed_bwd(embed_p, tokens, dx):
            _, pull = jax.vjp(lambda p: model.stream_embed(p, tokens),
                              embed_p)
            (dp,) = pull(dx)
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), dp)

        return (self._jit("block_fwd", block_fwd),
                self._jit("block_bwd", block_bwd),
                self._jit("head", head_fwd_bwd),
                self._jit("embed_bwd", embed_bwd),
                self._jit("embed_fwd", model.stream_embed))

    # -- training step ---------------------------------------------------

    def micro_step(self, batch):
        """Streamed fwd+bwd for ONE micro batch; fp32 grads accumulate
        into the host sink until apply_accumulated() consumes them
        (gradient accumulation without any extra device memory — the
        reference has no gas restriction either, stage3.py:2058)."""
        model = self.model
        cfg = model.config
        tokens, labels = _tokens_labels(batch)
        tokens = jnp.asarray(tokens)
        labels = jnp.asarray(labels)
        valid = labels >= 0
        labels = jnp.where(valid, labels, 0)
        L = cfg.num_layers
        block_fwd, block_bwd, head, embed_bwd, embed_fwd = self._programs()

        # ---- forward: stream blocks, double-buffered --------------------
        # resident (tied head needs wte); prefetch chains the NVMe reads
        # one group ahead of each use
        embed_dev = self._to_device("embed", prefetch="head")
        head_dev = self._to_device("head",
                                   prefetch="block:0" if L else None)
        x = embed_fwd(embed_dev, tokens)
        acts = [x]
        nxt = self._to_device("block:0",
                              prefetch="block:1" if L > 1 else None,
                              keep=L == 1) \
            if L else None
        for i in range(L):
            if i + 1 < L:
                pre = f"block:{i + 2}" if i + 2 < L else None
                cur, nxt = nxt, self._to_device(
                    f"block:{i + 1}", prefetch=pre,
                    keep=i + 1 == L - 1)  # bwd reads this group first
            else:
                cur, nxt = nxt, None
            x = block_fwd(cur, x)
            acts.append(x)
        proj = (embed_dev["wte"] if cfg.tie_embeddings
                else head_dev["lm_head"])
        head_in = {"ln_f": head_dev["ln_f"]}
        loss, dhead, dproj, dx = head(head_in, proj, acts[-1], labels, valid)

        # ---- backward: re-stream blocks in reverse ----------------------
        sink = self._acc_sink
        if cfg.tie_embeddings:
            # head group tree is exactly {"ln_f": ...}
            self._grads_to_host("head", dhead, sink)
        else:
            # grads must mirror the FULL head group structure
            # ({"ln_f", "lm_head"}) so flat leaf indices line up
            self._grads_to_host(
                "head", {"ln_f": dhead["ln_f"], "lm_head": dproj}, sink)
        nxt = self._to_device(
            f"block:{L - 1}",
            prefetch=f"block:{L - 2}" if L > 1 else None) if L else None
        for i in range(L - 1, -1, -1):
            if i - 1 >= 0:
                pre = f"block:{i - 2}" if i - 2 >= 0 else None
                cur, nxt = nxt, self._to_device(f"block:{i - 1}",
                                                prefetch=pre)
            else:
                cur, nxt = nxt, None
            dp, dx = block_bwd(cur, acts[i], dx)
            acts[i + 1] = None  # free
            self._grads_to_host(f"block:{i}", dp, sink)
        dembed = embed_bwd(embed_dev, tokens, dx)
        if cfg.tie_embeddings:
            # tied wte: embedding-lookup grad + projection grad (the vjp
            # wrt the [V, D] wte argument already carries wte's shape —
            # the transpose inside stream_head_loss is differentiated)
            dembed = {"wte": dembed["wte"] + dproj.astype(jnp.float32),
                      "wpe": dembed["wpe"]}
        self._grads_to_host("embed", dembed, sink)
        self._acc_count += 1

        # micro losses are reported globally under multi-host DP (grads
        # reduce ONCE at apply time instead — cheaper than per micro)
        if self.reducer is not None:
            loss = self.reducer.mean_scalar(loss)
        return loss

    def apply_accumulated(self, lr: Optional[float] = None,
                          clip: float = 0.0) -> bool:
        """Host Adam over the accumulated grad sink (mean over the
        accumulated micro steps). Returns the overflow flag; the whole
        step skips on any non-finite grad."""
        sink = self._acc_sink
        count = max(1, self._acc_count)
        self._acc_sink = {}
        self._acc_count = 0

        # ---- multi-host DP: average accumulated grads across processes --
        if self.reducer is not None:
            self.reducer.mean_inplace(sink)

        # ---- host optimizer over ALL groups (skip-step on any inf) ------
        # (post-reduction: a non-finite grad on ANY process poisons the
        # mean, so every process skips in lockstep)
        overflow = not all(np.isfinite(g).all() for g in sink.values())
        if overflow:
            return True
        scale = 1.0 / count  # sum over micro steps -> mean
        if clip > 0.0:
            norm = float(np.sqrt(sum(float(np.dot(g, g))
                                     for g in sink.values()))) / count
            if norm > clip:
                scale *= clip / (norm + 1e-6)
        self.adam.begin_step()
        order = self.group_order
        for idx, name in enumerate(order):
            flat = self._masters_flat(name)
            self._prefetch_masters(order[idx + 1]
                                   if idx + 1 < len(order) else None)
            base = self._leaf_base[name]
            for j, master in enumerate(flat):
                g = sink.get(base + j)
                if g is None:
                    continue
                if scale != 1.0:
                    g = g * np.float32(scale)
                key = base + j
                if self.nvme is not None:
                    self.adam._state[key] = self.nvme.load(key, master.size)
                self.adam.update_flat(key, master, np.ascontiguousarray(g),
                                      lr=lr)
                if self.nvme is not None:
                    self.nvme.store(key, self.adam._state.pop(key))
            self._commit_masters(name, flat)
        return False

    def train_step(self, batch, lr: Optional[float] = None,
                   clip: float = 0.0):
        """One streamed fwd+bwd+update (the gas==1 composition).
        Returns (loss, overflow)."""
        loss = self.micro_step(batch)
        return loss, self.apply_accumulated(lr=lr, clip=clip)

    # -- eval -------------------------------------------------------------

    def eval_loss(self, batch):
        model = self.model
        cfg = model.config
        tokens, labels = _tokens_labels(batch)
        tokens, labels = jnp.asarray(tokens), jnp.asarray(labels)
        valid = labels >= 0
        labels = jnp.where(valid, labels, 0)
        block_fwd, _, _, _, embed_fwd = self._programs()
        embed_dev = self._to_device("embed")
        head_dev = self._to_device("head")
        x = embed_fwd(embed_dev, tokens)
        for i in range(cfg.num_layers):
            x = block_fwd(self._to_device(f"block:{i}"), x)
        proj = (embed_dev["wte"] if cfg.tie_embeddings
                else head_dev["lm_head"])
        loss_fn = self._jit("head_eval", self.model.stream_head_loss)
        return loss_fn({"ln_f": head_dev["ln_f"]}, proj, x, labels, valid)

    # -- checkpoint parity -------------------------------------------------

    def save_streamed(self, ckpt_dir: str):
        """RAM-bounded checkpoint write for NVMe-paged masters: one group
        file per stream group carrying the group's fp32 masters, Adam
        moments and any mid-accumulation grad-sink entries, written while
        at most ~2 groups are resident.  Returns (module_skeleton,
        optimizer_sd_skeleton) — marker trees that slot into the normal
        checkpoint files, so the checkpoint loads in a non-paged engine
        via checkpointing.resolve_streamed.  Reference capability:
        swap-aware optimizer save, swap_tensor/optimizer_utils.py +
        partitioned_param_swapper.py:223-277."""
        import os

        from .. import checkpointing as ckpt_io

        write = jax.process_index() == 0  # masters replicated across hosts
        if write:
            os.makedirs(ckpt_dir, exist_ok=True)
        groups_markers: Dict[str, Any] = {}
        state_markers: Dict[str, str] = {}
        acc_markers: Dict[str, str] = {}
        order = self.group_order
        for idx, name in enumerate(order):
            _, treedef, shapes = self.masters[name]
            sizes = self._group_sizes(name)
            base = self._leaf_base[name]
            groups_markers[name] = jax.tree_util.tree_unflatten(
                treedef, [ckpt_io.stream_marker(name, f"leaf:{j}")
                          for j in range(len(shapes))])
            if write:
                flat = self._masters_flat(name)
                self._prefetch_masters(order[idx + 1]
                                       if idx + 1 < len(order) else None)
                payload: Dict[str, Any] = {
                    "leaves": {str(j): m.reshape(s).copy()
                               for j, (m, s) in enumerate(zip(flat, shapes))},
                    "optim": {}, "acc": {}}
            for j, n in enumerate(sizes):
                key = base + j
                if write:
                    if self.nvme is not None:
                        # nvme.load fabricates zeros for unknown keys —
                        # never-stepped leaves must serialize NO moments,
                        # not 8 bytes/param of zeros
                        st = (self.nvme.load(key, n)
                              if self.nvme.has(key) else None)
                    else:
                        st = self.adam._state.get(key)
                    if st is not None:
                        payload["optim"][str(key)] = {
                            k: np.asarray(v).copy() for k, v in st.items()}
                    if key in self._acc_sink:
                        payload["acc"][str(key)] = self._acc_sink[key]
                state_markers[str(key)] = ckpt_io.stream_marker(
                    name, f"optim:{key}")
                if key in self._acc_sink:
                    acc_markers[str(key)] = ckpt_io.stream_marker(
                        name, f"acc:{key}")
            if write:
                # pre-first-step: no moments exist yet; markers must not
                # dangle, so drop the skeleton entries for absent state
                for j in range(len(sizes)):
                    if str(base + j) not in payload["optim"]:
                        state_markers.pop(str(base + j), None)
                ckpt_io.write_stream_group(ckpt_dir, name, payload)
        sd: Dict[str, Any] = {"step": self.adam.step_count,
                              "state": state_markers,
                              "n_elements": self.n_elements}
        if self._acc_count:
            sd["acc_count"] = self._acc_count
            sd["acc_sink"] = acc_markers
        module_skel = self.model.assemble_groups(groups_markers)
        return module_skel, sd

    def load_streamed(self, ckpt_dir: str, sd: Optional[dict]) -> None:
        """RAM-bounded inverse of save_streamed: walk the group files,
        page each group's masters straight to NVMe and its moments into
        the moment store, never materializing the full model.  sd is the
        optimizer skeleton (None skips moments/step restore)."""
        import os

        from .. import checkpointing as ckpt_io

        # pre-flight BEFORE mutating anything: a missing group file must
        # leave the engine untouched (the loader's warn-and-return
        # contract), not half-loaded with mixed old/new masters
        missing = [name for name in self.group_order
                   if not os.path.isfile(
                       ckpt_io.stream_group_ckpt_name(ckpt_dir, name))]
        if missing:
            raise ckpt_io.CheckpointIntegrityError(
                f"streamed checkpoint incomplete: missing group files for "
                f"{missing} in {ckpt_dir}")
        self._kept.clear()
        load_opt = sd is not None
        if load_opt:
            self.adam.step_count = int(sd["step"])
            self.adam._state = {}
            self._acc_count = int(sd.get("acc_count", 0))
            self._acc_sink = {}
        for name in self.group_order:
            _, treedef, shapes = self.masters[name]
            sizes = self._group_sizes(name)
            base = self._leaf_base[name]
            payload = ckpt_io._read_stream_group(ckpt_dir, name)
            flat = [np.asarray(payload["leaves"][str(j)],
                               np.float32).ravel()
                    for j in range(len(shapes))]
            for f, n in zip(flat, sizes):
                if f.size != n:
                    raise ValueError(
                        f"stream group {name!r}: leaf size {f.size} != "
                        f"expected {n} (checkpoint/model config mismatch)")
            self._commit_masters(name, flat)
            if not load_opt:
                continue
            for key_s, st in (payload.get("optim") or {}).items():
                key = int(key_s)
                st = {k: np.asarray(v, np.float32) for k, v in st.items()}
                if self.nvme is not None:
                    self.nvme.store(key, st)
                else:
                    self.adam._state[key] = st
            for key_s, g in (payload.get("acc") or {}).items():
                self._acc_sink[int(key_s)] = np.asarray(g, np.float32)

    def masters_tree(self):
        # copies, not views: the masters mutate in place every step, and a
        # view would alias through zero-copy device_put on CPU backends.
        # NOTE: this materializes the FULL fp32 master set in host RAM —
        # engine checkpointing of paged masters streams group-by-group
        # (save_streamed) instead; this path remains for direct full-tree
        # access (engine.params, save_fp16_model), where materialization
        # is the point. Warn so an OOM is attributable
        if self.pager is not None:
            log_dist(
                f"materializing {self.n_elements * 4 / 2**30:.1f}"
                f" GiB of NVMe-paged fp32 masters in host RAM (checkpoint "
                f"save/load streams group-by-group and stays RAM-bounded; "
                f"this full-tree access does not)",
                ranks=[0])
        groups = {}
        for name in self.group_order:
            _, treedef, shapes = self.masters[name]
            flat = self._masters_flat(name)
            groups[name] = jax.tree_util.tree_unflatten(
                treedef, [m.reshape(s).copy() for m, s in zip(flat, shapes)])
        return self.model.assemble_groups(groups)

    def load_masters_tree(self, params):
        for name, tree in self.model.stream_groups(params):
            leaves = [np.asarray(l, np.float32).ravel()
                      for l in jax.tree_util.tree_leaves(tree)]
            _, treedef, shapes = self.masters[name]
            assert len(leaves) == len(shapes)
            if self.pager is not None:
                self.pager.write_group(name, leaves)
            else:
                self.masters[name] = (leaves, treedef, shapes)

    def state_dict(self):
        sd = self.adam.state_dict()
        if self.nvme is not None:
            # moments live on SSD between steps (train_step pops each into
            # the NvmeStateStore) — page them back for serialization, else
            # a checkpoint would silently carry empty Adam state
            state = {}
            base = 0
            for name in self.group_order:
                sizes = self._group_sizes(name)
                for j, n in enumerate(sizes):
                    st = self.nvme.load(base + j, n)
                    state[str(base + j)] = {k: v.copy()
                                            for k, v in st.items()}
                base += len(sizes)
            sd["state"] = state
        sd["n_elements"] = self.n_elements
        # mid-accumulation state: without this, a save between micro
        # steps would silently drop the pre-save grads and the resumed
        # boundary would apply a partial-batch update
        if self._acc_count:
            sd["acc_count"] = self._acc_count
            sd["acc_sink"] = {str(k): v.copy()
                              for k, v in self._acc_sink.items()}
        return sd

    def load_state_dict(self, sd):
        self.adam.load_state_dict({k: sd[k] for k in ("step", "state")})
        self._kept.clear()  # stash may predate the restored masters
        self._acc_count = int(sd.get("acc_count", 0))
        self._acc_sink = {int(k): np.asarray(v, np.float32)
                          for k, v in (sd.get("acc_sink") or {}).items()}
        if self.nvme is not None:
            # write restored moments through to the (fresh, pid-scoped)
            # store; train_step's nvme.load must see them, not zeros
            for key, st in list(self.adam._state.items()):
                self.nvme.store(int(key), st)
            self.adam._state = {}
