"""ZeRO config object (reference: deepspeed/runtime/zero/config.py:177).

On TPU the stages resolve to sharding specs (see zero/partition.py):
stage 1 shards optimizer state over the data axis, stage 2 additionally
reduce-scatters gradients, stage 3 additionally shards parameters with
XLA all-gather-on-use. Bucket/overlap knobs are accepted no-ops — XLA
latency-hides collectives without hand-managed buckets.
"""

from ..config_utils import DeepSpeedConfigObject, get_scalar_param
from . import constants as zc


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigObject):
    """reference zero/offload_config.py offload_param schema."""

    def __init__(self, param_dict=None):
        super().__init__()
        d = param_dict or {}
        self.device = get_scalar_param(d, zc.OFFLOAD_DEVICE, zc.OFFLOAD_CPU_DEVICE)
        self.nvme_path = get_scalar_param(d, zc.OFFLOAD_NVME_PATH, "/local_nvme")
        self.buffer_count = get_scalar_param(d, zc.OFFLOAD_BUFFER_COUNT, 5)
        self.buffer_size = get_scalar_param(d, zc.OFFLOAD_BUFFER_SIZE, int(1e8))
        self.max_in_cpu = get_scalar_param(d, zc.OFFLOAD_MAX_IN_CPU, int(1e9))
        self.pin_memory = get_scalar_param(d, zc.OFFLOAD_PIN_MEMORY, False)


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigObject):
    """reference zero/offload_config.py offload_optimizer schema."""

    def __init__(self, param_dict=None):
        super().__init__()
        d = param_dict or {}
        self.device = get_scalar_param(d, zc.OFFLOAD_DEVICE, zc.OFFLOAD_CPU_DEVICE)
        self.nvme_path = get_scalar_param(d, zc.OFFLOAD_NVME_PATH, "/local_nvme")
        self.buffer_count = get_scalar_param(d, zc.OFFLOAD_BUFFER_COUNT, 4)
        self.pin_memory = get_scalar_param(d, zc.OFFLOAD_PIN_MEMORY, False)
        self.pipeline_read = get_scalar_param(d, zc.OFFLOAD_PIPELINE_READ, False)
        self.pipeline_write = get_scalar_param(d, zc.OFFLOAD_PIPELINE_WRITE, False)
        self.fast_init = get_scalar_param(d, zc.OFFLOAD_FAST_INIT, False)
        self.pipeline = self.pipeline_read or self.pipeline_write


class DeepSpeedZeroConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        super().__init__()
        zero_dict = param_dict.get(zc.ZERO_OPTIMIZATION, None)
        if zero_dict is None:
            zero_dict = {}
        elif isinstance(zero_dict, bool):
            # legacy "zero_optimization": true => stage 1
            zero_dict = {zc.ZERO_OPTIMIZATION_STAGE: 1 if zero_dict else 0}
        elif not isinstance(zero_dict, dict):
            raise ValueError(
                f"ZeRO optimization must be a dict or bool, got {zero_dict!r}. "
                f"{zc.ZERO_FORMAT}")

        g = lambda key, default: get_scalar_param(zero_dict, key, default)

        self.stage = g(zc.ZERO_OPTIMIZATION_STAGE, zc.ZERO_OPTIMIZATION_STAGE_DEFAULT)
        if not (0 <= int(self.stage) <= zc.MAX_STAGE_ZERO_OPTIMIZATION):
            raise ValueError(f"invalid ZeRO stage {self.stage}")
        self.stage = int(self.stage)

        self.contiguous_gradients = g(
            zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
            zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT or self.stage == 3)
        self.reduce_scatter = g(zc.ZERO_OPTIMIZATION_REDUCE_SCATTER,
                                zc.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.reduce_bucket_size = int(g(zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                                        zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT))
        self.allgather_partitions = g(zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                                      zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = int(
            g(zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
              g(zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
                zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)))
        self.overlap_comm = g(zc.ZERO_OPTIMIZATION_OVERLAP_COMM,
                              self.stage == 3)
        self.load_from_fp32_weights = g(
            zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
            zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        self.elastic_checkpoint = g(zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                                    zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)

        # offload: new-style dicts win over legacy cpu_offload booleans
        self.cpu_offload = g(zc.ZERO_OPTIMIZATION_CPU_OFFLOAD,
                             zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.cpu_offload_params = g(zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS,
                                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT)
        self.cpu_offload_use_pin_memory = g(
            zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
            zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)

        offload_param_dict = zero_dict.get(zc.ZERO_OPTIMIZATION_OFFLOAD_PARAM)
        offload_opt_dict = zero_dict.get(zc.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER)
        if self.cpu_offload_params and offload_param_dict is None:
            offload_param_dict = {zc.OFFLOAD_DEVICE: zc.OFFLOAD_CPU_DEVICE,
                                  zc.OFFLOAD_PIN_MEMORY: self.cpu_offload_use_pin_memory}
        if self.cpu_offload and offload_opt_dict is None:
            offload_opt_dict = {zc.OFFLOAD_DEVICE: zc.OFFLOAD_CPU_DEVICE,
                                zc.OFFLOAD_PIN_MEMORY: self.cpu_offload_use_pin_memory}
        self.offload_param = (DeepSpeedZeroOffloadParamConfig(offload_param_dict)
                              if offload_param_dict is not None else None)
        self.offload_optimizer = (
            DeepSpeedZeroOffloadOptimizerConfig(offload_opt_dict)
            if offload_opt_dict is not None else None)
        # normalize legacy flags from new-style dicts
        if self.offload_optimizer is not None and \
                self.offload_optimizer.device == zc.OFFLOAD_CPU_DEVICE:
            self.cpu_offload = True
        if self.offload_param is not None and \
                self.offload_param.device == zc.OFFLOAD_CPU_DEVICE:
            self.cpu_offload_params = True

        # stage-3 knobs
        self.sub_group_size = int(g(zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE,
                                    zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT))
        self.max_live_parameters = int(g(
            zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
            zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT))
        self.max_reuse_distance = int(g(
            zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
            zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT))
        self.prefetch_bucket_size = int(g(
            zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
            zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT))
        self.param_persistence_threshold = int(g(
            zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
            zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT))
        self.gather_fp16_weights_on_model_save = g(
            zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
            zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)

        # qwZ: quantize the stage-3 parameter all-gather (ZeRO++).
        # Normalized to None | "int8" | "int4"; the master weights and
        # optimizer math stay full precision either way.
        qw = g(zc.ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS,
               zc.ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT)
        if isinstance(qw, bool) or qw is None:
            self.quantized_weights = "int8" if qw else None
        else:
            qw = str(qw).lower()
            if qw in ("false", "none", "off"):
                self.quantized_weights = None
            elif qw in ("true", "int8", "int4"):
                self.quantized_weights = "int8" if qw == "true" else qw
            else:
                raise ValueError(
                    f"zero_optimization.{zc.ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS} "
                    f"must be false, true, 'int8' or 'int4', got {qw!r}")
