"""Chaos-ready runtime: deterministic fault injection, transient-fault
retry, and an in-process hang watchdog.

DeepSpeed parity at pod scale means surviving the failures pods
actually have — flaky coordination-KV calls, storage hiccups
mid-checkpoint, dead data-pipeline workers, hung collectives — not just
clean SIGTERMs.  PR 6 built the recovery machinery (two-phase commit,
elastic restart); this module adds (1) the hardening that keeps a
TRANSIENT fault from being promoted to a full process death, and (2)
the only way to *prove* those paths work: deterministic fault
injection, so a chaos campaign is a reproducible test, not a shrug.

Three pieces:

* **FaultPlan** — seedable rules keyed by injection site, fault kind
  (`raise` / `delay_ms` / `corrupt` / `hang` / `kill`), rank, and a
  step/call schedule.  Layers that can actually fail carry named
  `fault_point(site)` hooks (hostwire KV traffic, checkpoint file IO
  and commit, prefetch workers, the engine step boundary); with no plan
  installed a hook is one module-global read — cheap enough to stay
  unconditional, like the monitor counters.  Determinism contract: the
  same (seed, rules) against the same invocation sequence injects the
  IDENTICAL fault sequence (pinned in tier-1) — a chaos failure is
  replayable by re-running with the same config.
* **retry_transient()** — bounded exponential backoff + jitter around
  an idempotent operation, with the transient-vs-fatal taxonomy
  (`is_transient`): coordination-KV blips and storage EIO retry;
  config/programming errors propagate immediately.  Applied to the
  hostwire KV ops and `checkpointing._atomic_write`.
* **StepWatchdog** — an in-process thread that detects a step/barrier
  exceeding its deadline (hung collective, wedged peer: the failure
  mode where the victim cannot raise), dumps a diagnostic snapshot
  (all-thread stack traces + monitor counter totals) to the run dir,
  and escalates to the elasticity supervisor by writing a
  machine-readable `watchdog_trip.json` that
  `elasticity.supervisor.HeartbeatWatcher` polls for.

Counters (monitor/counters.py, rendered as the report's "Resilience"
section): `fault.injected` (per injection), `fault.retried` (per retry
attempt), `fault.recovered_ms` (wall µs spent inside retry loops that
eventually succeeded, in the bytes slot), `watchdog.trips`.

Config ("faults" block, runtime/config.py):

    "faults": {
      "seed": 0,
      "enabled": true,                # default: true iff rules present
      "rules": [
        {"site": "hostwire.kv_get", "kind": "raise", "rank": 1,
         "calls": [0], "times": 1},
        {"site": "ckpt.atomic_write", "kind": "delay_ms",
         "delay_ms": 50, "every": 4},
        {"site": "engine.step", "kind": "hang", "hang_s": 30,
         "steps": [100]}
      ],
      "retry": {"max_attempts": 4, "base_delay_ms": 50,
                "max_delay_ms": 2000, "jitter": 0.25},
      "watchdog": {"enabled": true, "deadline_s": 600, "poll_s": 1.0}
    }

Injection (`rules`) is gated on `enabled`; the retry policy and the
watchdog are HARDENING and configure independently of it.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from ..monitor.counters import COUNTERS
from ..utils.logging import logger

FAULT_KINDS = ("raise", "delay_ms", "corrupt", "hang", "kill")

# escalation file the supervisor's HeartbeatWatcher polls for in the
# monitor run dir (elasticity/supervisor.py)
WATCHDOG_TRIP_FILE = "watchdog_trip.json"


class TransientFault(RuntimeError):
    """A fault the taxonomy classifies as retryable (coordination-KV
    blip, storage hiccup).  Injected transient faults are instances."""


class InjectedFault(TransientFault):
    """A fault raised by a FaultPlan `raise` rule (transient=true)."""


class InjectedFatalFault(RuntimeError):
    """A fault raised by a `raise` rule with transient=false — must NOT
    be absorbed by retry_transient (taxonomy regression cover)."""


# -- transient-vs-fatal taxonomy --------------------------------------------

# exception types that are retryable by nature: the operation may
# succeed verbatim on the next attempt
_TRANSIENT_TYPES = (TransientFault, TimeoutError, ConnectionError,
                    InterruptedError, BrokenPipeError)
# gRPC/coordination-service status markers that surface as plain
# RuntimeError text from the jax distributed client
_TRANSIENT_MARKERS = ("DEADLINE_EXCEEDED", "DEADLINE EXCEEDED",
                      "UNAVAILABLE", "ABORTED", "RESOURCE_EXHAUSTED",
                      "connection reset", "temporarily unavailable")
# OSError errnos worth retrying (EIO: storage hiccup; EAGAIN/EBUSY:
# contention).  ENOSPC/EROFS/ENOENT stay fatal — retrying cannot help.
_TRANSIENT_ERRNOS = frozenset(
    getattr(__import__("errno"), name)
    for name in ("EIO", "EAGAIN", "EBUSY", "EINTR", "ETIMEDOUT",
                 "ECONNRESET", "ECONNREFUSED", "ENETUNREACH"))


def is_transient(exc: BaseException) -> bool:
    """The fault taxonomy: True when retrying the SAME operation can
    plausibly succeed.  Fatal classes (FileNotFoundError, ValueError,
    injected-fatal, ...) return False so retry wrappers re-raise them
    on the first attempt instead of burning the backoff budget."""
    if isinstance(exc, InjectedFatalFault):
        return False
    if isinstance(exc, _TRANSIENT_TYPES):
        return True
    if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError)):
        return False
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    if isinstance(exc, (ValueError, TypeError, KeyError, AssertionError)):
        return False
    msg = str(exc)
    return any(m.lower() in msg.lower() for m in _TRANSIENT_MARKERS)


def _is_timeoutish(exc: BaseException) -> bool:
    return isinstance(exc, TimeoutError) or \
        "deadline" in str(exc).lower() or "timed out" in str(exc).lower()


def is_transient_not_timeout(exc: BaseException) -> bool:
    """Taxonomy variant for BLOCKING waits whose timeout is itself the
    dead-peer detector (KVSignals.wait, barrier rendezvous): retrying a
    deadline there multiplies the effective timeout and delays the
    legitimate failure surface, so timeouts stay fatal while genuine
    transport blips (UNAVAILABLE, connection reset, injected transient
    faults) still retry."""
    return is_transient(exc) and not _is_timeoutish(exc)


# -- retry ------------------------------------------------------------------


class RetryPolicy:
    """Bounded exponential backoff + jitter for transient faults.

    `max_attempts` counts TOTAL tries (1 = no retry); the delay before
    retry k is base_delay_ms * 2^(k-1), capped at max_delay_ms, times a
    uniform jitter in [1-jitter, 1+jitter] so a fleet of ranks does not
    hammer a recovering coordinator in lockstep.  `rng`/`sleep` are
    injectable for tests."""

    def __init__(self, max_attempts: int = 4, base_delay_ms: float = 50.0,
                 max_delay_ms: float = 2000.0, jitter: float = 0.25,
                 rng=None, sleep=time.sleep):
        if int(max_attempts) < 1:
            raise ValueError(
                f"retry max_attempts must be >= 1, got {max_attempts}")
        if not 0.0 <= float(jitter) < 1.0:
            raise ValueError(f"retry jitter must be in [0, 1), got {jitter}")
        if float(base_delay_ms) < 0 or float(max_delay_ms) < 0:
            raise ValueError("retry delays must be >= 0")
        self.max_attempts = int(max_attempts)
        self.base_delay_ms = float(base_delay_ms)
        self.max_delay_ms = float(max_delay_ms)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry `attempt` (1-based)."""
        d = min(self.base_delay_ms * (2.0 ** (attempt - 1)),
                self.max_delay_ms)
        return d * self._rng.uniform(1.0 - self.jitter,
                                     1.0 + self.jitter) / 1000.0


_DEFAULT_RETRY = RetryPolicy()


def default_retry_policy() -> RetryPolicy:
    return _DEFAULT_RETRY


def install_retry_policy(policy: Optional[RetryPolicy]) -> None:
    """Install the process-global retry policy (config-driven; None
    restores the built-in defaults)."""
    global _DEFAULT_RETRY
    _DEFAULT_RETRY = policy if policy is not None else RetryPolicy()


def retry_transient(fn: Callable[[], Any], site: str = "",
                    policy: Optional[RetryPolicy] = None,
                    classify: Callable[[BaseException], bool] = is_transient):
    """Run `fn()` retrying TRANSIENT failures with bounded backoff.

    `fn` must be idempotent (every instrumented site is: KV set/get of
    write-once keys, tmp+rename file writes).  Fatal faults — and the
    last transient attempt — re-raise unchanged.  Bookkeeping:
    `fault.retried` counts retry attempts, `fault.recovered_ms` (µs in
    the bytes slot) the wall time ops spent recovering before
    eventually succeeding."""
    policy = policy or _DEFAULT_RETRY
    t0 = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            out = fn()
            if t0 is not None:
                COUNTERS.add("fault.recovered_ms",
                             int((time.perf_counter() - t0) * 1e6))
            return out
        except BaseException as e:  # noqa: BLE001 — classified below
            if not classify(e) or attempt >= policy.max_attempts:
                raise
            if t0 is None:
                t0 = time.perf_counter()
            COUNTERS.add("fault.retried")
            delay = policy.delay_s(attempt)
            logger.warning(
                f"transient fault at {site or 'op'} (attempt {attempt}/"
                f"{policy.max_attempts}): {type(e).__name__}: {e}; "
                f"retrying in {delay * 1000:.0f} ms")
            policy._sleep(delay)


# -- fault rules / plan -----------------------------------------------------

_RULE_KEYS = {"site", "kind", "rank", "steps", "calls", "every", "prob",
              "times", "delay_ms", "hang_s", "exit_code", "transient",
              "truncate_to"}


class FaultRule:
    """One injection rule.  `site` is an fnmatch pattern over injection
    site names; the schedule is any combination of `rank` (None = every
    rank), `steps` (engine global steps; None = any), and per-site
    invocation selectors — `calls` (0-based site-invocation indices),
    `every` (every Nth matching invocation), `prob` (seeded coin per
    invocation).  With no invocation selector the rule fires on every
    matching invocation.  `times` caps total injections (default: 1 for
    hang/kill — a second one can never be reached anyway — else
    unbounded)."""

    def __init__(self, site: str, kind: str, rank: Optional[int] = None,
                 steps: Optional[List[int]] = None,
                 calls: Optional[List[int]] = None,
                 every: Optional[int] = None, prob: Optional[float] = None,
                 times: Optional[int] = None, delay_ms: float = 100.0,
                 hang_s: float = 3600.0, exit_code: int = 173,
                 transient: bool = True, truncate_to: int = 8):
        if not site:
            raise ValueError("fault rule needs a non-empty 'site'")
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"fault rule kind must be one of {FAULT_KINDS}, got {kind!r}")
        if prob is not None and not 0.0 <= float(prob) <= 1.0:
            raise ValueError(f"fault rule prob must be in [0, 1], got {prob}")
        if every is not None and int(every) < 1:
            raise ValueError(f"fault rule every must be >= 1, got {every}")
        # config-time validation is the contract: a malformed schedule
        # or negative sleep must never surface mid-training-step
        for name, val in (("steps", steps), ("calls", calls)):
            if val is not None and (isinstance(val, (str, bytes))
                                    or not hasattr(val, "__iter__")):
                raise ValueError(
                    f"fault rule {name} must be a list of ints, got "
                    f"{val!r}")
        for name, val in (("delay_ms", delay_ms), ("hang_s", hang_s)):
            if float(val) < 0:
                raise ValueError(
                    f"fault rule {name} must be >= 0, got {val}")
        if times is not None and int(times) < 0:
            raise ValueError(f"fault rule times must be >= 0, got {times}")
        if int(truncate_to) < 0:
            raise ValueError(
                f"fault rule truncate_to must be >= 0, got {truncate_to}")
        self.site = str(site)
        self.kind = str(kind)
        self.rank = None if rank is None else int(rank)
        self.steps = None if steps is None else [int(s) for s in steps]
        self.calls = None if calls is None else [int(c) for c in calls]
        self.every = None if every is None else int(every)
        self.prob = None if prob is None else float(prob)
        if times is None and kind in ("hang", "kill"):
            times = 1
        self.times = None if times is None else int(times)
        self.delay_ms = float(delay_ms)
        self.hang_s = float(hang_s)
        self.exit_code = int(exit_code)
        self.transient = bool(transient)
        self.truncate_to = int(truncate_to)
        self.fired = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultRule":
        if not isinstance(d, dict):
            raise ValueError(f"each faults.rules entry must be an object, "
                             f"got {type(d).__name__}")
        unknown = set(d) - _RULE_KEYS
        if unknown:
            raise ValueError(
                f"faults rule: unknown key(s) {sorted(unknown)}; expected "
                f"a subset of {sorted(_RULE_KEYS)}")
        if "site" not in d or "kind" not in d:
            raise ValueError("faults rule needs 'site' and 'kind'")
        return cls(**d)

    def describe(self) -> Dict[str, Any]:
        out = {"site": self.site, "kind": self.kind}
        for k in ("rank", "steps", "calls", "every", "prob", "times"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


class FaultPlan:
    """Deterministic, seedable fault injector.

    Site hooks call `check(site)` (may raise/sleep/exit) and data sites
    `filter(site, payload)` (corrupt rules).  Rule matching consumes a
    per-rule `random.Random(seed, rule_index)` stream only on `prob`
    evaluation of MATCHING invocations, and everything else keys off
    per-site invocation counts and the engine-advanced step — so the
    same plan against the same invocation sequence injects the
    identical fault sequence (the `injection_log` records it;
    determinism is pinned in tier-1).

    Thread-safe: sites fire from the training thread, the checkpoint
    writer pool, and prefetch workers."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 rank: Optional[int] = None, enabled: bool = True,
                 clock=time.monotonic):
        self.rules = list(rules)
        self.seed = int(seed)
        self.rank = rank  # resolved lazily when None (pre-distributed init)
        self.enabled = bool(enabled)
        self._clock = clock
        self._lock = threading.Lock()
        self._site_calls: Dict[str, int] = {}
        self._step = 0
        # one independent, deterministic stream per rule (int-seeded:
        # tuple seeding is deprecated and hash-dependent)
        self._rngs = [random.Random(self.seed * 1_000_003 + i)
                      for i in range(len(self.rules))]
        self.injection_log: List[Dict[str, Any]] = []

    @classmethod
    def from_config(cls, rules: List[Dict[str, Any]], seed: int = 0,
                    enabled: Optional[bool] = None) -> "FaultPlan":
        parsed = [FaultRule.from_dict(r) for r in rules]
        if enabled is None:
            enabled = bool(parsed)
        return cls(parsed, seed=seed, enabled=enabled)

    # -- schedule state ----------------------------------------------------

    def set_step(self, step: int) -> None:
        self._step = int(step)

    def _resolve_rank(self) -> int:
        if self.rank is None:
            try:
                import jax

                self.rank = int(jax.process_index())
            except Exception:
                self.rank = 0
        return self.rank

    def _select(self, site: str):
        """The first rule firing at this (site, rank, step, invocation),
        or None.  Increments the site invocation count either way."""
        with self._lock:
            idx = self._site_calls.get(site, 0)
            self._site_calls[site] = idx + 1
            if not self.enabled:
                return None, idx
            rank = self._resolve_rank()
            for i, rule in enumerate(self.rules):
                if not fnmatch.fnmatch(site, rule.site):
                    continue
                if rule.rank is not None and rule.rank != rank:
                    continue
                if rule.steps is not None and self._step not in rule.steps:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.calls is not None:
                    if idx not in rule.calls:
                        continue
                elif rule.every is not None:
                    if idx % rule.every != 0:
                        continue
                elif rule.prob is not None:
                    # the rng stream advances ONLY on matching
                    # invocations: deterministic across identical runs
                    if self._rngs[i].random() >= rule.prob:
                        continue
                rule.fired += 1
                entry = {"site": site, "kind": rule.kind, "rule": i,
                         "rank": rank, "step": self._step, "call": idx}
                self.injection_log.append(entry)
                return rule, idx
        return None, idx

    # -- site hooks --------------------------------------------------------

    def check(self, site: str) -> None:
        """Evaluate `site`: may raise InjectedFault/InjectedFatalFault,
        sleep (delay/hang), or kill the process."""
        rule, idx = self._select(site)
        if rule is None:
            return
        COUNTERS.add("fault.injected")
        if rule.kind == "raise":
            exc = (InjectedFault if rule.transient else InjectedFatalFault)(
                f"injected {'transient' if rule.transient else 'fatal'} "
                f"fault at {site} (call {idx}, step {self._step})")
            logger.warning(f"fault injection: raising at {site}: {exc}")
            raise exc
        if rule.kind == "delay_ms":
            logger.warning(f"fault injection: delaying {site} by "
                           f"{rule.delay_ms:.0f} ms")
            time.sleep(rule.delay_ms / 1000.0)
            return
        if rule.kind == "hang":
            logger.warning(f"fault injection: HANGING {site} for "
                           f"{rule.hang_s:.0f}s (watchdog bait)")
            time.sleep(rule.hang_s)
            return
        if rule.kind == "kill":
            logger.error(f"fault injection: KILLING process at {site} "
                         f"(exit {rule.exit_code})")
            sys.stderr.flush()
            os._exit(rule.exit_code)
        # "corrupt" selected through check(): the site carries no
        # payload here, treat as a transient raise so the schedule
        # still advances loudly instead of silently no-oping
        raise InjectedFault(
            f"injected corrupt-at-non-payload-site fault at {site}")

    def filter(self, site: str, payload: bytes) -> bytes:
        """Payload sites: apply a matching `corrupt` rule (truncation —
        the torn-write shape checksum/commit layers must catch); other
        kinds behave like check()."""
        rule, idx = self._select(site)
        if rule is None:
            return payload
        COUNTERS.add("fault.injected")
        if rule.kind == "corrupt":
            keep = min(len(payload), max(0, rule.truncate_to))
            logger.warning(
                f"fault injection: corrupting payload at {site} "
                f"({len(payload)} -> {keep} bytes)")
            return payload[:keep]
        if rule.kind == "raise":
            raise (InjectedFault if rule.transient
                   else InjectedFatalFault)(
                f"injected fault at {site} (call {idx})")
        if rule.kind == "delay_ms":
            time.sleep(rule.delay_ms / 1000.0)
        elif rule.kind == "hang":
            time.sleep(rule.hang_s)
        elif rule.kind == "kill":
            os._exit(rule.exit_code)
        return payload

    def describe(self) -> str:
        return (f"FaultPlan(seed={self.seed}, enabled={self.enabled}, "
                f"rules={[r.describe() for r in self.rules]})")


# -- process-global installation -------------------------------------------

_PLAN: Optional[FaultPlan] = None


def install_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with None) THE process-global fault plan every
    `fault_point` hook consults.  Returns the previous plan."""
    global _PLAN
    prev, _PLAN = _PLAN, plan
    if plan is not None and plan.enabled and plan.rules:
        logger.warning(f"fault injection ACTIVE: {plan.describe()}")
    return prev


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def fault_point(site: str) -> None:
    """Named injection site.  One global read when no plan is installed
    — cheap enough to live on hot paths unconditionally (the counter
    discipline, monitor/counters.py)."""
    if _PLAN is not None:
        _PLAN.check(site)


def fault_filter(site: str, payload: bytes) -> bytes:
    """Payload-carrying injection site (corrupt rules)."""
    if _PLAN is not None:
        return _PLAN.filter(site, payload)
    return payload


def step_boundary(step: int) -> None:
    """Advance the plan's step schedule + fire the engine step site.
    Called by the engine at every optimizer-step boundary."""
    if _PLAN is not None:
        _PLAN.set_step(step)
        _PLAN.check("engine.step")


# -- watchdog ---------------------------------------------------------------


def _all_stacks() -> Dict[str, List[str]]:
    """Stack traces for every live thread (the snapshot's core: WHAT is
    the hung step blocked on)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in frames.items():
        name = names.get(ident, f"thread-{ident}")
        out[f"{name} ({ident})"] = traceback.format_stack(frame)
    return out


class StepWatchdog:
    """In-process hang detector: a background thread that trips when no
    step-boundary `beat()` lands within `deadline_s`.

    On a trip it (1) dumps a diagnostic snapshot — all-thread stack
    traces + the monitor counter totals + the last beat — to
    `<snapshot_dir>/watchdog_snapshot.rank<r>.<n>.json`, (2) bumps the
    `watchdog.trips` counter, and (3) escalates to the elasticity
    supervisor by atomically writing `watchdog_trip.json` (machine-
    readable reason + snapshot path) into `escalate_dir` — the monitor
    run dir `HeartbeatWatcher` already polls, closing the loop to a
    SIGTERM-first elastic restart even though this process can no
    longer make progress on its own.  One trip per stall: it re-arms
    only after a fresh beat.

    Size `deadline_s` above the worst-case LEGITIMATE inter-beat gap —
    first-step compilation and a synchronous checkpoint's serialize+
    fsync both land between beats — or slow-but-progressing steps trip
    it spuriously; the 600 s default is sized for that, chaos tests use
    a couple of seconds.

    First-beat grace (`first_beat_mult`): BEFORE the first beat lands,
    the effective deadline is `deadline_s * first_beat_mult` anchored
    at construction.  The window before beat 1 is where full program
    compilation lives, and an ELASTIC restart (shrink-to-survivors or
    grow-back, elasticity/supervisor.py) recompiles every step program
    at the new mesh shape — a legitimate shrink-restart must not trip
    the watchdog that exists to catch the hang it is recovering from.
    `first_beat_mult=None` keeps the legacy behavior: not armed until
    the first beat (a pre-training hang is then the supervisor's
    stall-timeout's problem, not this watchdog's).  The engine wires
    `faults.watchdog.first_beat_mult` (default 4.0) here.

    The thread is daemonized and wakes every `poll_s`; `clock` and
    `on_trip` are injectable for tests."""

    def __init__(self, deadline_s: float, snapshot_dir: str,
                 escalate_dir: Optional[str] = None, poll_s: float = 1.0,
                 rank: int = 0, clock=time.monotonic,
                 on_trip: Optional[Callable[[Dict[str, Any]], None]] = None,
                 first_beat_mult: Optional[float] = None):
        if float(deadline_s) <= 0:
            raise ValueError(
                f"watchdog deadline_s must be > 0, got {deadline_s}")
        if float(poll_s) <= 0:
            # Event.wait(0) never blocks: a zero poll busy-spins the
            # daemon thread on a core for the whole run
            raise ValueError(f"watchdog poll_s must be > 0, got {poll_s}")
        if first_beat_mult is not None and float(first_beat_mult) < 1.0:
            # a sub-1 multiplier would make the COMPILE window stricter
            # than steady state — always wrong
            raise ValueError(f"watchdog first_beat_mult must be >= 1, "
                             f"got {first_beat_mult}")
        self.deadline_s = float(deadline_s)
        self.first_beat_mult = (None if first_beat_mult is None
                                else float(first_beat_mult))
        self.snapshot_dir = snapshot_dir
        self.escalate_dir = escalate_dir or snapshot_dir
        self.poll_s = float(poll_s)
        self.rank = int(rank)
        self._clock = clock
        self._on_trip = on_trip
        self._lock = threading.Lock()
        self._armed_at = clock()
        self._last_beat: Optional[float] = None
        self._last_step: Optional[int] = None
        self._tripped = False
        self._trips = 0
        self._thread_groups: Dict[str, Callable[[], list]] = {}
        self._flight_recorder: Optional[Callable[[], list]] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="dstpu-watchdog", daemon=True)
        self._thread.start()

    def beat(self, step: Optional[int] = None) -> None:
        """Progress heartbeat from the training thread; arms the
        deadline on the first call and re-arms after a trip."""
        with self._lock:
            self._last_beat = self._clock()
            if step is not None:
                self._last_step = int(step)
            self._tripped = False

    @property
    def trips(self) -> int:
        return self._trips

    def register_threads(self, group: str, threads_fn) -> None:
        """Register a named group of service threads (`threads_fn()` ->
        live threading.Thread list) whose liveness the trip snapshot
        reports explicitly — e.g. the overlap exchange's sender/
        receiver threads, so a hung exchange reads as 'exchange' in the
        snapshot instead of an anonymous 300 s stall.  Re-registering a
        group replaces it; a dead provider is dropped silently (the
        snapshot must never crash the watchdog)."""
        with self._lock:
            self._thread_groups[group] = threads_fn

    def unregister_threads(self, group: str) -> None:
        """Drop a registered thread group — the provider closure holds
        its owner alive, so tearing a service down (e.g. a demoted
        overlap exchange) must unregister or the watchdog pins the
        dead object (and its buffers) for the rest of the process."""
        with self._lock:
            self._thread_groups.pop(group, None)

    def set_flight_recorder(self, tail_fn) -> None:
        """Register a trace-tail provider (`tail_fn()` -> the newest
        trace events, e.g. monitor/tracing.py TraceRecorder.last_events)
        — the trip snapshot then ships a `trace_tail` timeline of what
        the wedged step was doing.  Like register_threads, a raising
        provider is reported, never propagated."""
        with self._lock:
            self._flight_recorder = tail_fn

    def _flight_recorder_tail(self) -> Optional[list]:
        with self._lock:
            fn = self._flight_recorder
        if fn is None:
            return None
        try:
            return list(fn())
        except Exception as e:
            return [{"error": f"{type(e).__name__}: {e}"}]

    def _thread_group_report(self) -> Dict[str, Any]:
        with self._lock:
            groups = dict(self._thread_groups)
        report = {}
        for name, fn in groups.items():
            try:
                report[name] = [
                    {"name": t.name, "alive": t.is_alive(),
                     "daemon": t.daemon, "ident": t.ident}
                    for t in fn()]
            except Exception as e:
                report[name] = [{"error": f"{type(e).__name__}: {e}"}]
        return report

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                beat, step = self._last_beat, self._last_step
                tripped = self._tripped
                armed_at = self._armed_at
            if tripped:
                continue
            if beat is None:
                # pre-first-beat: only armed when a first-beat grace
                # multiplier was configured (recompile after an elastic
                # restart legitimately lands in this window)
                if self.first_beat_mult is None:
                    continue
                stalled = self._clock() - armed_at
                if stalled > self.deadline_s * self.first_beat_mult:
                    try:
                        self.trip(stalled, None, first_beat=True)
                    except Exception as e:
                        logger.error(
                            f"watchdog trip handling failed: {e}")
                continue
            stalled = self._clock() - beat
            if stalled > self.deadline_s:
                try:
                    self.trip(stalled, step)
                except Exception as e:  # the watchdog must never crash
                    logger.error(f"watchdog trip handling failed: {e}")

    def trip(self, stalled_s: float, step: Optional[int],
             first_beat: bool = False) -> None:
        with self._lock:
            if self._tripped:
                return
            self._tripped = True
            self._trips += 1
            n = self._trips
        if first_beat:
            reason = (f"first step never completed: no step-boundary "
                      f"beat in {stalled_s:.1f}s since arming (> "
                      f"{self.deadline_s:.1f}s x first_beat_mult "
                      f"{self.first_beat_mult:g} — sized to cover "
                      f"first-step compile, incl. an elastic restart's "
                      f"recompile at the new mesh shape)")
        else:
            reason = (f"step deadline exceeded: no step-boundary progress "
                      f"in {stalled_s:.1f}s (> {self.deadline_s:.1f}s) "
                      f"after step {step}")
        logger.error(f"watchdog TRIP (rank {self.rank}): {reason}")
        COUNTERS.add("watchdog.trips")
        snapshot = {
            "reason": reason,
            "rank": self.rank,
            "last_step": step,
            "stalled_s": round(float(stalled_s), 3),
            "deadline_s": self.deadline_s,
            "trip": n,
            "unix_time": time.time(),
            "counters": COUNTERS.totals(),
            "stacks": _all_stacks(),
            "thread_groups": self._thread_group_report(),
        }
        tail = self._flight_recorder_tail()
        if tail is not None:
            snapshot["trace_tail"] = tail
        snap_path = os.path.join(
            self.snapshot_dir,
            f"watchdog_snapshot.rank{self.rank:05d}.{n}.json")
        try:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            self._atomic_json(snap_path, snapshot)
        except OSError as e:
            logger.error(f"watchdog snapshot write failed: {e}")
            snap_path = None
        trip = {
            "reason": reason,
            "rank": self.rank,
            "last_step": step,
            "stalled_s": round(float(stalled_s), 3),
            "snapshot": snap_path,
            "unix_time": time.time(),
        }
        try:
            os.makedirs(self.escalate_dir, exist_ok=True)
            self._atomic_json(
                os.path.join(self.escalate_dir, WATCHDOG_TRIP_FILE), trip)
        except OSError as e:
            logger.error(f"watchdog escalation write failed: {e}")
        if self._on_trip is not None:
            self._on_trip(trip)

    @staticmethod
    def _atomic_json(path: str, payload: Dict[str, Any]) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True, default=str)
        os.replace(tmp, path)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def read_watchdog_trip(run_dir: str) -> Optional[Dict[str, Any]]:
    """The machine-readable escalation payload under `run_dir`, or None.
    Shared by StepWatchdog (writer) and HeartbeatWatcher (poller)."""
    path = os.path.join(run_dir, WATCHDOG_TRIP_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
