"""Checkpoint save/load with reference-compatible layout + sharded I/O.

Reference: deepspeed/runtime/engine.py:1462-1890. Layout kept:

    <save_dir>/<tag>/mp_rank_00_model_states.msgpack
    <save_dir>/<tag>/zero_pp_rank_<dp>_mp_rank_00_optim_states.msgpack
    <save_dir>/latest                     (text file holding the tag)

Sharded design (reference engine.py:1462-1489 per-rank shard files):
device-sharded leaves are NOT gathered to one host. Each distinct shard of
a sharded jax.Array is written as a piece (with its index) into the
zero_pp_rank_<r> file of its shard rank; the model/optim skeleton files
keep a marker per sharded leaf. In multi-host jobs each process writes
only the pieces it can address — no cross-host gather, every host writes
in parallel (the reference's per-rank writer behaviour). Rank files are
written by a background thread pool; save returns after the writes land
(pass async_save=True to overlap with training and flush_pending() later).

On load the pieces are reassembled into full host arrays, so checkpoints
stay elastic by construction — loading at a different world size just
re-shards via device_put (subsumes the reference's ZeRO-1 elastic
re-partition logic, zero/stage1.py:924-1155). Unsharded (round-1/2 format)
checkpoints load unchanged.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

import jax
from flax import serialization

from ..utils.logging import logger

_SHARD_MARKER = "__dstpu_sharded_leaf__"
_writer = ThreadPoolExecutor(max_workers=4)
_pending: List[Any] = []


def flush_pending():
    """Block until all async checkpoint writes have landed."""
    global _pending
    for f in _pending:
        f.result()
    _pending = []


def _to_host(tree):
    def conv(x):
        if isinstance(x, (str, bytes, bool, int, float)) or x is None:
            return x  # plain scalars serialize natively; np.str_ would not
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def _is_sharded(x) -> bool:
    try:
        return isinstance(x, jax.Array) and not x.is_fully_replicated
    except Exception:
        return False


def _normalize_index(index, shape):
    return tuple(
        (0 if sl.start is None else int(sl.start),
         int(shape[d]) if sl.stop is None else int(sl.stop))
        for d, sl in enumerate(index))


def _split_sharded(tree, rank_pieces: Dict[int, Dict[str, Any]],
                   prefix: str):
    """Replace device-sharded leaves with markers; deposit each distinct
    shard (piece + index) into its shard-rank's payload. Replicated / host
    leaves come back as host arrays.

    Multi-host: a piece is written by the process owning the
    lowest-device-id replica of that shard, so every piece is written
    exactly once and no process gathers remote data."""

    proc = jax.process_index()

    def visit(path, leaf):
        if not _is_sharded(leaf):
            if isinstance(leaf, (str, bytes, bool, int, float)) or \
                    leaf is None:
                return leaf
            return np.asarray(leaf)
        key = prefix + jax.tree_util.keystr(path)
        imap = leaf.sharding.devices_indices_map(leaf.shape)
        owner = {}
        for dev, index in imap.items():
            idx = _normalize_index(index, leaf.shape)
            if idx not in owner or dev.id < owner[idx].id:
                owner[idx] = dev
        local = {}
        for sh in leaf.addressable_shards:
            idx = _normalize_index(sh.index, leaf.shape)
            if owner[idx].process_index == proc and idx not in local:
                local[idx] = sh.data
        for idx, data in local.items():
            # file index = owner DEVICE id: globally unique, so exactly one
            # process ever writes a given rank file (piece ranks per leaf
            # would collide across processes on mixed 2D shardings — the
            # loader merges pieces by key across all files, so file
            # assignment only needs to be collision-free, not dense)
            rank_pieces.setdefault(owner[idx].id, {})[key] = {
                "index": [list(p) for p in idx],
                "piece": np.asarray(data),
            }
        return {_SHARD_MARKER: True, "key": key,
                "shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "num_pieces": len(owner)}

    return jax.tree_util.tree_map_with_path(visit, tree)


def _is_marker(x) -> bool:
    return isinstance(x, dict) and x.get(_SHARD_MARKER, False)


def _reassemble(tree, pieces_by_key: Dict[str, list]):
    """Inverse of _split_sharded: markers -> full host arrays."""

    def visit(leaf):
        if not _is_marker(leaf):
            return leaf
        key = leaf["key"]
        got = pieces_by_key.get(key, [])
        if len(got) != int(leaf["num_pieces"]):
            raise FileNotFoundError(
                f"sharded checkpoint leaf {key}: found {len(got)} of "
                f"{leaf['num_pieces']} pieces (missing rank files?)")
        full = np.empty([int(s) for s in leaf["shape"]],
                        dtype=np.dtype(leaf["dtype"]))
        for entry in got:
            sl = tuple(slice(int(a), int(b)) for a, b in entry["index"])
            full[sl] = entry["piece"]
        return full

    return jax.tree_util.tree_map(visit, tree, is_leaf=_is_marker)


def _load_rank_pieces(ckpt_dir: str, mp_rank: int) -> Dict[str, list]:
    import glob as _glob

    pieces: Dict[str, list] = {}
    pattern = os.path.join(
        ckpt_dir, f"zero_pp_rank_*_mp_rank_{mp_rank:02d}_optim_states"
        f".msgpack")
    for path in sorted(_glob.glob(pattern)):
        with open(path, "rb") as f:
            payload = serialization.msgpack_restore(f.read())
        for key, entry in (payload.get("pieces") or {}).items():
            pieces.setdefault(key, []).append(entry)
    return pieces


_STREAM_PREFIX = "__dstpu_stream__:"


def stream_group_ckpt_name(ckpt_dir: str, group: str) -> str:
    """Per-stream-group checkpoint file (masters + that group's Adam
    moments), the RAM-bounded unit of the Infinity streaming writer.
    Reference capability: swap-aware optimizer save,
    swap_tensor/partitioned_param_swapper.py:223-277."""
    safe = group.replace(":", "_").replace("/", "_")
    return os.path.join(ckpt_dir, f"stream_group_{safe}.msgpack")


def stream_marker(group: str, slot: str) -> str:
    """Marker leaf standing in for streamed data: slot is 'leaf:<j>'
    (master leaf j of the group), 'optim:<key>' (Adam moments of flat
    leaf <key>) or 'acc:<key>' (mid-accumulation grad sink entry)."""
    return f"{_STREAM_PREFIX}{group}|{slot}"


def write_stream_group(ckpt_dir: str, group: str, payload) -> str:
    path = stream_group_ckpt_name(ckpt_dir, group)
    with open(path, "wb") as f:
        f.write(serialization.msgpack_serialize(_to_host(payload)))
    return path


def _read_stream_group(ckpt_dir: str, group: str):
    path = stream_group_ckpt_name(ckpt_dir, group)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"streamed checkpoint group file not found: {path}")
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def has_stream_markers(tree) -> bool:
    return any(isinstance(l, str) and l.startswith(_STREAM_PREFIX)
               for l in jax.tree_util.tree_leaves(tree))


def resolve_streamed(tree, ckpt_dir: str):
    """Materialize stream markers by reading group files (one cached at a
    time — marker visitation order has group locality, so each file is
    normally read once).  Consumers that must stay RAM-bounded skip this
    and walk the group files themselves (InfinityRuntime.load_streamed)."""
    cache: Dict[str, Any] = {}

    def lookup(marker: str):
        group, slot = marker[len(_STREAM_PREFIX):].split("|", 1)
        if group not in cache:
            cache.clear()
            cache[group] = _read_stream_group(ckpt_dir, group)
        payload = cache[group]
        kind, _, idx = slot.partition(":")
        if kind == "leaf":
            return np.asarray(payload["leaves"][idx])
        if kind == "optim":
            return {k: np.asarray(v)
                    for k, v in payload["optim"][idx].items()}
        if kind == "acc":
            return np.asarray(payload["acc"][idx])
        raise ValueError(f"unknown stream marker slot {slot!r}")

    def visit(node):
        if isinstance(node, str) and node.startswith(_STREAM_PREFIX):
            return lookup(node)
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(visit(v) for v in node)
        return node

    return visit(tree)


def model_ckpt_name(ckpt_dir: str, mp_rank: int = 0) -> str:
    return os.path.join(ckpt_dir, f"mp_rank_{mp_rank:02d}_model_states.msgpack")


def optim_ckpt_name(ckpt_dir: str, dp_rank: int = 0, mp_rank: int = 0) -> str:
    return os.path.join(
        ckpt_dir,
        f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states.msgpack")


def layer_ckpt_name(ckpt_dir: str, layer_idx: int, mp_rank: int = 0) -> str:
    """Per-layer pipeline checkpoint file (reference pipe/module.py:520-578
    `layer_{idx:02d}-model_{mp:02d}-model_states.pt`)."""
    return os.path.join(
        ckpt_dir, f"layer_{layer_idx:02d}-model_{mp_rank:02d}-model_states"
        f".msgpack")


def save_checkpoint_state(save_dir: str, tag: str, model_state: Dict[str, Any],
                          optim_state: Optional[Dict[str, Any]] = None,
                          save_latest: bool = True, mp_rank: int = 0,
                          dp_rank: int = 0, layer_states=None,
                          tied_states=None, async_save: bool = False) -> str:
    ckpt_dir = os.path.join(save_dir, str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)

    # sharded leaves are split into per-rank piece files; nothing is
    # gathered across hosts — each process serializes only what it owns
    rank_pieces: Dict[int, Dict[str, Any]] = {}
    model_state = _split_sharded(model_state, rank_pieces, "model:")
    optim_skeleton = None
    if optim_state is not None:
        optim_skeleton = _split_sharded(optim_state, rank_pieces, "optim:")

    def _write(path, payload):
        with open(path, "wb") as f:
            f.write(serialization.msgpack_serialize(payload))

    jobs = []
    if jax.process_index() == 0:
        if layer_states is not None:
            # pipeline layout: layer params go to per-layer files (reference
            # pipe/module.py:520-578); the module file keeps placeholders
            for idx, lp in sorted(layer_states.items()):
                jobs.append((layer_ckpt_name(ckpt_dir, idx, mp_rank),
                             _to_host(lp)))
            model_state = dict(model_state)
            model_state["module"] = {
                "layers": [None] * len(model_state["module"]["layers"]),
                "tied": _to_host(tied_states or {}),
                "num_layers": len(model_state["module"]["layers"]),
            }
        jobs.append((model_ckpt_name(ckpt_dir, mp_rank),
                     _to_host(model_state)))
        if optim_skeleton is not None and 0 not in rank_pieces:
            rank_pieces[0] = {}

    for rank, pieces in rank_pieces.items():
        payload: Dict[str, Any] = {"__dstpu_ckpt_v2__": True,
                                   "pieces": pieces}
        if rank == 0 and optim_skeleton is not None:
            payload["state"] = _to_host(optim_skeleton)
        jobs.append((optim_ckpt_name(ckpt_dir, rank, mp_rank), payload))

    if async_save:
        # snapshot host arrays NOW: offload/infinity masters mutate in
        # place, and the background write must not see later steps
        jobs = [(path, jax.tree_util.tree_map(
            lambda x: x.copy() if isinstance(x, np.ndarray) else x, payload))
            for path, payload in jobs]
    futures = [_writer.submit(_write, path, payload)
               for path, payload in jobs]
    if async_save:
        _pending.extend(futures)
    else:
        for f in futures:
            f.result()

    if save_latest and jax.process_index() == 0:
        def _latest():
            for fut in futures:  # latest must not point at a partial write
                fut.result()
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(str(tag))

        if async_save:
            _pending.append(_writer.submit(_latest))
        else:
            _latest()
    logger.info(f"saved checkpoint {tag} to {ckpt_dir}"
                + (" (async)" if async_save else ""))
    return ckpt_dir


def read_latest_tag(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            return f.read().strip()
    return None


def load_checkpoint_state(load_dir: str, tag: Optional[str] = None,
                          mp_rank: int = 0, dp_rank: int = 0,
                          resolve_streams: bool = True):
    """Returns (ckpt_dir, model_state, optim_state_or_None).

    resolve_streams=False leaves Infinity stream markers in place so a
    paged engine can walk the group files RAM-bounded instead of
    materializing the full fp32 set here."""
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no 'latest' file in {load_dir}; pass an explicit tag")
    ckpt_dir = os.path.join(load_dir, str(tag))
    path = model_ckpt_name(ckpt_dir, mp_rank)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"checkpoint file not found: {path}")
    with open(path, "rb") as f:
        model_state = serialization.msgpack_restore(f.read())

    # pipeline layout: reassemble per-layer files if present
    module = model_state.get("module")
    if isinstance(module, dict) and "num_layers" in module:
        layers = []
        for i in range(int(module["num_layers"])):
            lpath = layer_ckpt_name(ckpt_dir, i, mp_rank)
            if os.path.isfile(lpath):
                with open(lpath, "rb") as f:
                    layers.append(serialization.msgpack_restore(f.read()))
            else:
                layers.append(None)
        model_state["module"] = {"layers": layers,
                                 "tied": module.get("tied", {})}

    pieces = _load_rank_pieces(ckpt_dir, mp_rank)
    if pieces:
        model_state = _reassemble(model_state, pieces)

    optim_state = None
    opath = optim_ckpt_name(ckpt_dir, dp_rank, mp_rank)
    if os.path.isfile(opath):
        with open(opath, "rb") as f:
            optim_state = serialization.msgpack_restore(f.read())
        if isinstance(optim_state, dict) and \
                optim_state.get("__dstpu_ckpt_v2__"):
            # v2 sharded layout: the skeleton lives in rank 0's file
            optim_state = _reassemble(optim_state.get("state"), pieces)
    if resolve_streams:
        if has_stream_markers(model_state):
            model_state = resolve_streamed(model_state, ckpt_dir)
        if optim_state is not None and has_stream_markers(optim_state):
            optim_state = resolve_streamed(optim_state, ckpt_dir)
    return ckpt_dir, model_state, optim_state
